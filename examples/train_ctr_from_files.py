"""CTR training straight from slot-format files (the AsyncExecutor flow).

DeepFM over multi-slot text files: the native C++ DataFeed parses files
off the training thread, sparse ids convert to padded+mask form, and
device prefetch overlaps H2D with compute — the reference's
AsyncExecutor.run_from_file / MultiSlotDataFeed capability
(framework/async_executor.cc, data_feed.cc) in TPU form.

    python examples/train_ctr_from_files.py [--rows 20000] [--epochs 2]
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu import Trainer, train_from_files
from paddle_tpu.data.datafeed import write_slot_file
from paddle_tpu.models.nlp import DeepFM
from paddle_tpu.ops import functional as F
from paddle_tpu.optim.optimizer import Adam

CONFIG = "label:int64:dense:1;dense:float:dense:13;ids:int64:sparse"
FIELDS, VOCAB, DENSE = 26, 1000, 13


def synthesize(datadir: str, rows: int, n_files: int = 4) -> None:
    """Criteo-shaped slot files with a learnable signal in the ids."""
    os.makedirs(datadir, exist_ok=True)
    rs = np.random.RandomState(0)
    per = rows // n_files
    for fi in range(n_files):
        exs = []
        for _ in range(per):
            ids = rs.randint(0, VOCAB, FIELDS)
            dense = rs.randn(DENSE)
            label = int((ids[0] % 2) ^ (dense[0] > 0))
            exs.append(([label],
                        [float(np.float32(v)) for v in dense],
                        [int(v) for v in ids]))
        write_slot_file(os.path.join(datadir, f"part-{fi:03d}.txt"),
                        exs, CONFIG)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--datadir", default="/tmp/ptpu_ctr")
    ap.add_argument("--rows", type=int, default=20000)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--nthreads", type=int, default=4)
    args = ap.parse_args()

    files = sorted(glob.glob(os.path.join(args.datadir, "part-*.txt")))
    if not files:
        synthesize(args.datadir, args.rows)
        files = sorted(glob.glob(os.path.join(args.datadir, "part-*.txt")))
    print(f"{len(files)} slot files in {args.datadir}")

    model = DeepFM(num_fields=FIELDS, vocab_per_field=VOCAB,
                   dense_dim=DENSE)

    def loss_fn(module, variables, batch, rng, training):
        dense, sparse, y = batch
        logit, mut = module.apply(variables, dense, sparse,
                                  training=training, rngs=rng, mutable=True)
        loss = jnp.mean(F.sigmoid_cross_entropy_with_logits(logit, y))
        return (loss, {}), mut.get("state", {})

    def batch_fn(b):
        padded, _ = b["ids"]
        return (jnp.asarray(b["dense"]), jnp.asarray(padded),
                jnp.asarray(b["label"][:, 0], jnp.float32))

    trainer = Trainer(model, Adam(1e-3), loss_fn)
    ts = trainer.init_state(jnp.zeros((args.batch_size, DENSE)),
                            jnp.zeros((args.batch_size, FIELDS), jnp.int32))

    losses = []
    ts = train_from_files(
        trainer, ts, files, CONFIG, batch_fn,
        batch_size=args.batch_size, nthreads=args.nthreads,
        epochs=args.epochs, max_sparse_len=FIELDS,
        callback=lambda s, f: losses.append(float(f["loss"])))
    n = max(1, len(losses) // 10)
    print(f"{len(losses)} steps; loss {np.mean(losses[:n]):.4f} -> "
          f"{np.mean(losses[-n:]):.4f}")


if __name__ == "__main__":
    main()

"""Long-context causal-LM training on one chip.

Trains `CausalLM` (decoder-only, GPT-style) with the two pieces that
keep memory linear in sequence length — block-causal Pallas flash
attention (O(T) score memory; kernels/flash.py) and the chunked fused
cross-entropy (no [T, V] logits tensor; ops/fused_ce.py) — then
generates a continuation with the KV-cache decode path. On a v5e this
recipe trains full steps at 16k+ tokens (PERF_NOTES.md: 107k tok/s at
seq 16384); the defaults here are sized to finish in seconds anywhere:

    python examples/train_causal_lm.py                 # TPU or CPU
    python examples/train_causal_lm.py --seq 16384     # the long-context point (TPU)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.executor import Trainer
from paddle_tpu.models.transformer import CausalLM
from paddle_tpu.ops.fused_ce import linear_cross_entropy
from paddle_tpu.optim.optimizer import Adam


def sequence_batch(rs, batch, seq, vocab):
    """Learnable stream: next token = (token + 3) mod vocab."""
    start = rs.randint(0, vocab, (batch, 1))
    ramp = np.arange(seq + 1)[None, :] * 3
    return ((start + ramp) % vocab).astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--ckpt-dir", default=None,
                    help="enable the resilient loop: checkpoint here, "
                         "resume from the newest intact checkpoint, "
                         "preemption-safe (SIGTERM => emergency save + "
                         "reschedulable exit)")
    ap.add_argument("--save-every", type=int, default=20,
                    help="checkpoint cadence in steps (with --ckpt-dir)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve training telemetry (step phases, goodput, "
                         "MFU, device memory) at :PORT/metrics while the "
                         "resilient loop runs (needs --ckpt-dir)")
    ap.add_argument("--flightrec-dir", default=None,
                    help="dump a postmortem bundle here when the watchdog "
                         "flags a hung step or the loop crashes "
                         "(needs --ckpt-dir)")
    args = ap.parse_args()
    if (args.metrics_port or args.flightrec_dir) and not args.ckpt_dir:
        ap.error("--metrics-port/--flightrec-dir ride on the resilient "
                 "loop: pass --ckpt-dir too")

    on_tpu = jax.devices()[0].platform == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    model = CausalLM(args.vocab, model_dim=args.dim, num_heads=4,
                     num_layers=args.layers, ffn_dim=4 * args.dim,
                     dropout=0.0, max_len=args.seq + 8, dtype=dtype)

    def loss_fn(module, variables, batch, rng, training):
        inp, tgt = batch
        hid, mut = module.apply(variables, inp, training=training,
                                rngs=rng, mutable=True, return_hidden=True)
        w, b = module.head_weights(variables)
        loss = jnp.mean(linear_cross_entropy(
            hid, w.astype(hid.dtype), tgt,
            None if b is None else b.astype(hid.dtype)))
        return (loss, {}), mut.get("state", {})

    trainer = Trainer(model, Adam(3e-3), loss_fn)
    rs = np.random.RandomState(0)
    tok = sequence_batch(rs, args.batch, args.seq, args.vocab)
    ts = trainer.init_state(jnp.asarray(tok[:, :-1]))
    batch = (jnp.asarray(tok[:, :-1]), jnp.asarray(tok[:, 1:]))
    print(f"device={jax.devices()[0].device_kind} seq={args.seq} "
          f"params={sum(x.size for x in jax.tree.leaves(ts.params)):,}")
    if args.ckpt_dir:
        # Resilient loop (resilience/supervisor.py): deterministic
        # batch_for + resume-from-latest means a preempted run relaunched
        # with the same command continues the same loss curve.
        from paddle_tpu.io.checkpoint import CheckpointManager
        from paddle_tpu.resilience.supervisor import train_resilient

        manager = CheckpointManager(args.ckpt_dir, max_to_keep=3)
        restored, rstep = manager.restore_latest(ts)
        start = 0
        if restored is not None:
            ts, start = restored, rstep
            print(f"resumed from {args.ckpt_dir} at step {start}")

        def on_step(step, out):
            if step % 10 == 0 or step == args.steps - 1:
                print(f"step {step:4d}  loss {float(out['loss']):.4f}")

        # Training telemetry (OBSERVABILITY.md "Training telemetry"):
        # one registry feeds the scrape server, the goodput ledger, the
        # MFU gauge (absent where the platform peak is unknown), the
        # per-device memory gauges and the flight recorder's snapshot.
        import contextlib

        from paddle_tpu.obs import (
            DeviceMemoryMonitor, FlightRecorder, GoodputLedger,
            MetricsServer, default_registry)
        from paddle_tpu.obs.goodput import causal_lm_step_flops, param_count

        telemetry = {}
        srv = contextlib.nullcontext()
        if args.metrics_port or args.flightrec_dir:
            reg = default_registry()
            flops = causal_lm_step_flops(
                batch_size=args.batch, seq_len=args.seq, d_model=args.dim,
                n_layers=args.layers, n_params=param_count(ts.params))
            telemetry = dict(registry=reg,
                             goodput=GoodputLedger(registry=reg),
                             flops_per_step=flops,
                             memory_monitor=DeviceMemoryMonitor(registry=reg))
            if args.flightrec_dir:
                telemetry["flight_recorder"] = FlightRecorder(
                    streams=("resilience", "obs"),
                    snapshot_fn=lambda: {"metrics": reg.snapshot()},
                    out_dir=args.flightrec_dir, registry=reg)
            if args.metrics_port:
                srv = MetricsServer(reg, port=args.metrics_port)

        with srv:
            ts = train_resilient(trainer, ts, lambda step: batch, args.steps,
                                 manager, start_step=start,
                                 save_every=args.save_every,
                                 rng_for_step=jax.random.key,
                                 on_step=on_step, **telemetry)
        if telemetry:
            gl = telemetry["goodput"]
            lost = ", ".join(f"{c}={s:.3f}s" for c, s in
                             sorted(gl.lost_seconds().items())) or "none"
            print(f"goodput {gl.goodput():.4f}  "
                  f"productive {gl.productive_seconds():.3f}s  lost: {lost}")
    else:
        for step in range(args.steps):
            ts, out = trainer.train_step(ts, batch, rng=jax.random.key(step))
            if step % 10 == 0 or step == args.steps - 1:
                print(f"step {step:4d}  loss {float(out['loss']):.4f}")

    # KV-cache generation: the (t+3)%V stream is learnable, so the
    # continuation should keep stepping by 3
    p0 = min(8, args.seq)              # stay inside max_len for tiny --seq
    prompt = jnp.asarray(tok[:2, :p0])
    cont = model.generate(ts.variables, prompt, num_steps=8)
    print("prompt     :", np.asarray(prompt[0]))
    print("continued  :", np.asarray(cont[0, p0:]))
    want = (np.asarray(prompt[0, -1]) + 3 * np.arange(1, 9)) % args.vocab
    print("ideal      :", want)


if __name__ == "__main__":
    main()

"""BERT-style MLM pretraining on a device mesh (dp x fsdp).

The BASELINE "BERT-base pretraining, pod-scale allreduce" flow as a
runnable script: synthetic corpus, MeshTrainer with ZeRO (REDUCE)
sharding, gradient accumulation, async checkpointing. Runs unchanged on
one chip, a TPU slice, or the 8-device virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/pretrain_bert.py --dp 4 --fsdp 2 --tiny

Multi-host: wrap with `python -m paddle_tpu.parallel.launch --nproc N`
(or generate cluster manifests with `python -m paddle_tpu.parallel.kube`).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.io import CheckpointManager
from paddle_tpu.models.transformer import BertEncoder
from paddle_tpu.ops import functional as F
from paddle_tpu.optim.optimizer import Adam
from paddle_tpu.parallel import (DistStrategy, MeshConfig, MeshTrainer,
                                 ReduceStrategy, make_mesh)
from paddle_tpu.parallel.distributed import init_distributed
from paddle_tpu.parallel.sharding import fsdp_rules


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=0, help="0 = all devices")
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--grad-accum", type=int, default=1)
    ap.add_argument("--per-chip-batch", type=int, default=8)
    ap.add_argument("--tiny", action="store_true",
                    help="small config for CPU-mesh runs")
    ap.add_argument("--ckpt", default="/tmp/ptpu_bert/ckpt")
    args = ap.parse_args()

    init_distributed()   # no-op single-process; PTPU_* env multi-host
    ndev = jax.device_count()
    dp = args.dp or max(1, ndev // args.fsdp)
    mesh = make_mesh(MeshConfig(dp=dp, fsdp=args.fsdp))

    if args.tiny:
        vocab, dim, layers, heads, ffn = 1024, 64, 2, 4, 128
    else:
        vocab, dim, layers, heads, ffn = 30522, 768, 12, 12, 3072
    seq, k = args.seq_len, max(1, args.seq_len * 15 // 100)
    dtype = (jnp.bfloat16 if jax.devices()[0].platform == "tpu"
             else jnp.float32)
    model = BertEncoder(vocab=vocab, model_dim=dim, num_heads=heads,
                        num_layers=layers, ffn_dim=ffn, max_len=seq,
                        dropout=0.0, dtype=dtype)

    def loss_fn(module, variables, batch, rng, training):
        tokens, positions, labels = batch
        logits, mut = module.apply(variables, tokens, positions,
                                   training=training, rngs=rng,
                                   mutable=True)
        loss = jnp.mean(F.softmax_with_cross_entropy(
            logits.astype(jnp.float32), labels))
        return (loss, {}), mut.get("state", {})

    trainer = MeshTrainer(
        model, Adam(1e-4), loss_fn, mesh,
        strategy=DistStrategy(reduce_strategy=ReduceStrategy.REDUCE,
                              gradient_accumulation_steps=args.grad_accum),
        rules=fsdp_rules(min_size=1024))

    gbs = args.per_chip_batch * dp * args.grad_accum
    rs = np.random.RandomState(0)
    tokens0 = rs.randint(0, vocab, (gbs, seq)).astype(np.int32)
    pos0 = np.sort(rs.rand(gbs, seq).argsort(1)[:, :k], 1).astype(np.int32)
    ts = trainer.init_state(jnp.asarray(tokens0), jnp.asarray(pos0))
    mgr = CheckpointManager(args.ckpt, max_to_keep=2, async_save=True)
    restored, start = mgr.restore_latest(ts)
    if restored is not None:
        ts, step0 = restored, start
        print(f"resumed from step {start}")
    else:
        step0 = 0

    for step in range(step0, args.steps):
        rs = np.random.RandomState(step)
        batch = trainer.put_batch((
            rs.randint(0, vocab, (gbs, seq)).astype(np.int32),
            np.sort(rs.rand(gbs, seq).argsort(1)[:, :k], 1).astype(np.int32),
            rs.randint(0, vocab, (gbs, k)).astype(np.int32)))
        ts, fetches = trainer.train_step(ts, batch,
                                         rng=jax.random.key(step))
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step} loss {float(fetches['loss']):.4f}")
        if (step + 1) % 25 == 0:
            mgr.save(ts, step=step + 1)
    mgr.save(ts, step=args.steps)
    mgr.wait()
    print(f"done: mesh {dict(mesh.shape)}, global batch {gbs}, "
          f"checkpoints at {args.ckpt}")


if __name__ == "__main__":
    main()

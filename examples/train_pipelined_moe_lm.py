"""Pipeline + expert parallelism in one training run.

A decoder-only LM whose transformer blocks are pipeline stages (pp axis,
GPipe microbatch streaming — O(batch/S) resident input per device) trained
through MeshTrainer on a pp×dp mesh, next to a standalone top-2 MoE FFN
dispatched with all_to_all over the ep axis — the two parallelism modes the
reference lacks (SURVEY §2.6), in their TPU-native form. Runs unchanged on
one chip, a TPU slice, or the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_pipelined_moe_lm.py --pp 4 --dp 2

Multi-host: wrap with `python -m paddle_tpu.parallel.launch --nproc N`.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.optim.optimizer import Adam
from paddle_tpu.parallel import (DistStrategy, MeshConfig, MeshTrainer,
                                 PipelinedLM, make_mesh, pipeline_rules,
                                 pipelined_lm_loss)
from paddle_tpu.parallel.moe import (init_moe_params, load_balancing_loss,
                                     moe_ffn_a2a)


def sequence_batch(rs, batch, seq, vocab):
    """Learnable stream: next token = (token + 1) mod vocab."""
    start = rs.randint(0, vocab, (batch, 1))
    toks = (start + np.arange(seq + 1)) % vocab
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=0,
                    help="pipeline stages (0 = largest divisor of the "
                         "device count <= 4)")
    ap.add_argument("--dp", type=int, default=0,
                    help="data-parallel width (0 = remaining devices)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor parallelism INSIDE each pipeline stage "
                         "(Megatron column/row splits; pp×tp×dp 3D)")
    ap.add_argument("--sp", type=int, default=1,
                    help="sequence parallelism INSIDE each stage (ring "
                         "attention over sequence shards; composes with "
                         "--tp for pp×tp×sp×dp)")
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    args = ap.parse_args()

    # ---- pipelined LM on pp(×tp×sp)×dp ---------------------------------
    n = jax.device_count()
    if n % (args.tp * args.sp):
        raise SystemExit(
            f"--tp {args.tp} × --sp {args.sp} must divide device count {n}")
    if not args.pp:   # adapt to whatever devices exist (1 chip included)
        args.pp = max(c for c in (1, 2, 4)
                      if n % (c * args.tp * args.sp) == 0)
    args.dp = args.dp or n // (args.pp * args.tp * args.sp)
    mesh = make_mesh(MeshConfig(pp=args.pp, tp=args.tp, sp=args.sp,
                                dp=args.dp))
    tp_axis = "tp" if args.tp > 1 else None
    sp_axis = "sp" if args.sp > 1 else None
    lm = PipelinedLM(args.vocab, d_model=64, n_heads=4, d_ff=128,
                     num_stages=args.pp, max_len=args.seq)
    trainer = MeshTrainer(
        lm, Adam(3e-3),
        pipelined_lm_loss(mesh, num_microbatches=2 * args.pp,
                          tp_axis=tp_axis, sp_axis=sp_axis),
        mesh, strategy=DistStrategy(batch_axes=("dp",)),
        rules=pipeline_rules(tp_axis=tp_axis))

    rs = np.random.RandomState(0)
    src, trg = sequence_batch(rs, args.batch, args.seq, args.vocab)
    state = trainer.init_state(jnp.asarray(src))
    batch = trainer.put_batch((src, trg))
    for step in range(args.steps):
        state, fetches = trainer.train_step(state, batch)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"[lm pp={args.pp}×tp={args.tp}×sp={args.sp}"
                  f"×dp={args.dp}] "
                  f"step {step:3d} "
                  f"loss {float(fetches['loss']):.4f}")

    logits = lm.apply({"params": jax.device_get(state.params)},
                      jnp.asarray(src))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(trg)).mean())
    print(f"[lm] greedy next-token accuracy (dense forward): {acc:.3f}")

    # ---- top-2 MoE FFN with all_to_all dispatch on ep ------------------
    ep = n   # all devices become expert shards
    mesh_ep = make_mesh(MeshConfig(ep=ep))
    params = init_moe_params(jax.random.key(0), num_experts=2 * ep,
                             d_model=32, d_hidden=64)
    x = jnp.asarray(rs.randn(16 * ep, 32), jnp.float32)
    y, aux = jax.jit(lambda p, x: moe_ffn_a2a(
        p, x, mesh=mesh_ep, k=2, capacity_factor=1.5))(params, x)
    print(f"[moe ep={ep}] tokens {x.shape[0]} -> y {tuple(y.shape)}, "
          f"dropped {float(aux['dropped_fraction']):.3f}, "
          f"balance loss {float(load_balancing_loss(aux)):.3f}")


if __name__ == "__main__":
    main()

"""Train LeNet on MNIST end to end, checkpoint, export, and serve.

The "recognize digits" book chapter (reference tests/book/
test_recognize_digits.py) as a runnable script: real dataset (synthetic
fallback when the files are absent), train loop, CheckpointManager,
inference export, and a prediction through InferencePredictor.

    python examples/train_mnist.py [--epochs 1] [--bf16]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon sitecustomize pins the TPU plugin; honor an explicit CPU ask
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu import Trainer, supervised_loss
from paddle_tpu.data import datasets, readers
from paddle_tpu.io import (CheckpointManager, InferencePredictor,
                           save_inference_model)
from paddle_tpu.metrics import accuracy
from paddle_tpu.models import LeNet
from paddle_tpu.ops import functional as F
from paddle_tpu.optim.optimizer import Adam


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--max-steps", type=int, default=None,
                    help="cap steps per epoch (smoke runs)")
    ap.add_argument("--outdir", default="/tmp/ptpu_mnist")
    args = ap.parse_args()

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = LeNet(num_classes=10, dtype=dtype)
    loss_fn = supervised_loss(
        lambda lg, y: F.softmax_with_cross_entropy(
            lg.astype(jnp.float32), y),
        metrics={"acc": accuracy})
    trainer = Trainer(model, Adam(1e-3), loss_fn)
    ts = trainer.init_state(jnp.zeros((args.batch_size, 28, 28, 1)))
    mgr = CheckpointManager(f"{args.outdir}/ckpt", max_to_keep=2,
                            async_save=True)

    train = readers.batch(
        readers.shuffle(datasets.mnist_train(), buf_size=5000),
        args.batch_size, drop_last=True)
    step = 0
    for epoch in range(args.epochs):
        for bi, (xs, ys) in enumerate(train()):
            if args.max_steps and bi >= args.max_steps:
                break
            ts, fetches = trainer.train_step(
                ts, (jnp.asarray(xs), jnp.asarray(ys)))
            step += 1
            if step % 100 == 0:
                print(f"epoch {epoch} step {step} "
                      f"loss {float(fetches['loss']):.4f} "
                      f"acc {float(fetches['acc']):.3f}")
        mgr.save(ts, step=step)
    mgr.wait()

    # evaluate
    test = readers.batch(datasets.mnist_test(), args.batch_size,
                         drop_last=True)
    accs = []
    for bi, (xs, ys) in enumerate(test()):
        if args.max_steps and bi >= args.max_steps:
            break
        accs.append(float(trainer.eval_step(
            ts, (jnp.asarray(xs), jnp.asarray(ys)))["acc"]))
    print(f"test acc: {np.mean(accs):.4f}")

    # export + serve one prediction
    export = f"{args.outdir}/export"
    save_inference_model(
        export, model, {"params": ts.params, "state": ts.state},
        example_inputs=(jnp.zeros((1, 28, 28, 1)),))
    pred = InferencePredictor(export)
    xs, ys = next(iter(test()))
    digit = int(np.argmax(pred.run([xs[:1]])[0]))
    print(f"predicted {digit}, label {int(ys[0])}; export at {export}")


if __name__ == "__main__":
    main()

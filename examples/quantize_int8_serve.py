"""Train (briefly) → freeze to TRUE int8 → compare → export for serving.

The int8 counterpart of the MNIST book chapter: a small CNN is trained
for a few steps, frozen to the real int8 execution path
(quant/int8_compute.py — int8 x int8 -> int32 on the MXU, per-channel
weight scales, calibrated static activation scales), its accuracy
checked against the float model, and exported with
save_inference_model so the C-ABI server (serving/serving.cc) or
InferencePredictor can serve the quantized artifact.

    python examples/quantize_int8_serve.py            # CPU or TPU
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.executor import Trainer, supervised_loss
from paddle_tpu.data import datasets
from paddle_tpu.metrics import accuracy
from paddle_tpu.models import LeNet
from paddle_tpu.ops import functional as F
from paddle_tpu.optim.optimizer import Adam
from paddle_tpu.quant.int8_compute import freeze_int8
from paddle_tpu.testing import export_servable


def batches(reader, bs):
    rows = list(reader())
    for i in range(0, len(rows) - bs + 1, bs):
        chunk = rows[i:i + bs]
        x = np.stack([r[0] for r in chunk]).astype(np.float32)
        y = np.asarray([r[1] for r in chunk], np.int64)
        yield x.reshape(len(chunk), 28, 28, 1), y


def main():
    model = LeNet(num_classes=10)
    loss = supervised_loss(
        lambda lg, y: F.softmax_with_cross_entropy(
            lg.astype(jnp.float32), y),
        metrics={"acc": accuracy})
    trainer = Trainer(model, Adam(1e-3), loss)
    ts = trainer.init_state(jnp.zeros((32, 28, 28, 1)))
    train = list(batches(datasets.mnist_train(synthetic_n=512), 32))
    for epoch in range(2):
        for b in train:
            ts, f = trainer.train_step(ts, b)
    print(f"trained: loss {float(f['loss']):.3f} "
          f"acc {float(f['acc']):.3f}")

    # float accuracy on held-out batches
    held = list(batches(datasets.mnist_test(synthetic_n=128), 32))
    variables = ts.variables

    def acc_of(m, v):
        hits = tot = 0
        for x, y in held:
            p = np.asarray(m.apply(v, jnp.asarray(x), training=False))
            hits += (p.argmax(-1) == y).sum()
            tot += len(y)
        return hits / tot

    a_f32 = acc_of(model, variables)

    # freeze to int8 compute, calibrating static activation scales on a
    # couple of training batches
    qmodel, qvars = freeze_int8(model, variables,
                                calib_batches=[(jnp.asarray(train[0][0]),),
                                               (jnp.asarray(train[1][0]),)])
    a_int8 = acc_of(qmodel, qvars)
    print(f"accuracy: float {a_f32:.3f}  int8 {a_int8:.3f} "
          f"(delta {a_f32 - a_int8:+.3f})")

    # export the QUANTIZED model for serving; export_servable(verify=True)
    # round-trips the batch through InferencePredictor and asserts the
    # served logits match direct apply
    d = tempfile.mkdtemp(prefix="int8_serve_")
    path = export_servable(os.path.join(d, "model"), qmodel, qvars,
                           [jnp.asarray(held[0][0])], input_names=["x"],
                           verify=True)
    print(f"exported + served from {path}: predictions match direct apply")


if __name__ == "__main__":
    main()

"""Benchmark entrypoint (driver contract: prints ONE JSON line).

Primary metric: ResNet-50 training throughput (imgs/s, bs=64) — the
reference's headline trainable-model metric (BASELINE.md: 81.69 imgs/s on
2x Xeon E5-2650v4, the only published trainable ResNet-50 number in the
reference tree). The `extra` field carries the rest of BASELINE.md's
north-star metrics: Transformer-base tokens/s and MFU for both, measured
by paddle_tpu.benchmark (XLA cost analysis / chip peak).

Runs on whatever jax.devices() provides (real TPU under the driver; CPU
locally — where windows shrink so CI stays fast).
"""

import json

import jax
import jax.numpy as jnp


def main():
    from paddle_tpu.benchmark import run_model

    on_tpu = jax.devices()[0].platform == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    min_time = 2.5 if on_tpu else 0.2
    bs = 64 if on_tpu else 8

    resnet = run_model("resnet50", batch_size=bs, dtype=dtype,
                       min_time=min_time)
    extra = {}
    try:
        xf = run_model("transformer", batch_size=32 if on_tpu else 2,
                       dtype=dtype, min_time=min_time)
        extra = {
            "transformer_tokens_per_sec": round(xf.value, 1),
            "transformer_mfu": round(xf.mfu, 4) if xf.mfu else None,
            "transformer_ms_per_step": round(xf.ms_per_step, 2),
        }
    except Exception as e:  # primary metric must still print
        extra = {"transformer_error": f"{type(e).__name__}: {e}"[:200]}

    out = {
        "metric": f"resnet50_train_imgs_per_sec_bs{bs}",
        "value": round(resnet.value, 2),
        "unit": "imgs/s",
        "vs_baseline": round(resnet.vs_baseline, 3),
        "extra": {
            "device": resnet.device,
            "resnet50_mfu": round(resnet.mfu, 4) if resnet.mfu else None,
            "resnet50_tflops_per_sec": (round(resnet.tflops_per_sec, 1)
                                        if resnet.tflops_per_sec else None),
            "resnet50_ms_per_step": round(resnet.ms_per_step, 2),
            "timed_steps": resnet.steps,
            **extra,
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Benchmark entrypoint (driver contract: a parseable primary-metric
JSON line, whatever happens).

The primary line prints TWICE: once the moment the primary metric is
measured (flushed, with `extra.partial: true`, before any optional
entry can run long) and once complete at the end — so a driver timeout
mid-extras still leaves a parseable line, and a finished run's last
line carries everything.

Primary metric: ResNet-50 training throughput (imgs/s, bs=64) — the
reference's headline trainable-model metric (BASELINE.md: 81.69 imgs/s on
2x Xeon E5-2650v4, the only published trainable ResNet-50 number in the
reference tree). `extra` carries the rest of the north-star metrics:

- resnet50 best-batch-size throughput/MFU (bs=128 saturates v5e),
- Transformer-base tokens/s + MFU,
- flash_check: on-TPU numerical validation of the Pallas flash-attention
  kernel against the XLA reference path (fwd+bwd) with the dispatch gate
  asserted — the only hardware the kernels run on doubles as their
  correctness gate,
- dp8_scaling_eff: weak-scaling efficiency at dp=8 measured on the
  8-device virtual CPU mesh in a subprocess (plumbing correctness; the
  platform label makes clear it is not a hardware scaling claim),
- serving axis (serve_*): in-process ServeEngine decode tokens/s,
  TTFT/TPOT p99 read from the metrics registry, and speculative-decode
  steps per token — measured on every platform and re-flushed as a
  partial primary line the moment it lands, so a driver kill later in
  the run cannot cost the serving series.

Runs on whatever jax.devices() provides (real TPU under the driver; CPU
locally — where windows shrink so CI stays fast).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

# Soft wall-clock budget: optional entries are skipped (with a marker)
# once exceeded, and even required entries stop starting once the
# budget is SPENT, so the run always finishes inside any sane driver
# timeout. The default sits well under the shortest observed driver
# kill (r5 artifact: rc=124 with the JSON line unprinted because the
# required set + a budget extension overran it). Override with
# PTPU_BENCH_BUDGET_S. The anchor rides PTPU_BENCH_T0 across the
# backend-init re-exec (time.time, not monotonic: the epoch must
# survive the process boundary) so retries spend from the SAME budget
# rather than resetting it.
_T0 = float(os.environ.setdefault("PTPU_BENCH_T0", str(time.time())))
_BUDGET_S = float(os.environ.get("PTPU_BENCH_BUDGET_S", "900"))


def _elapsed() -> float:
    return time.time() - _T0


def _budget_ok(est_s: float = 120.0) -> bool:
    return _elapsed() + est_s < _BUDGET_S


def _scaling_subprocess_start():
    """Launch the dp=1..8 weak-scaling sweep on a virtual CPU mesh as a
    BACKGROUND subprocess (own process: platform choice is frozen at
    first jax import; background: it shares no device with the TPU
    entries, so running it concurrently costs the bench ~zero budget —
    the r4 artifact budget-dropped it, r4 VERDICT missing #1)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    here = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import json\n"
        "from paddle_tpu.benchmark.scaling import run_scaling, "
        "scaling_summary\n"
        "out = {}\n"
        "rows = run_scaling('mlp', sizes=(1, 2, 4, 8), per_chip_batch=64,"
        " min_time=0.3)\n"
        "out.update(scaling_summary(rows))\n"
        "rows = run_scaling('bert_tiny', sizes=(1, 2, 4, 8),"
        " per_chip_batch=8, min_time=0.3)\n"
        "out.update(scaling_summary(rows, prefix='bert_'))\n"
        "print('SCALING ' + json.dumps(out))\n")
    # stdout/stderr go to a FILE, not a pipe: JAX/absl warnings exceed
    # the pipe buffer long before the sweep finishes, and an undrained
    # pipe would block the child until the final join — serializing the
    # "background" work exactly where it must overlap the TPU entries
    out_f = tempfile.TemporaryFile(mode="w+", encoding="utf-8")
    proc = subprocess.Popen([sys.executable, "-c", code], cwd=here,
                            env=env, stdout=out_f,
                            stderr=subprocess.STDOUT, text=True)
    proc._ptpu_out = out_f          # keep the fd alive with the handle
    return proc


def _scaling_subprocess_join(proc, timeout: float = 900):
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()                 # reap — no zombie for the bench's life
        return {"scaling_error": f"scaling subprocess >{timeout:.0f}s"}
    out_f = proc._ptpu_out
    out_f.seek(0)
    text = out_f.read()
    out_f.close()
    for line in text.splitlines():
        if line.startswith("SCALING "):
            return json.loads(line[len("SCALING "):])
    return {"scaling_error": text[-200:]}


def _longcontext_bench(seq: int = 16384):
    """fwd+bwd attention time at 16k tokens: Pallas flash vs XLA dense —
    the long-context headline (SURVEY §5.7)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import chain_k, run_timed
    from paddle_tpu.kernels import attention as A
    from paddle_tpu.utils.flags import FLAGS

    rs = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rs.randn(1, seq, 8, 64), jnp.bfloat16) * 0.3
    q, k, v = mk(), mk(), mk()
    out = {}
    prev = FLAGS.get("flash_attention")
    try:
        for label, flag in (("flash", True), ("dense", False)):
            FLAGS.set("flash_attention", flag)

            def loss(q, k, v):
                return jnp.sum(A.mha(q, k, v, causal=True)
                               .astype(jnp.float32))

            g = jax.grad(loss, argnums=(0, 1, 2))

            # harness.chain_k: K backwards per dispatch, carry touching
            # ALL THREE grads (else XLA dead-code-eliminates the dense
            # path's dk/dv matmuls while the fused flash kernel cannot
            # be pruned, biasing the comparison).
            K = 4
            kg = chain_k(lambda c, q, k, v: g(q + c, k, v), K)

            sec_k, _, _ = run_timed(
                lambda s: (kg(s, q, k, v),) * 2,
                jnp.zeros((), q.dtype), min_time=1.0)
            out[f"attn16k_{label}_ms"] = round(sec_k / K * 1e3, 2)
    finally:
        FLAGS.set("flash_attention", prev)
    out["attn16k_flash_speedup"] = round(
        out["attn16k_dense_ms"] / out["attn16k_flash_ms"], 2)
    return out


def _ptq_bench(min_time: float = 1.0):
    """int8 PTQ inference story on this chip (BASELINE int8 infer rows,
    reference benchmark/IntelOptimizedPaddle.md:73-107 + contrib/
    int8_inference). Three numbers:

    - resnet50 bf16 vs PTQ-int8 *simulated* inference (the framework's
      PTQ path stores int8 weights and dequantizes at compute — the
      reference contrib flow's semantics; on TPU this measures the
      simulation overhead, typically a slowdown),
    - a raw int8 matmul (preferred_element_type=int32) vs bf16 matmul
      microbench, documenting what the MXU int8 path yields from JAX —
      i.e. whether a true-int8 serving path would pay off.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import chain_k, run_timed
    from paddle_tpu.models import vision as V
    from paddle_tpu.quant.ptq import calibrate

    on_tpu = jax.devices()[0].platform == "tpu"
    bs, img = (16, 224) if on_tpu else (2, 64)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(bs, img, img, 3), jnp.float32)
    out = {}

    def time_fwd(apply_fn, label):
        K = 8 if on_tpu else 2
        kf = chain_k(lambda c, xx: apply_fn(xx + c), K)
        sec_k, _, _ = run_timed(lambda s: (kf(s, x),) * 2,
                                jnp.zeros((), x.dtype), min_time=min_time)
        out[f"{label}_ms"] = round(sec_k / K * 1e3, 2)

    model = V.resnet50(1000, dtype=jnp.bfloat16)
    variables = model.init(jax.random.key(0), x)
    time_fwd(lambda xx: model.apply(variables, xx, training=False),
             f"resnet50_infer_bf16_bs{bs}")

    qmodule, qvars = calibrate(model, variables, [(x,)])
    time_fwd(lambda xx: qmodule.apply(qvars, xx, training=False),
             f"resnet50_infer_ptq_int8_bs{bs}")
    out["ptq_vs_bf16"] = round(out[f"resnet50_infer_bf16_bs{bs}_ms"]
                               / out[f"resnet50_infer_ptq_int8_bs{bs}_ms"],
                               2)

    # raw MXU story: is a TRUE int8 path worth building on this chip?
    n = 4096 if on_tpu else 256
    a8 = jnp.asarray(rs.randint(-127, 127, (n, n)), jnp.int8)
    ab = jnp.asarray(rs.randn(n, n), jnp.bfloat16)
    for label, mat, dt in (("int8", a8, jnp.int32), ("bf16", ab, None)):
        def mm(c, m, dt=dt):
            # carry perturbs the input (runtime zero): the matmul stays
            # loop-carried inside chain_k's fori_loop, so XLA cannot
            # hoist it; chain_k's carry threading defeats DCE
            mp = m + (c * 1e-30).astype(m.dtype)
            return jax.lax.dot_general(
                mp, m, (((1,), (0,)), ((), ())),
                preferred_element_type=dt).ravel()[:1]
        kf = chain_k(mm, 8)
        sec, _, _ = run_timed(lambda s: (kf(s, mat),) * 2,
                              jnp.zeros((), jnp.float32),
                              min_time=min_time)
        out[f"matmul{n}_{label}_ms"] = round(sec / 8 * 1e3, 3)
    out["matmul_int8_vs_bf16"] = round(
        out[f"matmul{n}_bf16_ms"] / out[f"matmul{n}_int8_ms"], 2)
    return out


def _moe_bench(min_time: float = 1.0):
    """Masked vs all_to_all MoE dispatch cost at E=8 (top-2, cf=1.25).

    Even single-chip the difference is structural: masked dispatch runs
    every token through every expert (E× dense-FFN FLOPs), a2a runs each
    expert on only its capacity buffer (k·cf× dense) — so the step-cost
    ratio approaches E/(k·cf) ≈ 3.2 when FFN compute dominates."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import chain_k, run_timed
    from paddle_tpu.parallel import MeshConfig, make_mesh
    from paddle_tpu.parallel.moe import init_moe_params, moe_ffn, moe_ffn_a2a

    on_tpu = jax.devices()[0].platform == "tpu"
    E, D, HID, T = (8, 1024, 4096, 8192) if on_tpu else (8, 64, 128, 512)
    mesh = make_mesh(MeshConfig(ep=1), devices=jax.devices()[:1])
    mp = init_moe_params(jax.random.key(0), E, D, HID, dtype=jnp.bfloat16)
    x = jnp.asarray(np.random.RandomState(0).randn(T, D),
                    jnp.bfloat16) * 0.3
    out = {}
    # cf 1.0 and 2.0 bracket the capacity contract: smaller buffers are
    # faster but drop more under skew (training behavior under pressure
    # is tested in tests/test_moe.py::test_moe_a2a_under_capacity_pressure)
    for label, fn in (
            ("masked", lambda p, xx: moe_ffn(p, xx, k=2)[0]),
            ("a2a", lambda p, xx: moe_ffn_a2a(p, xx, mesh=mesh, k=2,
                                              capacity_factor=1.25)[0]),
            ("a2a_cf1", lambda p, xx: moe_ffn_a2a(p, xx, mesh=mesh, k=2,
                                                  capacity_factor=1.0)[0]),
            ("a2a_cf2", lambda p, xx: moe_ffn_a2a(p, xx, mesh=mesh, k=2,
                                                  capacity_factor=2.0)[0])):
        g = jax.grad(lambda p, xx: jnp.mean(
            fn(p, xx).astype(jnp.float32) ** 2))
        K = 4
        kg = chain_k(lambda c, p, xx: g(p, xx + c)["gate"], K)
        sec_k, _, _ = run_timed(lambda s: (kg(s, mp, x),) * 2,
                                jnp.zeros((), x.dtype), min_time=min_time)
        out[f"moe_e8_{label}_ms"] = round(sec_k / K * 1e3, 2)
    out["moe_a2a_speedup"] = round(
        out["moe_e8_masked_ms"] / out["moe_e8_a2a_ms"], 2)
    return out


def _decode_bench(min_time: float = 0.8):
    """Autoregressive decode: CausalLM.generate (parallel prefill +
    bf16-KV-cached steps) at the lm_longctx model size, swept over
    batch {1, 8, 32} at prompt 32 and prompt {2048, 8192} at bs 8 —
    with a bytes/token HBM roofline per point (decode reads the full
    parameter set + the KV cache every step; r4 VERDICT #4 demanded the
    sweep, the roofline, and >=2x bs8->bs32 throughput).

    Prefill is timed separately (its own jit of model.prefill) and
    subtracted, so decode_ms_per_token is steady-state decode only
    (r4 ADVICE: dividing the whole generate wall time by the step count
    overstated per-token latency).

    Roofline caveat (measured): hbm_bound_frac can exceed 1 at small
    batch/prompt because the model's 70 MB of bf16 weights fit v5e VMEM
    and XLA keeps them RESIDENT across the decode fori_loop — the
    "params re-read every step" premise only binds once the KV cache +
    activations push weights out (the long-prompt points, frac ~0.3-0.4,
    are the genuinely HBM-bound regime). The frac is reported per point
    so the regime is visible, not asserted away."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import run_timed
    from paddle_tpu.benchmark.models import LM_BASE, LM_VOCAB
    from paddle_tpu.core.module import Context, PARAMS, _CtxCore
    from paddle_tpu.models.transformer import CausalLM

    on_tpu = jax.devices()[0].platform == "tpu"
    HBM_GBPS = 819.0            # v5e datasheet HBM bandwidth
    points = ([(1, 32), (8, 32), (32, 32), (8, 2048), (8, 8192)]
              if on_tpu else [(2, 8)])
    steps = 128 if on_tpu else 8
    out = {}
    rs = np.random.RandomState(0)
    for bs, t0 in points:
        model = CausalLM(LM_VOCAB, max_len=t0 + steps,
                         dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                         **LM_BASE)
        tok = jnp.asarray(rs.randint(0, LM_VOCAB, (bs, t0)), jnp.int32)
        variables = model.init(jax.random.key(0), tok)
        gen = jax.jit(lambda v, pr: model.generate(v, pr, steps))

        def prefill_fn(v, pr, model=model, t0=t0):
            cx = Context(_CtxCore(mode="apply", variables=v, mutated={},
                                  rng=None, rng_count=0, training=False))
            caches = model.init_cache(pr.shape[0], t0 + steps)
            return model.prefill(cx, pr, caches)[0]

        pre = jax.jit(prefill_fn)

        # loop-carry a PROMPT THAT NEVER REPEATS: an untrained model's
        # greedy continuation collapses to a constant token, so feeding
        # out[:, -t0:] back makes every dispatch after the first
        # identical and the axon pool serves cached results (measured:
        # bs1 "decode" at 4.8x the HBM roofline). Mixing in the previous
        # prompt AND a step counter keeps inputs injective.
        def step_gen(carry):
            pr, i = carry
            o = gen(variables, pr)
            nxt = (o[:, -t0:].astype(jnp.int32) + pr + i) % LM_VOCAB
            return (nxt, i + 1), o

        def step_pre(carry):
            pr, i = carry
            o = pre(variables, pr)
            nxt = (pr + o[:, :1].astype(jnp.int32) + i) % LM_VOCAB
            return (nxt, i + 1), o

        sec_gen, _, _ = run_timed(step_gen, (tok, jnp.int32(1)),
                                  min_time=min_time)
        sec_pre, _, _ = run_timed(step_pre, (tok, jnp.int32(1)),
                                  min_time=min_time / 2)
        # two independently-noisy windows: clamp the subtraction so a
        # prefill-dominated point on a noisy pool day cannot emit a
        # negative rate or divide by zero (keep >=5% of the gen window)
        dec_sec = max(sec_gen - sec_pre, sec_gen * 0.05)
        dec_ms = dec_sec / steps * 1e3
        key = f"decode_bs{bs}_p{t0}"
        out[f"{key}_tokens_per_sec"] = round(bs * steps / dec_sec, 1)
        out[f"{key}_ms_per_token"] = round(dec_ms, 3)
        if on_tpu:
            # HBM roofline: every decode step reads all params (bf16)
            # plus the live KV cache (bf16, 2 x layers x T x D x bs)
            nparams = sum(x.size for x in
                          jax.tree.leaves(variables[PARAMS]))
            t_avg = t0 + steps / 2
            kv = (2 * LM_BASE["num_layers"] * t_avg
                  * LM_BASE["model_dim"] * bs)
            min_ms = (nparams + kv) * 2 / (HBM_GBPS * 1e6)
            out[f"{key}_hbm_bound_frac"] = round(min_ms / dec_ms, 3)
    if on_tpu:
        r = (out.get("decode_bs32_p32_tokens_per_sec", 0)
             / max(out.get("decode_bs8_p32_tokens_per_sec", 1), 1e-9))
        out["decode_bs32_vs_bs8"] = round(r, 2)
        out["decode_note"] = (
            "frac>1 = weights VMEM-resident across the decode loop "
            "(70MB bf16 fits); long-prompt points are the HBM-bound "
            "regime")
    return out


def _packed_vs_padded_bench(min_time: float = 1.0):
    """Packed ragged batches vs padded batches — the capability the
    segment-id flash kernel buys (r4 VERDICT #1: the LoD->dense packing
    idiom, lod_tensor.h:44-58). Seven documents of mixed lengths
    (512..2048, sum 8192) trained either PACKED into [2, 8192] rows
    with segment ids + per-doc positions (flash skips cross-doc blocks:
    cost ~sum len_i^2) or PADDED to [14, 2048] (75% more tokens, all
    attended). Metric: REAL (non-pad) tokens/s through a full train
    step; the ratio is the packing win."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import run_timed
    from paddle_tpu.benchmark.models import LM_BASE, LM_VOCAB
    from paddle_tpu.core.executor import Trainer
    from paddle_tpu.ops.fused_ce import linear_cross_entropy
    from paddle_tpu.optim.optimizer import Adam

    from paddle_tpu.models.transformer import CausalLM

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        doc_lens = [512, 768, 1024, 1280, 1536, 1024, 2048]   # sum 8192
        pad_to, rows = 2048, 2
    else:
        doc_lens = [64, 96, 96]                               # sum 256
        pad_to, rows = 128, 1
    total = sum(doc_lens)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rs = np.random.RandomState(0)

    def make_model(seq):
        return CausalLM(LM_VOCAB, max_len=seq + 8, dtype=dtype,
                        **LM_BASE)

    # ---- packed: [rows, total] with segs + per-doc positions ---------
    segs = np.concatenate([np.full(n, i, np.int32)
                           for i, n in enumerate(doc_lens)])
    pos = np.concatenate([np.arange(n, dtype=np.int32)
                          for n in doc_lens])
    wts = np.ones(total, np.float32)
    wts[np.cumsum(doc_lens) - 1] = 0.0       # doc-final predicts across
    tokens = rs.randint(0, LM_VOCAB, (rows, total + 1)).astype(np.int32)
    segs_b = jnp.asarray(np.tile(segs, (rows, 1)))
    pos_b = jnp.asarray(np.tile(pos, (rows, 1)))
    wts_b = jnp.asarray(np.tile(wts, (rows, 1)))

    def make_loss(seg_ids, positions, weights):
        def loss_fn(module, variables, batch, rng, training):
            hid, mut = module.apply(variables, batch[0], training=training,
                                    rngs=rng, mutable=True,
                                    return_hidden=True,
                                    segment_ids=seg_ids,
                                    positions=positions)
            w, _ = module.head_weights(variables)
            ce = linear_cross_entropy(hid, w.astype(hid.dtype),
                                      batch[1], None)
            return (jnp.sum(ce * weights) / jnp.sum(weights), {}), \
                mut.get("state", {})
        return loss_fn

    out = {}
    real_tokens = rows * total

    def run(model, loss_fn, batch, label, tokens_per_step):
        tr = Trainer(model, Adam(1e-4), loss_fn)
        ts = tr.init_state(jnp.asarray(batch[0]))
        db = jax.device_put(batch)

        def step(ts):
            ts, f = tr.train_step(ts, db)
            return ts, f["loss"]

        sec, _, _ = run_timed(step, ts, min_time=min_time)
        out[f"{label}_tokens_per_sec"] = round(tokens_per_step / sec, 1)
        out[f"{label}_ms_per_step"] = round(sec * 1e3, 2)

    run(make_model(total), make_loss(segs_b, pos_b, wts_b),
        (tokens[:, :-1], tokens[:, 1:]), "lm_packed", real_tokens)

    # ---- padded: each doc its own row, padded to pad_to --------------
    n_rows = rows * len(doc_lens)
    ptoks = np.zeros((n_rows, pad_to + 1), np.int32)
    pw = np.zeros((n_rows, pad_to), np.float32)
    r = 0
    for b in range(rows):
        off = 0
        for n in doc_lens:
            # row b's token stream, cut per doc — both arms train on the
            # same data
            ptoks[r, :n + 1] = tokens[b, off:off + n + 1]
            pw[r, :n - 1 + 1] = 1.0
            pw[r, n - 1] = 0.0               # last real token: no target
            off += n
            r += 1
    lens_col = np.array([n for _ in range(rows) for n in doc_lens])
    pseg = jnp.asarray((np.arange(pad_to)[None, :]
                        < lens_col[:, None]).astype(np.int32))
    pwts = jnp.asarray(pw)

    run(make_model(pad_to), make_loss(pseg, None, pwts),
        (ptoks[:, :-1], ptoks[:, 1:]), "lm_padded", real_tokens)
    out["packed_vs_padded"] = round(
        out["lm_packed_tokens_per_sec"]
        / max(out["lm_padded_tokens_per_sec"], 1e-9), 2)
    return out


def _int8_compute_bench(min_time: float = 1.0):
    """TRUE int8 inference (quant/int8_compute.py): ResNet-50 frozen to
    int8 MXU compute with calibrated static activation scales, vs the
    bf16 model — at bs16 (the r4 VERDICT #5 point; bandwidth-bound,
    int8 loses) and bs128 (compute-bound, int8 wins ~1.4x measured).
    Accuracy: top-1 agreement + max relative logit error vs bf16."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import chain_k, run_timed
    from paddle_tpu.models import vision as V
    from paddle_tpu.quant.int8_compute import freeze_int8

    on_tpu = jax.devices()[0].platform == "tpu"
    sizes = (16, 128) if on_tpu else (2,)
    img = 224 if on_tpu else 64
    rs = np.random.RandomState(0)
    out = {}
    for bs in sizes:
        x = jnp.asarray(rs.randn(bs, img, img, 3), jnp.float32)
        model = V.resnet50(1000, dtype=jnp.bfloat16 if on_tpu
                           else jnp.float32)
        variables = model.init(jax.random.key(0), x)

        def time_fwd(apply_fn):
            K = 8 if on_tpu else 2
            kf = chain_k(lambda c, xx: apply_fn(xx + c), K)
            sec, _, _ = run_timed(lambda s: (kf(s, x),) * 2,
                                  jnp.zeros((), x.dtype),
                                  min_time=min_time)
            return sec / K * 1e3

        tb = time_fwd(lambda xx: model.apply(variables, xx,
                                             training=False))
        ref = np.asarray(model.apply(variables, x, training=False),
                         np.float32)
        qmodel, qvars = freeze_int8(model, variables,
                                    calib_batches=[(x,)])
        t8 = time_fwd(lambda xx: qmodel.apply(qvars, xx,
                                              training=False))
        got = np.asarray(qmodel.apply(qvars, x, training=False),
                         np.float32)
        out[f"int8_vs_bf16_bs{bs}"] = round(tb / t8, 2)
        out[f"resnet50_int8_infer_imgs_per_sec_bs{bs}"] = round(
            bs / t8 * 1e3, 1)
        out[f"int8_top1_agree_bs{bs}"] = round(
            float((got.argmax(-1) == ref.argmax(-1)).mean()), 3)
        out[f"int8_max_rel_logit_err_bs{bs}"] = round(
            float(np.abs(got - ref).max()
                  / (np.abs(ref).max() + 1e-9)), 4)
    return out


def _resnet_s2d(min_time: float, bs: int = 128):
    """ResNet-50 with the space-to-depth stem (equivalent-capacity
    reparameterization; PERF_NOTES.md addendum)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.benchmark.harness import bench_trainer
    from paddle_tpu.core.executor import Trainer, supervised_loss
    from paddle_tpu.metrics import accuracy
    from paddle_tpu.models import vision as V
    from paddle_tpu.ops import functional as F
    from paddle_tpu.optim.optimizer import Momentum

    model = V.ResNet((3, 4, 6, 3), 1000, dtype=jnp.bfloat16, s2d_stem=True)
    loss_fn = supervised_loss(
        lambda lg, y: F.softmax_with_cross_entropy(
            lg.astype(jnp.float32), y),
        metrics={"acc": accuracy})
    trainer = Trainer(model, Momentum(0.1, momentum=0.9), loss_fn)
    rs = np.random.RandomState(0)
    x = rs.randn(bs, 224, 224, 3).astype(np.float32)
    y = rs.randint(0, 1000, bs).astype(np.int64)
    ts = trainer.init_state(jnp.zeros((bs, 224, 224, 3)))
    batch = jax.device_put((x, y))
    return bench_trainer("resnet50_s2d", trainer, ts, batch,
                         items_per_step=bs, unit="imgs/s", batch_size=bs,
                         min_time=min_time)


def _serving_bench(requests: int = 8, new_tokens: int = 32):
    """Serving axis (ENGINE.md): an in-process ServeEngine under
    continuous batching + speculative decode on a lookup-friendly
    workload. Emits decode throughput plus the latency numbers a
    production scrape would read — TTFT/TPOT p99 straight from the
    metrics registry, and decode steps per generated token (< 1.0 when
    the n-gram drafter is earning its keep). CPU-cheap: the model is
    tiny, so the entry runs on every platform."""
    import logging

    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.engine import ServeEngine
    from paddle_tpu.models.transformer import CausalLM
    from paddle_tpu.obs.metrics import MetricsRegistry

    model = CausalLM(vocab=128, model_dim=64, num_heads=4, num_layers=2,
                     ffn_dim=256, dropout=0.0, max_len=128)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4), jnp.int32))
    rng = np.random.default_rng(9)
    # repetitive prompts: the self-drafter's best case, so steps/token
    # reflects the speculation mechanism rather than model noise
    prompts = [np.tile(rng.integers(0, 127, 6), 4).tolist()
               for _ in range(requests)]
    # bench stdout carries METRIC lines only: mute the engine's
    # per-step serve_event chatter for the duration of the run
    # (.disabled, not setLevel — the lazy _stream_logger creation
    # path resets the level to INFO on first emit)
    lg = logging.getLogger("paddle_tpu.serve")
    prev_disabled = lg.disabled
    lg.disabled = True
    try:
        eng = ServeEngine(model, variables, max_batch_size=4,
                          block_size=16, num_blocks=64, spec_k=4,
                          registry=MetricsRegistry())
        eng.generate([[127] * 4], max_new_tokens=2)  # compile untimed
        eng.reset_stats()
        t0 = time.time()
        for p in prompts:
            eng.add_request(list(p), max_new_tokens=new_tokens)
        eng.run()
        wall = time.time() - t0
    finally:
        lg.disabled = prev_disabled
    gen = int(eng.obs.get("ptpu_serve_tokens_total")
              .labels(kind="generated").value)
    ttft = eng.obs.get("ptpu_serve_ttft_ms")
    tpot = eng.obs.get("ptpu_serve_tpot_ms")
    step_h = eng.obs.get("ptpu_serve_step_ms")
    decode_steps = sum(c.count for kind, c in step_h.children().items()
                       if kind != ("prefill",))
    # direct-read columns (ISSUE 20): repeat traffic against a
    # compression-enabled engine. The cold turn caches the prompt,
    # filler churn evicts its blocks into the int8 tier, and the warm
    # turn re-reads them IN PLACE (kv_promote_hits=0, no promote
    # round-trip). The streamed-KB/token pair prices the warm decode's
    # per-token KV traffic twice — all-fp account vs the measured
    # mixed-residency account (int8-resident tokens at 1 B/elem).
    lg.disabled = True
    try:
        eng2 = ServeEngine(model, variables, max_batch_size=4,
                           block_size=4, num_blocks=24, spec_k=4,
                           kv_compress_blocks=256, kv_promote_hits=0,
                           registry=MetricsRegistry())
        prompt = prompts[0][:23]    # off block stride: the final
        # partial block stays fp-writable, so no forced promote
        eng2.generate([list(prompt)], max_new_tokens=4)      # cold
        for _ in range(6):          # churn: evict into the int8 tier
            eng2.generate([rng.integers(0, 127, 33).tolist()],
                          max_new_tokens=2)
        eng2.reset_stats()
        eng2.generate([list(prompt)], max_new_tokens=4)      # warm
    finally:
        lg.disabled = prev_disabled
    c2 = eng2.cache
    st2 = c2.stats()
    direct_toks = int(st2.get("direct_int8_tokens", 0))
    itemsize = jnp.dtype(c2.dtype).itemsize
    per_tok_fp = len(c2.pools) * 2 * c2.num_kv_heads * c2.head_dim \
        * itemsize
    ctx = -(-len(prompt) // c2.block_size) * c2.block_size
    mix_bytes = (ctx - direct_toks) * per_tok_fp \
        + direct_toks * (per_tok_fp // itemsize)
    return {
        "serve_decode_tok_per_sec": round(gen / max(wall, 1e-9), 1),
        "serve_ttft_p99_ms": round(ttft.quantile(0.99), 3),
        "serve_tpot_p99_ms": round(tpot.quantile(0.99), 3),
        "serve_spec_steps_per_token": round(decode_steps / max(gen, 1), 4),
        # tensor-parallel serving columns (ISSUE 14): the bench engine
        # runs tp=1 (CPU, single device); the columns exist so rig rows
        # at tp>1 land in the same schema, and per-chip pool bytes is
        # MEASURED off the pool arrays' addressable shards
        "serve_tp_size": eng.tp_size,
        "serve_kv_pool_bytes_per_chip": eng.cache.per_chip_pool_bytes(),
        "serve_kv_direct_int8_reads": int(st2.get("direct_int8_reads",
                                                  0)),
        "serve_kv_direct_int8_tokens": direct_toks,
        "serve_kv_streamed_kb_per_tok_fp": round(ctx * per_tok_fp / 1e3,
                                                 3),
        "serve_kv_streamed_kb_per_tok_mix": round(mix_bytes / 1e3, 3),
    }


def _training_bench(steps: int = 10):
    """Training telemetry axis (ISSUE 13 satellite): step-phase p99 and
    MFU for a tiny causal LM through MeshTrainer's instrumented path,
    read back from the SAME ptpu_train_* families a production scrape
    would — so BENCH_r* rows carry the training numbers next to the
    serving axis. CPU-cheap (tiny model, private registry)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.executor import supervised_loss
    from paddle_tpu.models.transformer import CausalLM
    from paddle_tpu.obs.goodput import (causal_lm_step_flops, param_count,
                                        resolve_peak_flops)
    from paddle_tpu.obs.metrics import MetricsRegistry
    from paddle_tpu.optim.optimizer import Adam
    from paddle_tpu.ops import functional as F
    from paddle_tpu.parallel import MeshConfig, MeshTrainer, make_mesh

    vocab, dm, layers, t, b = 128, 64, 2, 32, 8
    model = CausalLM(vocab=vocab, model_dim=dm, num_heads=4,
                     num_layers=layers, ffn_dim=256, dropout=0.0, max_len=t)
    mesh = make_mesh(MeshConfig(dp=jax.device_count()))
    reg = MetricsRegistry()
    loss_fn = supervised_loss(
        lambda lg, y: F.softmax_with_cross_entropy(
            lg.astype(jnp.float32), y))
    trainer = MeshTrainer(model, Adam(1e-3), loss_fn, mesh)
    trainer.enable_metrics(reg)
    rs = np.random.RandomState(0)
    tok = rs.randint(0, vocab, (b, t + 1)).astype(np.int32)
    ts = trainer.init_state(jnp.asarray(tok[:, :-1]))
    batch = trainer.put_batch((tok[:, :-1], tok[:, 1:]))
    for _ in range(steps):
        ts, _ = trainer.train_step(ts, batch)

    step_h = reg.get("ptpu_train_step_ms")
    phase = reg.get("ptpu_train_phase_ms")
    out = {
        "train_step_p99_ms": round(step_h.quantile(0.99), 3),
        "train_dispatch_p99_ms": round(
            phase.labels(phase="dispatch").quantile(0.99), 3),
        "train_wait_p99_ms": round(
            phase.labels(phase="wait").quantile(0.99), 3),
        "train_compiles": int(reg.get("ptpu_train_compiles").value),
    }
    peak = resolve_peak_flops()
    if peak:
        flops = causal_lm_step_flops(
            batch_size=b, seq_len=t, d_model=dm, n_layers=layers,
            n_params=param_count(ts.params))
        # p50 excludes the compile-laden warmup step from the MFU clock
        sec = step_h.quantile(0.5) / 1e3
        if sec > 0:
            out["train_mfu"] = round(flops / sec / peak, 4)
    return out


def _retry(fn, attempts: int = 2):
    """Shared transient-tunnel guard (benchmark/harness.retry_transient);
    imported lazily so this file stays importable before backend init."""
    from paddle_tpu.benchmark.harness import retry_transient
    return retry_transient(fn, attempts=attempts)


def _devices_or_reexec():
    """jax.devices(), robust to a flaky tunnel (observed: hours-long
    UNAVAILABLE windows, and init calls that HANG rather than error).

    Backend init is probed in a SUBPROCESS with a hard timeout first, so
    a hung tunnel can be retried — an in-process hang is unkillable from
    inside. Once a probe succeeds, init in-process (re-exec clears any
    cached failed-backend state). Bounded retries; budget time spent
    waiting counts against _BUDGET_S via the PTPU_BENCH_T0 anchor."""
    def give_up(detail):
        # An in-process init would HANG unkillably on a dead tunnel;
        # print an honest zero-valued line instead of vanishing. (bs64 is
        # the TPU series: the driver only records bench runs on TPU.)
        sys.stderr.write(f"backend unreachable, giving up: {detail}\n")
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec_bs64", "value": 0,
            "unit": "imgs/s", "vs_baseline": 0,
            # top-level no_measurement separates "no measurement taken"
            # from "measured zero" for any consumer regressing on the
            # series; the driver still gets its one JSON line.
            "no_measurement": True,
            "extra": {"error": "TPU backend unreachable after "
                               f"{int(_elapsed())}s of retries; no "
                               "measurement taken", "probe": detail}}))
        sys.exit(0)

    probe = ("import jax\n"
             "print('PLATFORM=' + jax.devices()[0].platform)\n")
    n = int(os.environ.get("PTPU_BENCH_INIT_RETRY", "0"))
    # Probe only under the tunnel (where init can hang); n > 0 means we
    # re-exec'd because a probe just succeeded — skip straight to init.
    while n == 0 and os.environ.get("PALLAS_AXON_POOL_IPS") is not None:
        try:
            t0 = time.time()
            r = subprocess.run([sys.executable, "-c", probe],
                               capture_output=True, text=True, timeout=120)
            ok = "PLATFORM=" in r.stdout
            full = r.stdout + r.stderr          # classify on everything,
            detail = full[-200:]                # truncate for display
            transient = (time.time() - t0 > 20 or "UNAVAILABLE" in full
                         or "Unavailable" in full)
        except subprocess.TimeoutExpired:
            ok, detail, transient = False, "init probe hung >120s", True
        if ok:
            n = int(os.environ.get("PTPU_BENCH_PROBE_FAILS", "0"))
            break
        if not transient:
            # fast deterministic failure (broken env, import error):
            # retrying cannot help, and a zero line would record the
            # breakage as a green run — fail loudly instead
            sys.stderr.write(f"bench init probe failed "
                             f"deterministically:\n{full[-2000:]}\n")
            sys.exit(1)
        fails = int(os.environ.get("PTPU_BENCH_PROBE_FAILS", "0")) + 1
        os.environ["PTPU_BENCH_PROBE_FAILS"] = str(fails)
        if fails > 6 or _elapsed() + 210 > _BUDGET_S:
            give_up(detail)
        sys.stderr.write(f"backend probe failed (try {fails}): {detail}\n")
        time.sleep(90)
    if n and os.environ.get("PTPU_BENCH_INIT_RETRY") != str(n):
        # re-exec so the retried init starts from a clean backend cache
        env = dict(os.environ, PTPU_BENCH_INIT_RETRY=str(n))
        os.execve(sys.executable, [sys.executable] + sys.argv, env)
    import jax
    try:
        return jax.devices()
    except RuntimeError as e:   # tunnel flapped between probe and init
        m = int(os.environ.get("PTPU_BENCH_INIT_FLAP", "0"))
        if m < 2 and _elapsed() + 210 < _BUDGET_S:
            sys.stderr.write(f"init failed after probe ok ({e}); retry\n")
            time.sleep(60)
            env = dict(os.environ, PTPU_BENCH_INIT_FLAP=str(m + 1),
                       PTPU_BENCH_INIT_RETRY="0")
            os.execve(sys.executable, [sys.executable] + sys.argv, env)
        give_up(f"init failed after successful probe: {e}")


def main():
    import jax.numpy as jnp

    from paddle_tpu.benchmark import run_model

    on_tpu = _devices_or_reexec()[0].platform == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    # 1.5s windows (was 2.5): every entry is compile-dominated on the
    # tunnel, and the r4 artifact budget-dropped advertised extras —
    # smaller windows buy entries (r4 VERDICT missing #1)
    min_time = 1.5 if on_tpu else 0.2
    bs = 64 if on_tpu else 8

    # DRIVER CONTRACT bootstrap (BENCH_r05 audit, PERF_NOTES): r5 died
    # rc=124 with parsed:null because the first flushed line printed
    # only AFTER backend init AND the full resnet50 build/compile — on
    # a slow tunnel day that window alone exceeds the driver's kill.
    # Print a zero-valued no_measurement line the moment the metric
    # name is known, BEFORE any model build: a driver kill at any later
    # point still finds a parseable primary line. Every subsequent
    # partial/complete line supersedes it for last-line consumers;
    # first-line consumers see no_measurement=true and know no
    # measurement was taken.
    print(json.dumps({
        "metric": f"resnet50_train_imgs_per_sec_bs{bs}", "value": 0,
        "unit": "imgs/s", "vs_baseline": 0, "no_measurement": True,
        "extra": {"bootstrap": True,
                  "note": "bench starting; measurement pending"},
    }), flush=True)

    # weak-scaling runs on a VIRTUAL CPU mesh in its own process. On TPU
    # it starts NOW and overlaps the device-bound entries (host CPU is
    # nearly idle between dispatches, so the contention is the tunnel
    # sync cost at worst); on a CPU-only run it would steal the very
    # cores the foreground entries are timed on, so there it runs
    # sequentially at the end.
    scaling_proc = _scaling_subprocess_start() if on_tpu else None

    resnet = _retry(lambda: run_model("resnet50", batch_size=bs,
                                      dtype=dtype, min_time=min_time))
    extra = {
        "device": resnet.device,
        "resnet50_mfu": round(resnet.mfu, 4) if resnet.mfu else None,
        "resnet50_tflops_per_sec": (round(resnet.tflops_per_sec, 1)
                                    if resnet.tflops_per_sec else None),
        "resnet50_ms_per_step": round(resnet.ms_per_step, 2),
        "timed_steps": resnet.steps,
    }

    # Entry gate. required=True entries are the priority set (r4
    # VERDICT missing #1: the artifact should carry everything the
    # README claims — decode, s2d, infer, sustained_matmul, scaling,
    # plus the flash correctness gate): they ignore the per-entry
    # estimate and only stop once the budget is actually SPENT — on a
    # pathologically slow day they too must yield rather than run into
    # the driver's kill (r5 artifact: rc=124, no JSON line). Optional
    # entries check the soft budget up front so a slow day degrades to
    # fewer extras first.
    def _gate(key, est_s=120.0, tpu_only=True, required=False):
        if tpu_only and not on_tpu:
            return False
        if required:
            if _elapsed() < _BUDGET_S:
                return True
        elif _budget_ok(est_s):
            return True
        extra[f"{key}_skipped"] = "bench budget"
        return False

    def _primary_line(partial):
        return json.dumps({
            "metric": f"resnet50_train_imgs_per_sec_bs{bs}",
            "value": round(resnet.value, 2), "unit": "imgs/s",
            "vs_baseline": round(resnet.vs_baseline, 3),
            "extra": dict(extra, partial=True) if partial else extra,
        })

    # DRIVER CONTRACT: the measured primary metric prints the moment it
    # exists, flushed, BEFORE any optional entry can run long — a
    # driver timeout (r1/r5 artifacts: rc=124, parsed:null) then still
    # finds a parseable line (the bootstrap line above covers kills
    # before this point). The complete line prints again at the end.
    print(_primary_line(partial=True), flush=True)

    # ---- serving axis: runs EVERYWHERE, right behind the partial
    # primary line (the in-process engine is tiny, and printing another
    # flushed partial line directly after means a later driver kill
    # cannot cost the serving series)
    if _gate("serving", est_s=60, tpu_only=False, required=True):
        try:
            extra.update(_retry(lambda: _serving_bench()))
        except Exception as e:
            extra["serving_error"] = f"{type(e).__name__}: {e}"[:160]
        print(_primary_line(partial=True), flush=True)

    # ---- training telemetry axis: step-phase p99 + MFU off the live
    # ptpu_train_* families (tiny model, runs everywhere)
    if _gate("training_telemetry", est_s=60, tpu_only=False, required=True):
        try:
            extra.update(_retry(lambda: _training_bench()))
        except Exception as e:
            extra["training_telemetry_error"] = \
                f"{type(e).__name__}: {e}"[:160]
        print(_primary_line(partial=True), flush=True)

    try:
        # winning config from the r4 tools/profile_transformer.py sweep:
        # raw_ce (bf16 logits straight into the promoting CE) at bs=32 —
        # 283k tok/s / 56.7% MFU vs 243k / 48.7% at the r3 bs=64 config
        # (fused_qkv and fused_ce both measured slower; PERF_NOTES).
        xf = _retry(lambda: run_model(
            "transformer", batch_size=32 if on_tpu else 2,
            dtype=dtype, min_time=min_time, raw_ce=True))
        extra.update({
            "transformer_tokens_per_sec": round(xf.value, 1),
            "transformer_mfu": round(xf.mfu, 4) if xf.mfu else None,
            "transformer_ms_per_step": round(xf.ms_per_step, 2),
            "transformer_bs": xf.batch_size,
            "transformer_cfg": "raw_ce",
        })
    except Exception as e:  # primary metric must still print
        extra["transformer_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- never-skip set -------------------------------------------------
    if _gate("sustained_matmul", required=True):
        # same-day matmul ceiling NEXT TO the headline numbers: pool
        # noise bounds every MFU (r3: 149 TFLOP/s = 76% of peak; r4:
        # 112 = 57% — without this probe the confound is invisible)
        try:
            from paddle_tpu.benchmark.harness import sustained_matmul_flops
            mp = _retry(lambda: sustained_matmul_flops())
            if mp:
                extra["sustained_matmul_tflops"] = round(mp / 1e12, 1)
        except Exception as e:
            extra["sustained_matmul_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("flash_check", required=True):
        # the on-hardware kernel correctness gate (now incl. segment-id
        # masking and in-kernel dropout) must survive any budget squeeze
        try:
            from paddle_tpu.kernels.selfcheck import flash_selfcheck
            extra.update(_retry(flash_selfcheck))
        except Exception as e:
            extra["flash_check"] = f"FAILED: {type(e).__name__}: {e}"[:220]

    if _gate("lm16k", required=True):  # 16k-token causal-LM TRAIN step:
        # flash causal attention + fused CE (no [T,V] logits) — the
        # long-context training headline (SURVEY §5.7)
        try:
            lm = _retry(lambda: run_model("lm_longctx", batch_size=1,
                                          dtype=dtype, min_time=min_time))
            extra["lm16k_tokens_per_sec"] = round(lm.value, 1)
            extra["lm16k_mfu"] = round(lm.mfu, 4) if lm.mfu else None
            extra["lm16k_ms_per_step"] = round(lm.ms_per_step, 2)
        except Exception as e:
            extra["lm16k_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("decode", required=True):  # KV-cached generate: bs x prompt
        # sweep + HBM roofline (bf16 caches; prefill subtracted)
        try:
            extra.update(_retry(lambda: _decode_bench(
                min_time=max(min_time / 2, 0.6))))
        except Exception as e:
            extra["decode_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("packed", required=True):  # packed ragged batches through
        # the segment-id flash kernel vs padded rows (r4 VERDICT #1)
        try:
            extra.update(_retry(lambda: _packed_vs_padded_bench(
                min_time=max(min_time / 2, 0.6))))
        except Exception as e:
            extra["packed_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("resnet50_s2d", required=True):  # s2d stem: best measured
        # ResNet-50 training config (PERF_NOTES: 0.334 MFU at bs=128)
        try:
            s2d = _retry(lambda: _resnet_s2d(min_time=min_time))
            extra["resnet50_s2d_imgs_per_sec_bs128"] = round(s2d.value, 1)
            extra["resnet50_s2d_mfu"] = (round(s2d.mfu, 4)
                                         if s2d.mfu else None)
        except Exception as e:
            extra["resnet50_s2d_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("infer", required=True):  # inference (reference infer tables)
        try:
            from paddle_tpu.benchmark.models import run_infer
            inf = _retry(lambda: run_infer(
                "resnet50", batch_size=16, dtype=dtype,
                min_time=min_time))
            extra["resnet50_infer_imgs_per_sec_bs16"] = round(inf.value, 1)
            extra["resnet50_infer_vs_baseline"] = (
                round(inf.vs_baseline, 1) if inf.vs_baseline else None)
        except Exception as e:
            extra["infer_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("int8", required=True):  # TRUE int8 compute (r4 VERDICT #5)
        try:
            extra.update(_retry(lambda: _int8_compute_bench(
                min_time=max(min_time / 2, 0.8))))
        except Exception as e:
            extra["int8_error"] = f"{type(e).__name__}: {e}"[:160]

    # ---- optional extras, most important first --------------------------
    # (The r4-era "extend the budget after the required set" hack is
    # gone: it pushed total wall time past the driver's kill and cost
    # the r5 artifact its primary line. The budget is ONE fixed ceiling;
    # required entries drain it first, optionals get what remains.)
    if _gate("bert"):  # BERT-base MLM (BASELINE BERT row)
        try:
            b = _retry(lambda: run_model("bert", batch_size=64,
                                         dtype=dtype, min_time=min_time))
            extra["bert_tokens_per_sec"] = round(b.value, 1)
            extra["bert_mfu"] = round(b.mfu, 4) if b.mfu else None
        except Exception as e:
            extra["bert_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("moe", est_s=240):  # MoE dispatch: masked (E×) vs a2a
        # (k·cf×) + the cf 1.0/2.0 sweep — 4 timed configs
        try:
            extra.update(_retry(lambda: _moe_bench(min_time=min_time)))
        except Exception as e:
            extra["moe_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("longcontext"):  # long-context: flash vs dense at 16k
        try:
            extra.update(_retry(_longcontext_bench))
        except Exception as e:
            extra["longcontext_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("ptq", est_s=180):  # int8 PTQ SIMULATION story (the
        # reference contrib semantics; the true-int8 path is `int8` above)
        try:
            extra.update(_retry(lambda: _ptq_bench(min_time=min_time)))
        except Exception as e:
            extra["ptq_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("resnet50_best_bs"):  # best-bs point (report bs=64 AND best)
        try:
            best = _retry(lambda: run_model(
                "resnet50", batch_size=128, dtype=dtype,
                min_time=min_time))
            extra["resnet50_best_bs"] = 128
            extra["resnet50_imgs_per_sec_best_bs"] = round(best.value, 1)
            extra["resnet50_mfu_best_bs"] = (round(best.mfu, 4)
                                             if best.mfu else None)
        except Exception as e:
            extra["resnet50_best_bs_error"] = f"{type(e).__name__}: {e}"[:160]

    if _gate("transformer_bs64"):  # r3-comparable config, for the series
        try:
            x64 = _retry(lambda: run_model("transformer", batch_size=64,
                                           dtype=dtype,
                                           min_time=min_time))
            extra["transformer_bs64_tokens_per_sec"] = round(x64.value, 1)
            extra["transformer_bs64_mfu"] = (round(x64.mfu, 4)
                                             if x64.mfu else None)
        except Exception as e:
            extra["transformer_bs64_error"] = f"{type(e).__name__}: {e}"[:160]

    if on_tpu:  # reference GPU-table headline models (K40m ms/batch,
        # BASELINE.md: AlexNet 334 ms, GoogLeNet 1149 ms at bs=128)
        for name, ref_ms in (("alexnet", 334.0), ("googlenet", 1149.0)):
            if not _gate(name):
                continue
            try:
                r = _retry(lambda: run_model(name, batch_size=128,
                                             dtype=dtype,
                                             min_time=min_time))
                extra[f"{name}_train_ms_bs128"] = round(r.ms_per_step, 2)
                extra[f"{name}_vs_k40m_speedup"] = round(
                    ref_ms / r.ms_per_step, 1)
            except Exception as e:
                extra[f"{name}_error"] = f"{type(e).__name__}: {e}"[:160]

    # collect the CPU-mesh weak-scaling sweep (on TPU it ran
    # concurrently with everything above; on CPU it runs now,
    # sequentially, so it never contended with the timed entries). The
    # join is bounded by the REMAINING budget: a wedged subprocess must
    # not hold the final JSON line past the driver timeout.
    try:
        if scaling_proc is None:
            scaling_proc = _scaling_subprocess_start()
        extra.update(_scaling_subprocess_join(
            scaling_proc, timeout=max(30.0, _BUDGET_S - _elapsed())))
    except Exception as e:
        extra["scaling_error"] = f"{type(e).__name__}: {e}"[:160]

    print(_primary_line(partial=False), flush=True)


if __name__ == "__main__":
    main()

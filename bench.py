"""Benchmark entrypoint (driver contract: prints ONE JSON line).

Metric: ResNet-50 training throughput, imgs/sec, batch 64, synthetic data —
the reference's headline trainable-model metric (BASELINE.md: ResNet-50
train, imgs/s, bs=64 = 81.69 on 2x Xeon E5-2650v4 via MKL-DNN; the modern
harness benchmark/fluid/fluid_benchmark.py reports the same imgs/s metric).

Runs on whatever jax.devices() provides (real TPU under the driver; CPU
locally). Keeps compile out of the timed region.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMGS_PER_SEC = 81.69  # reference ResNet-50 train bs=64 (BASELINE.md)


def main():
    from paddle_tpu.core.executor import Trainer, supervised_loss
    from paddle_tpu.metrics import accuracy
    from paddle_tpu.models import resnet50
    from paddle_tpu.ops import functional as F
    from paddle_tpu.optim.optimizer import Momentum

    batch = 64
    on_tpu = jax.devices()[0].platform == "tpu"
    # bf16 compute on TPU (MXU native), fp32 params.
    model = resnet50(num_classes=1000,
                     dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    loss_fn = supervised_loss(
        lambda logits, y: F.softmax_with_cross_entropy(
            logits.astype(jnp.float32), y),
        metrics={"acc": accuracy})
    trainer = Trainer(model, Momentum(0.1, momentum=0.9), loss_fn)

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 224, 224, 3).astype(np.float32)
    y = rng.randint(0, 1000, size=batch).astype(np.int64)
    x, y = jax.device_put(x), jax.device_put(y)

    ts = trainer.init_state(x)
    key = jax.random.key(0)

    # warmup/compile
    for _ in range(3):
        ts, fetches = trainer.train_step(ts, (x, y), rng=key)
    jax.block_until_ready(fetches["loss"])

    steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(steps):
        ts, fetches = trainer.train_step(ts, (x, y), rng=key)
    jax.block_until_ready(fetches["loss"])
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_bs64",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/s",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()

from paddle_tpu.io.checkpoint import (
    load_checkpoint, load_persistables, save_checkpoint, save_persistables,
    latest_checkpoint, list_checkpoints, checkpoint_step, verify_checkpoint,
    AsyncCheckpointer, CheckpointIntegrityError, CheckpointManager,
)
from paddle_tpu.io.inference import (
    save_inference_model, load_inference_model, InferencePredictor,
)

"""Inference model export/load.

Capability-equivalent of the reference inference stack:
- save_inference_model (io.py:859): prune to fetch targets + serialize
  program + params → here: export the *traced* forward fn as StableHLO
  (jax.export) + params checkpoint + a JSON signature. StableHLO is the
  TPU-native analog of the pruned ProgramDesc: a compiler-stable, versioned
  serialization of exactly the computation to serve.
- load_inference_model (io.py:1011) / AnalysisPredictor::Run
  (api/analysis_predictor.h:52): `InferencePredictor` deserializes and
  compiles once, then `run()` is zero-overhead (≈ ZeroCopyRun :61).
- The reference's Analyzer fusion passes (analysis/ir_pass_manager.cc) are
  XLA's job at compile time — the export records optimization-independent
  semantics.

The C++ serving shim (paddle_tpu/serving/) reads the same artifact layout.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
# jax 0.4.x: the `jax.export` ATTRIBUTE raises (accelerated deprecation
# shim) while the submodule imports fine — bind the module directly
from jax import export as jax_export

from paddle_tpu.core.module import Module, Variables
from paddle_tpu.io.checkpoint import load_checkpoint, save_checkpoint

_SIG = "signature.json"
_HLO = "model.stablehlo"
_PARAMS = "params"


def _prune_empty(tree):
    """Drop empty sub-dicts (e.g. a stateless model's empty `state`
    collection) so the exported pytree structure matches what a checkpoint
    round-trip reconstructs."""
    if isinstance(tree, dict):
        out = {k: _prune_empty(v) for k, v in tree.items()}
        return {k: v for k, v in out.items()
                if not (isinstance(v, dict) and not v)}
    return tree


def save_inference_model(path: str, module_or_fn, variables: Variables,
                         example_inputs: Sequence[Any],
                         input_names: Optional[Sequence[str]] = None,
                         serve_meta: Optional[Dict] = None) -> str:
    """Export a servable model directory.

    module_or_fn: a Module (its apply in eval mode is exported) or a pure
    fn(variables, *inputs). The exported computation closes over nothing —
    params are explicit inputs so the same artifact serves any checkpoint
    with the same structure.

    serve_meta: optional dict recorded as the manifest's `serve` block
    (engine.serve_metadata(model) for a CausalLM: max seq length, KV
    head count/dim, vocab size, layer config) so
    `ServeEngine.from_saved_model` can rebuild the module and size its
    KV pools without re-deriving shapes from the checkpoint. Manifests
    written without it stay loadable everywhere (readers use .get).
    """
    if isinstance(module_or_fn, Module):
        module = module_or_fn

        def fn(variables, *inputs):
            return module.apply(variables, *inputs, training=False)
    else:
        fn = module_or_fn

    variables = _prune_empty(variables)
    # Gather to host first: training variables may be mesh-sharded, and
    # jax.export would bake the training device count into the artifact —
    # a served model must load on any topology (≈ the reference's pruned
    # inference ProgramDesc being executor-agnostic, io.py:859).
    variables = jax.tree.map(np.asarray, variables)
    example_inputs = tuple(jnp.asarray(x) for x in example_inputs)
    exported = jax_export.export(jax.jit(fn))(variables, *example_inputs)
    blob = exported.serialize()

    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, _HLO), "wb") as f:
        f.write(blob)
    save_checkpoint(os.path.join(path, _PARAMS), variables)
    sig = {
        "version": 1,
        "input_names": list(input_names or
                            [f"x{i}" for i in range(len(example_inputs))]),
        "inputs": [{"shape": list(x.shape), "dtype": str(x.dtype)}
                   for x in example_inputs],
    }
    if serve_meta is not None:
        sig["serve"] = dict(serve_meta)
    with open(os.path.join(path, _SIG), "w") as f:
        json.dump(sig, f, indent=1)
    return path


def load_inference_model(path: str) -> Tuple[Callable, Variables, Dict]:
    """Returns (callable(variables, *inputs), variables, signature)."""
    with open(os.path.join(path, _HLO), "rb") as f:
        exported = jax_export.deserialize(f.read())
    variables = load_checkpoint(os.path.join(path, _PARAMS))
    with open(os.path.join(path, _SIG)) as f:
        sig = json.load(f)
    return exported.call, variables, sig


class InferencePredictor:
    """Compiled predictor over an exported model (≈ AnalysisPredictor).

    run(feed) accepts positional list or name-keyed dict; outputs come back
    as numpy. The first call compiles; afterwards it's a single dispatch.
    """

    def __init__(self, model_dir: str):
        fn, self.variables, self.signature = load_inference_model(model_dir)
        self._fn = jax.jit(fn)
        self._input_names = self.signature["input_names"]

    def run(self, feed) -> List[np.ndarray]:
        if isinstance(feed, dict):
            inputs = [feed[n] for n in self._input_names]
        else:
            inputs = list(feed)
        out = self._fn(self.variables, *[jnp.asarray(x) for x in inputs])
        leaves = jax.tree_util.tree_leaves(out)
        return [np.asarray(x) for x in leaves]

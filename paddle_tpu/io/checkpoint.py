"""Checkpoint save/load for arbitrary pytrees (TrainState, variables).

Capability-equivalent of the reference persistence stack:
- save/load_persistables (python/paddle/fluid/io.py:441,657) via save/load
  graph ops (operators/save_op.cc, load_op.cc) — here a direct, durable
  on-disk format: one .npz of flattened leaves + a JSON manifest describing
  the tree structure and dtypes (the "combined single-file" form,
  io.py `filename=`).
- Distributed-aware save (_save_distributed_persistables io.py:261): sharded
  arrays are gathered per-leaf via `jax.device_get` (addressable shards are
  reassembled by JAX); on load, arrays are put back with the requested
  sharding. Multi-host: only process 0 writes (others no-op) and every
  process reads — the TPU idiom replacing pserver-side slicing.
- CheckpointManager adds retention + atomic-rename commit + resume
  (the reference's checkpoint dir rotation in the old trainer API).

Format stability note: keys are '/'-joined tree paths; values are raw numpy.
No pickle anywhere — loadable by any numpy, auditable, and
language-neutral (the C++ serving shim reads the same manifest).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

Pytree = Any

_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"


def _flatten(tree: Pytree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, leaf))
    return out


def _is_multiprocess() -> bool:
    return jax.process_count() > 1


def save_checkpoint(path: str, tree: Pytree, step: Optional[int] = None,
                    metadata: Optional[Dict] = None) -> str:
    """Write `tree` to directory `path` atomically. Returns the path."""
    if _is_multiprocess() and jax.process_index() != 0:
        return path  # single-writer; data is replicated or addressable-gathered
    flat = _flatten(tree)
    arrays = {}
    manifest = {"version": 1, "step": step, "metadata": metadata or {},
                "leaves": []}
    for i, (key, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf))
        slot = f"a{i}"
        arrays[slot] = arr
        manifest["leaves"].append(
            {"key": key, "slot": slot, "shape": list(arr.shape),
             "dtype": str(arr.dtype)})
    tmp = tempfile.mkdtemp(dir=os.path.dirname(os.path.abspath(path)) or ".")
    try:
        np.savez(os.path.join(tmp, _ARRAYS), **arrays)
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


def load_checkpoint(path: str, target: Optional[Pytree] = None,
                    shardings: Optional[Pytree] = None) -> Pytree:
    """Load a checkpoint directory.

    With `target` (a pytree of like-structured arrays/ShapeDtypeStructs) the
    result mirrors its structure exactly (and validates shapes). Without, a
    nested dict keyed by path segments is returned. `shardings` (same
    structure as target) places leaves onto the mesh on load — the analog of
    the reference's slice-on-load (_load_distributed_persistables io.py:704).
    """
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    with np.load(os.path.join(path, _ARRAYS)) as z:
        by_key = {l["key"]: z[l["slot"]] for l in manifest["leaves"]}

    if target is None:
        out: Dict[str, Any] = {}
        for key, arr in by_key.items():
            node = out
            parts = key.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = arr
        return out

    flat_t = _flatten(target)
    missing = [k for k, _ in flat_t if k not in by_key]
    if missing:
        raise FileNotFoundError(
            f"checkpoint {path} missing {len(missing)} leaves, "
            f"e.g. {missing[:5]}")
    leaves = []
    shard_flat = _flatten(shardings) if shardings is not None else None
    for i, (key, ref) in enumerate(flat_t):
        arr = by_key[key]
        if tuple(arr.shape) != tuple(ref.shape):
            raise ValueError(f"leaf {key}: checkpoint shape {arr.shape} != "
                             f"target {tuple(ref.shape)}")
        arr = arr.astype(ref.dtype) if hasattr(ref, "dtype") else arr
        if shard_flat is not None:
            arr = jax.device_put(arr, shard_flat[i][1])
        leaves.append(arr)
    treedef = jax.tree_util.tree_structure(target)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# Reference-compatible aliases (io.py:441 save_persistables / :657 load).
save_persistables = save_checkpoint
load_persistables = load_checkpoint


_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    best = None
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name, _MANIFEST)):
            step = int(m.group(1))
            if best is None or step > best[0]:
                best = (step, os.path.join(directory, name))
    return best[1] if best else None


class CheckpointManager:
    """Rotation + resume policy over save/load (elastic-recovery story §5.3:
    restart-from-checkpoint replaces the reference's nonexistent elasticity,
    and checkpoint-notify becomes a plain directory convention)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    def save(self, tree: Pytree, step: int,
             metadata: Optional[Dict] = None) -> str:
        path = os.path.join(self.directory, f"ckpt-{step}")
        save_checkpoint(path, tree, step=step, metadata=metadata)
        self._gc()
        return path

    def restore_latest(self, target: Optional[Pytree] = None,
                       shardings: Optional[Pytree] = None
                       ) -> Tuple[Optional[Pytree], Optional[int]]:
        path = latest_checkpoint(self.directory)
        if path is None:
            return None, None
        with open(os.path.join(path, _MANIFEST)) as f:
            step = json.load(f).get("step")
        return load_checkpoint(path, target, shardings), step

    def _gc(self) -> None:
        if _is_multiprocess() and jax.process_index() != 0:
            return
        entries = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                entries.append((int(m.group(1)), name))
        entries.sort()
        for _, name in entries[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)

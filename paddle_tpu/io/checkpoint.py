"""Checkpoint save/load for arbitrary pytrees (TrainState, variables).

Capability-equivalent of the reference persistence stack:
- save/load_persistables (python/paddle/fluid/io.py:441,657) via save/load
  graph ops (operators/save_op.cc, load_op.cc).
- Distributed-aware save (_save_distributed_persistables io.py:261): the
  reference gathers sliced param blocks from pservers; here every process
  writes ONLY the shards it owns (addressable shards with replica_id 0),
  so a multi-host FSDP/tp-sharded TrainState checkpoints without any
  cross-host gather — the orbax-style sharded layout SURVEY §5.4 commits
  to, in a dependency-free npz+json form.
- On load, each process reads only the shard files that intersect the
  pieces it needs (jax.make_array_from_callback drives which regions are
  materialised) — the analog of slice-on-load
  (_load_distributed_persistables io.py:704).
- CheckpointManager adds retention + atomic-rename commit + resume.

On-disk layout (format version 2):
    manifest.json           tree structure: key, global shape, dtype per leaf
    shards-p{K}.npz         arrays owned by process K
    shard_index-p{K}.json   per-shard placement: leaf ordinal + index slices

Multi-process coordination: processes meet at barriers between the write
and commit phases. A process that fails locally drops an error marker
next to the target path *before* entering the barrier, and every process
checks for markers *after* it — so one bad disk surfaces as an exception
everywhere instead of a silent hang. (A process that dies outright still
hangs the collective — that is inherent to any barrier and is bounded by
the job-level timeout, same as the reference's RPC deadline story.)

No pickle anywhere — loadable by any numpy, auditable, language-neutral
(the C++ serving shim reads the same manifest). Version-1 checkpoints
(single arrays.npz) remain loadable.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import shutil
import tempfile
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from paddle_tpu.resilience import chaos as _chaos
from paddle_tpu.resilience.retry import (
    RetryPolicy, retry_call, shared_budget)
from paddle_tpu.utils.log import resilience_event

Pytree = Any

_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"  # version-1 layout (read-compat only)

# Shared-FS writes/reads see transient errors (NFS timeouts, GCS 5xx);
# bounded retries here, content errors (CRC) are NOT retryable.
_IO_RETRY = RetryPolicy(attempts=3, base_delay=0.1, max_delay=2.0,
                        retry_on=(OSError,))
# A barrier re-wait reuses the SAME key (peers that already joined are
# still blocked on us), but a DEADLINE error means they moved on —
# re-waiting can only hang again, so give up on those.
_BARRIER_RETRY = RetryPolicy(
    attempts=2, base_delay=0.2, max_delay=2.0, retry_on=(RuntimeError,),
    giveup=lambda e: "deadline" in str(e).lower()
    or "timed out" in str(e).lower())


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint exists on disk but its content cannot be trusted:
    unreadable/garbled manifest, missing shard file, or a CRC32/size
    mismatch (torn write, bit rot). `CheckpointManager.restore_latest`
    treats it as "skip this checkpoint, try the next-newest"."""


def _crc32_file(path: str) -> Tuple[int, int]:
    """(crc32, size) of a file, streamed."""
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                return crc, size
            crc = zlib.crc32(block, crc)
            size += len(block)


def _flatten(tree: Pytree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out.append((key, leaf))
    return out


def _is_multiprocess() -> bool:
    return jax.process_count() > 1


_barrier_lock = threading.Lock()
_barrier_seq: Dict[str, int] = {}   # per-barrier-name use counts


def _barrier(name: str) -> None:
    """Cross-process barrier over the coordination service (host-side
    RPC), NOT a device collective: an async-save worker thread must be
    able to hit this while the main thread keeps dispatching training
    programs — sync_global_devices from a second thread deadlocks the
    device stream (observed).

    Barrier ids must agree across processes. `name` already embeds the
    checkpoint path and stage; the per-NAME use count (not a global
    counter) disambiguates repeated saves to the same path without
    coupling independent save streams — a global counter would make ids
    depend on thread interleaving when an async save overlaps a sync
    save to a different path. Within one path's stream, ordering is the
    single-writer contract every save already requires.
    """
    if not _is_multiprocess():
        return
    try:
        from jax._src import distributed as _distributed
        client = _distributed.global_state.client
    except (ImportError, AttributeError):
        client = None
    if client is not None:
        with _barrier_lock:
            seq = _barrier_seq.get(name, 0)
            _barrier_seq[name] = seq + 1
        key = f"ptpu-ckpt:{seq}:{name}".replace("/", "|")

        def wait():
            _chaos.maybe_fail("barrier")
            client.wait_at_barrier(key, 600_000)
        # transient RPC failure before joining: peers are still blocked
        # on us, so a re-wait on the same key completes the rendezvous
        retry_call(wait, policy=_BARRIER_RETRY, name="barrier",
                   budget=shared_budget())
        return
    # No coordination client (private jax API moved?): the device-
    # collective fallback is only safe on the main thread — from a
    # worker thread it would race the training stream (the deadlock this
    # barrier exists to avoid), so fail loudly instead.
    if threading.current_thread() is not threading.main_thread():
        raise RuntimeError(
            "checkpoint barrier: no coordination-service client available "
            "(jax._src.distributed.global_state moved?) and a device-"
            "collective barrier cannot run from the async-save thread")
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def _index_to_json(index, shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _normalize(region: Tuple[slice, ...], shape: Tuple[int, ...]
               ) -> Tuple[Tuple[int, int], ...]:
    """Slices (possibly open-ended) → concrete (start, stop) per dim."""
    out = []
    for sl, dim in zip(region, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


# -- failure-marker protocol around multi-process barriers ------------------

def _marker(path: str, proc: int) -> str:
    return os.path.abspath(path) + f".err-p{proc}"


def _mark_failure(path: str, proc: int, exc: BaseException) -> None:
    try:
        with open(_marker(path, proc), "w") as f:
            f.write(f"{type(exc).__name__}: {exc}")
    except OSError:
        pass  # the check below will still see *our* raised exception


def _check_failures(path: str) -> None:
    # glob.escape: a checkpoint path containing [ ] ? * must not be
    # treated as a pattern, or peer-failure markers become invisible.
    markers = sorted(glob.glob(glob.escape(os.path.abspath(path))
                               + ".err-p*"))
    if markers:
        msgs = []
        for m in markers:
            try:
                with open(m) as f:
                    msgs.append(f"{os.path.basename(m)}: {f.read()}")
            except OSError:
                msgs.append(os.path.basename(m))
        raise RuntimeError(
            f"checkpoint save to {path} failed on a peer process:\n  "
            + "\n  ".join(msgs))


def _clear_markers(path: str) -> None:
    for m in glob.glob(glob.escape(os.path.abspath(path)) + ".err-p*"):
        try:
            os.remove(m)
        except OSError:
            pass


def _snapshot(tree: Pytree):
    """Device→host snapshot of the shards this process owns.

    Runs on the CALLING thread (the arrays may be donated/overwritten by
    the very next train step, so the copies must exist before control
    returns); the result is pure host data that `_write_snapshot` can
    persist from any thread.
    """
    flat = _flatten(tree)
    proc = jax.process_index()
    leaves_meta = []
    my_shards: Dict[str, np.ndarray] = {}
    my_index: List[dict] = []
    for i, (key, leaf) in enumerate(flat):
        if isinstance(leaf, jax.Array):
            shape, dtype = leaf.shape, str(leaf.dtype)
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                slot = f"a{i}_s{len(my_index)}"
                # copy=True: on CPU backends jax.Array→numpy can be
                # zero-copy, and a view into a donated buffer would be
                # overwritten by the next train step.
                my_shards[slot] = np.array(shard.data, copy=True)
                my_index.append({"leaf": i, "slot": slot,
                                 "index": _index_to_json(shard.index, shape)})
        else:
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, str(arr.dtype)
            if proc == 0:
                slot = f"a{i}_s{len(my_index)}"
                my_shards[slot] = arr
                my_index.append(
                    {"leaf": i, "slot": slot,
                     "index": _index_to_json((slice(None),) * arr.ndim,
                                             shape)})
        leaves_meta.append({"key": key, "shape": list(shape), "dtype": dtype})
    return leaves_meta, my_shards, my_index, proc


def save_checkpoint(path: str, tree: Pytree, step: Optional[int] = None,
                    metadata: Optional[Dict] = None) -> str:
    """Write `tree` to directory `path` atomically. Returns the path.

    Every process participates: each writes the shards it owns (exactly
    one process holds replica 0 of any shard index, so each piece of data
    is written once globally). Process 0 additionally writes the manifest
    and commits the rename. Assumes a shared filesystem across processes
    (the same assumption the reference's pserver checkpointing makes).

    Multi-process cadence contract: every process must call
    save_checkpoint the same number of times for any given `path` —
    the barrier ids embed a per-path sequence counter held in process
    memory, so a process that locally retries a failed save (or a
    restarted process rejoining mid-stream) desynchronizes the counters
    and every peer blocks for the full barrier timeout. Use
    CheckpointManager (unique ckpt-{step} directory per save) when saves
    may be retried or processes may restart.
    """
    snap = _snapshot(tree)
    return _write_snapshot(path, snap, step, metadata)


def _write_snapshot(path: str, snap, step: Optional[int],
                    metadata: Optional[Dict]) -> str:
    """File/commit phase over a host snapshot — no device access, safe to
    run on a background thread (AsyncCheckpointer)."""
    leaves_meta, my_shards, my_index, proc = snap
    multi = _is_multiprocess()
    if multi:
        # Deterministic staging dir: all processes must agree on the name.
        tmp = os.path.abspath(path) + ".ptmp"
        if proc == 0:
            _clear_markers(path)
            try:
                shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp, exist_ok=True)
            except BaseException as e:
                _mark_failure(path, proc, e)
        _barrier(f"ckpt-stage:{path}")
        _check_failures(path)
    else:
        # Clear stale markers here too: a failed multi-host save followed by
        # a single-process retry to the same path must not keep failing on
        # the dead peer's marker.
        _clear_markers(path)
        tmp = tempfile.mkdtemp(
            dir=os.path.dirname(os.path.abspath(path)) or ".")
    try:
        try:
            def write_shards():
                _chaos.maybe_fail("ckpt_write")
                np.savez(os.path.join(tmp, f"shards-p{proc}.npz"),
                         **my_shards)
                with open(os.path.join(tmp, f"shard_index-p{proc}.json"),
                          "w") as f:
                    json.dump(my_index, f)
            retry_call(write_shards, policy=_IO_RETRY, name="ckpt_write",
                       budget=shared_budget())
        except BaseException as e:
            if multi:
                _mark_failure(path, proc, e)
            raise
        finally:
            _barrier(f"ckpt-shards:{path}")
        _check_failures(path)
        if proc == 0:
            try:
                # Per-shard CRC32s into the manifest: proc 0 reads every
                # peer's staged files back through the shared FS — the
                # checksum covers what actually landed on disk, and a
                # file the FS hasn't surfaced yet fails the save loudly
                # instead of committing a torn checkpoint.
                files = {}
                for name in sorted(os.listdir(tmp)):
                    if name.startswith(("shards-p", "shard_index-p")):
                        crc, size = _crc32_file(os.path.join(tmp, name))
                        files[name] = {"crc32": crc, "bytes": size}
                manifest = {"version": 2, "step": step,
                            "metadata": metadata or {},
                            "process_count": jax.process_count(),
                            "files": files,
                            "leaves": leaves_meta}
                with open(os.path.join(tmp, _MANIFEST), "w") as f:
                    json.dump(manifest, f, indent=1)
                if os.path.isdir(path):
                    shutil.rmtree(path)
                os.replace(tmp, path)
            except BaseException as e:
                if multi:
                    _mark_failure(path, proc, e)
                raise
            finally:
                _barrier(f"ckpt-commit:{path}")
        else:
            _barrier(f"ckpt-commit:{path}")
        _check_failures(path)
    except BaseException:
        if proc == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


class _ShardSource:
    """Lazy reader over a checkpoint's shard files: loads only the slots
    whose saved index intersects a requested region, keeping npz handles
    open across reads. This is what makes multi-host restore scale — a
    host assembles its own pieces, never the full model."""

    def __init__(self, path: str, manifest: dict):
        self.path = path
        self.manifest = manifest
        self.version = manifest.get("version", 1)
        # leaf ordinal -> [(concrete index spans, file id, slot)]
        self.pieces: Dict[int, List[Tuple[Tuple[Tuple[int, int], ...],
                                          Any, str]]] = {}
        self._files: Dict[Any, Any] = {}
        # fname -> recorded {"crc32", "bytes"}; absent on v1/older-v2
        # checkpoints, which load unverified (read-compat)
        self._sums: Dict[str, dict] = manifest.get("files") or {}
        self._verified: set = set()
        if self.version == 1:
            for i, meta in enumerate(manifest["leaves"]):
                spans = tuple((0, d) for d in meta["shape"])
                self.pieces[i] = [(spans, _ARRAYS, meta["slot"])]
        else:
            for p in range(manifest.get("process_count", 1)):
                iname = f"shard_index-p{p}.json"
                self._verify(iname)
                try:
                    with open(os.path.join(path, iname)) as f:
                        index = json.load(f)
                except json.JSONDecodeError as e:
                    raise CheckpointIntegrityError(
                        f"checkpoint {path}: {iname} is not valid JSON "
                        f"({e})") from e
                fname = f"shards-p{p}.npz"
                for rec in index:
                    spans = tuple((a, b) for a, b in rec["index"])
                    self.pieces.setdefault(rec["leaf"], []).append(
                        (spans, fname, rec["slot"]))

    def _verify(self, fname: str) -> None:
        """CRC32/size check of `fname` against the manifest record,
        once per file, lazily — a multi-host restore only pays for the
        shard files it actually opens."""
        if fname in self._verified:
            return
        meta = self._sums.get(fname)
        if meta is not None:
            full = os.path.join(self.path, fname)
            if not os.path.exists(full):
                raise CheckpointIntegrityError(
                    f"checkpoint {self.path}: missing {fname}")
            crc, size = _crc32_file(full)
            if size != meta["bytes"] or crc != meta["crc32"]:
                raise CheckpointIntegrityError(
                    f"checkpoint {self.path}: {fname} corrupt "
                    f"(crc32 {crc:#x} != {meta['crc32']:#x} or "
                    f"{size} != {meta['bytes']} bytes)")
        self._verified.add(fname)

    def _slot(self, fname: str, slot: str) -> np.ndarray:
        if fname not in self._files:
            self._verify(fname)

            def load():
                _chaos.maybe_fail("ckpt_read")
                return np.load(os.path.join(self.path, fname))
            self._files[fname] = retry_call(load, policy=_IO_RETRY,
                                            name="ckpt_read",
                                            budget=shared_budget())
        return self._files[fname][slot]

    def read_region(self, leaf: int, region: Tuple[slice, ...],
                    shape: Tuple[int, ...], dtype) -> np.ndarray:
        want = _normalize(region, shape)
        rshape = tuple(b - a for a, b in want)
        total = math.prod(rshape) if rshape else 1
        recs = self.pieces.get(leaf, [])
        # fast path: one piece exactly covers the request
        for spans, fname, slot in recs:
            if spans == want:
                return np.asarray(self._slot(fname, slot))
        out = np.empty(rshape, dtype)
        filled = 0
        for spans, fname, slot in recs:
            inter = []
            for (ws, we), (ps, pe) in zip(want, spans):
                s, e = max(ws, ps), min(we, pe)
                if s >= e:
                    inter = None
                    break
                inter.append((s, e))
            if inter is None:
                continue
            dst = tuple(slice(s - ws, e - ws)
                        for (s, e), (ws, _) in zip(inter, want))
            src = tuple(slice(s - ps, e - ps)
                        for (s, e), (ps, _) in zip(inter, spans))
            out[dst] = self._slot(fname, slot)[src]
            filled += math.prod(e - s for s, e in inter) if inter else 1
        if filled < total:
            key = self.manifest["leaves"][leaf]["key"]
            raise FileNotFoundError(
                f"checkpoint {self.path}: leaf {key!r} region incomplete "
                f"({filled}/{total} elements); missing shard files?")
        return out

    def read_full(self, leaf: int) -> np.ndarray:
        meta = self.manifest["leaves"][leaf]
        shape = tuple(meta["shape"])
        return self.read_region(leaf, tuple(slice(0, d) for d in shape),
                                shape, np.dtype(meta["dtype"]))

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files.clear()


def load_checkpoint(path: str, target: Optional[Pytree] = None,
                    shardings: Optional[Pytree] = None) -> Pytree:
    """Load a checkpoint directory.

    With `target` (a pytree of like-structured arrays/ShapeDtypeStructs) the
    result mirrors its structure exactly (and validates shapes). Without, a
    nested dict keyed by path segments is returned. `shardings` (same
    structure as target) places leaves onto the mesh on load; non-fully-
    addressable shardings (multi-host) are honoured — each process reads
    and materialises only its own pieces via jax.make_array_from_callback.
    """
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    src = _ShardSource(path, manifest)
    try:
        key_to_leaf = {meta["key"]: i
                       for i, meta in enumerate(manifest["leaves"])}

        if target is None:
            out: Dict[str, Any] = {}
            for key, i in key_to_leaf.items():
                node = out
                parts = key.split("/")
                for p in parts[:-1]:
                    node = node.setdefault(p, {})
                node[parts[-1]] = src.read_full(i)
            return out

        flat_t = _flatten(target)
        missing = [k for k, _ in flat_t if k not in key_to_leaf]
        if missing:
            raise FileNotFoundError(
                f"checkpoint {path} missing {len(missing)} leaves, "
                f"e.g. {missing[:5]}")
        out_leaves = []
        shard_flat = _flatten(shardings) if shardings is not None else None
        for i, (key, ref) in enumerate(flat_t):
            leaf = key_to_leaf[key]
            meta = manifest["leaves"][leaf]
            shape = tuple(meta["shape"])
            if shape != tuple(ref.shape):
                raise ValueError(
                    f"leaf {key}: checkpoint shape {shape} != "
                    f"target {tuple(ref.shape)}")
            dtype = getattr(ref, "dtype", np.dtype(meta["dtype"]))
            if shard_flat is not None:
                sharding = shard_flat[i][1]
            elif isinstance(ref, jax.Array):
                # No explicit shardings: restore onto the TARGET's own
                # sharding (a donated/deleted target still carries its
                # sharding metadata). Without this, a restored fsdp state
                # came back as host numpy and the train step's donation
                # paired differently-sharded in/out buffers — an XLA
                # "aliased input/output size" crash on the first step
                # after resume.
                sharding = ref.sharding
            else:
                sharding = None
            if sharding is not None:
                memo: Dict[Tuple, np.ndarray] = {}

                def cb(idx, _leaf=leaf, _shape=shape, _dtype=dtype,
                       _memo=memo):
                    mk = _normalize(idx, _shape)
                    if mk not in _memo:
                        _memo[mk] = src.read_region(
                            _leaf, idx, _shape, _dtype).astype(
                                _dtype, copy=False)
                    return _memo[mk]

                out_leaves.append(jax.make_array_from_callback(
                    shape, sharding, cb))
            else:
                arr = src.read_full(leaf)
                out_leaves.append(arr.astype(dtype, copy=False)
                                  if hasattr(ref, "dtype") else arr)
        treedef = jax.tree_util.tree_structure(target)
        return jax.tree_util.tree_unflatten(treedef, out_leaves)
    finally:
        src.close()


def read_metadata(path: str) -> Dict:
    """Read a checkpoint's manifest metadata dict (without loading data).

    Used to validate structural assumptions on restore, e.g.
    ShardedEmbedding.validate_checkpoint guards against a num_embeddings
    change silently misaligning padded table rows."""
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    return manifest.get("metadata", {}) or {}


# Reference-compatible aliases (io.py:441 save_persistables / :657 load).
save_persistables = save_checkpoint
load_persistables = load_checkpoint


_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """Committed checkpoints as [(step, path)], NEWEST first. Only
    exact `ckpt-{step}` names count — `ckpt-{step}.ptmp` staging dirs
    (an uncommitted save in flight or crashed mid-write) and anything
    without a manifest are never offered for restore."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name, _MANIFEST)):
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def latest_checkpoint(directory: str) -> Optional[str]:
    ckpts = list_checkpoints(directory)
    return ckpts[0][1] if ckpts else None


def checkpoint_step(path: str) -> Optional[int]:
    """The manifest's recorded step (None for stepless saves)."""
    with open(os.path.join(path, _MANIFEST)) as f:
        return json.load(f).get("step")


def verify_checkpoint(path: str) -> Dict:
    """Validate a committed checkpoint end to end and return its
    manifest: manifest parses, every recorded file exists, and every
    CRC32/size matches. Checkpoints written before checksums existed
    (and version-1 single-npz saves) pass on existence alone.

    Raises CheckpointIntegrityError with the first failure — the
    message is what restore_latest logs in its `ckpt_reject` event."""
    manifest_path = os.path.join(path, _MANIFEST)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except OSError as e:
        raise CheckpointIntegrityError(
            f"checkpoint {path}: manifest unreadable ({e})") from e
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointIntegrityError(
            f"checkpoint {path}: manifest is not valid JSON ({e})") from e
    files = manifest.get("files")
    if files:
        for fname, meta in sorted(files.items()):
            full = os.path.join(path, fname)
            if not os.path.exists(full):
                raise CheckpointIntegrityError(
                    f"checkpoint {path}: missing {fname}")
            crc, size = _crc32_file(full)
            if size != meta["bytes"] or crc != meta["crc32"]:
                raise CheckpointIntegrityError(
                    f"checkpoint {path}: {fname} corrupt "
                    f"(crc32 {crc:#x} != {meta['crc32']:#x} or "
                    f"{size} != {meta['bytes']} bytes)")
    elif manifest.get("version", 1) == 1:
        if not os.path.exists(os.path.join(path, _ARRAYS)):
            raise CheckpointIntegrityError(
                f"checkpoint {path}: missing {_ARRAYS}")
    else:
        for p in range(manifest.get("process_count", 1)):
            for fname in (f"shards-p{p}.npz", f"shard_index-p{p}.json"):
                if not os.path.exists(os.path.join(path, fname)):
                    raise CheckpointIntegrityError(
                        f"checkpoint {path}: missing {fname}")
    return manifest


class AsyncCheckpointer:
    """Background-thread checkpoint writes (the orbax-style async tier,
    SURVEY §5.4): `save` snapshots device shards to host ON THE CALLING
    THREAD (the arrays may be donated/overwritten by the very next train
    step) and hands the serialize/commit to a worker thread, hiding the
    file I/O — usually the dominant cost — behind training.

    Single-writer ordering: a save while one is in flight joins it first.
    A background failure re-raises on the next save()/wait(). Call
    wait() before reading the checkpoint back or exiting the process.
    In multi-process mode every process's save() participates in the
    commit barriers from its worker thread, so all processes must keep
    the same save cadence (same contract as the sync path).
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, path: str, tree: Pytree, step: Optional[int] = None,
             metadata: Optional[Dict] = None,
             _after: Optional[Callable[[], None]] = None) -> str:
        self.wait()
        snap = _snapshot(tree)

        def work():
            try:
                _write_snapshot(path, snap, step, metadata)
                if _after is not None:
                    _after()
            except BaseException as e:  # surfaced on next save()/wait()
                self._error = e

        self._thread = threading.Thread(target=work, daemon=True,
                                        name="ptpu-async-ckpt")
        self._thread.start()
        return path

    def wait(self) -> None:
        """Join the in-flight write; re-raise its failure, if any."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err


class CheckpointManager:
    """Rotation + resume policy over save/load (elastic-recovery story §5.3:
    restart-from-checkpoint replaces the reference's nonexistent elasticity,
    and checkpoint-notify becomes a plain directory convention).

    `async_save=True` routes saves through AsyncCheckpointer: the call
    returns once device shards are snapshotted to host and the write +
    rotation happen behind training. `wait()` (also called automatically
    by restore_latest) drains the in-flight write.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._async = AsyncCheckpointer() if async_save else None
        os.makedirs(directory, exist_ok=True)
        # Stale failure markers from a PREVIOUS crashed run would poison
        # this run's first save: _clear_markers inside save_checkpoint
        # only runs on proc 0 / for the exact path being saved, so a
        # marker a dead peer left for a DIFFERENT step (one this run
        # resumes past and never re-saves) survived until _check_failures
        # tripped over it. Managers are constructed before any save on
        # every process (the same cadence contract saves already have),
        # so sweeping at init cannot race an in-flight save's markers.
        for name in os.listdir(directory):
            if ".err-p" in name:
                try:
                    os.remove(os.path.join(directory, name))
                except OSError:
                    pass

    def save(self, tree: Pytree, step: int,
             metadata: Optional[Dict] = None) -> str:
        path = os.path.join(self.directory, f"ckpt-{step}")
        if self._async is not None:
            return self._async.save(
                path, tree, step=step, metadata=metadata,
                _after=lambda: self._post_commit(path, step))
        save_checkpoint(path, tree, step=step, metadata=metadata)
        self._post_commit(path, step)
        return path

    def _post_commit(self, path: str, step: int) -> None:
        # chaos corruption happens AFTER commit, once (proc 0), so a
        # test's torn-checkpoint scenario matches a real torn write:
        # the manifest promises content the files no longer have
        if not _is_multiprocess() or jax.process_index() == 0:
            _chaos.maybe_corrupt_checkpoint(path, step)
        self._gc()

    def wait(self) -> None:
        if self._async is not None:
            self._async.wait()

    def restore_latest(self, target: Optional[Pytree] = None,
                       shardings: Optional[Pytree] = None
                       ) -> Tuple[Optional[Pytree], Optional[int]]:
        """Restore the newest INTACT checkpoint. A torn or corrupt
        latest (integrity failure, garbled manifest/index, missing
        shards, structural mismatch with `target`) is rejected with a
        `ckpt_reject` event and the next-newest is tried — a bad disk
        costs the run a few steps of progress, never the whole job.
        Every process verifies the full file set against the same
        manifest, so a multi-host restore converges on the same step."""
        self.wait()   # an in-flight async save IS the latest checkpoint
        for step, path in list_checkpoints(self.directory):
            try:
                manifest = verify_checkpoint(path)
                return (load_checkpoint(path, target, shardings),
                        manifest.get("step"))
            except (CheckpointIntegrityError, OSError, ValueError,
                    KeyError) as e:
                resilience_event(
                    "ckpt_reject", ckpt=os.path.basename(path), step=step,
                    reason=f"{type(e).__name__}: {e}")
        return None, None

    def _gc(self) -> None:
        if _is_multiprocess() and jax.process_index() != 0:
            return
        entries = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                entries.append((int(m.group(1)), name))
            elif name.endswith(".ptmp") or ".err-p" in name:
                # Debris from a save that crashed mid-flight (each save
                # targets a fresh ckpt-{step} path, so its own retry-cleanup
                # never runs for these): a .ptmp staging dir holds a full
                # checkpoint's worth of shards and would otherwise leak
                # forever. Anything still staging belongs to the save in
                # progress right now — which is ours, already committed.
                full = os.path.join(self.directory, name)
                if os.path.isdir(full):
                    shutil.rmtree(full, ignore_errors=True)
                else:
                    try:
                        os.remove(full)
                    except OSError:
                        pass
        entries.sort()
        for _, name in entries[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)

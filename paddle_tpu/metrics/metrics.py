"""Metrics: in-graph functional metrics + stateful accumulators.

Capability-equivalent of:
- in-graph metric ops (operators/metrics/accuracy_op.cc, auc_op.cc,
  precision_recall_op.cc) → jit-safe functions below (compose into the step
  function, fused by XLA);
- Python MetricBase family (python/paddle/fluid/metrics.py:57-566:
  Precision, Recall, Accuracy, ChunkEvaluator, EditDistance, Auc,
  CompositeMetric) → host-side accumulators with the same
  update/eval/reset surface.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------ in-graph (jit)

def accuracy(logits_or_pred, label, k: int = 1):
    """Top-k accuracy (operators/metrics/accuracy_op.cc). label: [N] ints."""
    label = jnp.asarray(label)
    label = label.reshape(label.shape[0], -1)[:, 0]
    if k == 1:
        pred = jnp.argmax(logits_or_pred, axis=-1)
        return jnp.mean((pred == label).astype(jnp.float32))
    idx = jnp.argsort(logits_or_pred, axis=-1)[..., ::-1][..., :k]
    return jnp.mean(jnp.any(idx == label[:, None], axis=-1)
                    .astype(jnp.float32))


def auc(probs, label, num_thresholds: int = 200):
    """Streaming-free AUC on one batch via threshold bucketing
    (operators/metrics/auc_op.cc capability)."""
    pos_prob = probs[..., -1] if probs.ndim > 1 else probs
    label = jnp.asarray(label).reshape(-1).astype(jnp.float32)
    thresh = jnp.linspace(0.0, 1.0, num_thresholds)
    pred_pos = pos_prob[None, :] >= thresh[:, None]
    tp = jnp.sum(pred_pos * label[None, :], axis=1)
    fp = jnp.sum(pred_pos * (1 - label)[None, :], axis=1)
    pos = jnp.maximum(jnp.sum(label), 1e-6)
    neg = jnp.maximum(jnp.sum(1 - label), 1e-6)
    tpr = tp / pos
    fpr = fp / neg
    return -jnp.trapezoid(tpr, fpr)


# ----------------------------------------------------------- host-side state

class MetricBase:
    """update/eval/reset accumulator surface (metrics.py:57)."""

    def __init__(self, name: Optional[str] = None):
        self._name = name or type(self).__name__

    def reset(self) -> None:
        raise NotImplementedError

    def update(self, **kwargs) -> None:
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self) -> Dict[str, Any]:
        return {"name": self._name}


class Accuracy(MetricBase):
    """Weighted streaming accuracy (metrics.py Accuracy)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).round().astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).round().astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Streaming AUC with threshold buckets (metrics.py:459)."""

    def __init__(self, num_thresholds: int = 4095, name=None):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        pos_prob = preds[..., -1] if preds.ndim > 1 else preds
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((pos_prob * self.num_thresholds).astype(int),
                      0, self.num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self._pos[i] += 1
            else:
                self._neg[i] += 1

    def eval(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # integrate ROC from the highest threshold down
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class EditDistance(MetricBase):
    """Streaming normalized Levenshtein distance (metrics.py:316,
    operators/edit_distance_op.cc capability)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self.correct = 0

    @staticmethod
    def distance(a: Sequence, b: Sequence) -> int:
        m, n = len(a), len(b)
        dp = np.arange(n + 1)
        for i in range(1, m + 1):
            prev = dp[0]
            dp[0] = i
            for j in range(1, n + 1):
                cur = dp[j]
                dp[j] = min(dp[j] + 1, dp[j - 1] + 1,
                            prev + (a[i - 1] != b[j - 1]))
                prev = cur
        return int(dp[n])

    def update(self, hyps, refs):
        for h, r in zip(hyps, refs):
            d = self.distance(list(h), list(r))
            self.total += d / max(len(r), 1)
            self.count += 1
            self.correct += (d == 0)

    def eval(self):
        if not self.count:
            return 0.0, 0.0
        return self.total / self.count, self.correct / self.count


class ChunkEvaluator(MetricBase):
    """F1 over extracted chunks (metrics.py:219, chunk_eval_op capability).
    update() takes counts; chunk extraction lives with the tagging model."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += int(num_infer_chunks)
        self.num_label += int(num_label_chunks)
        self.num_correct += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct / self.num_infer
                     if self.num_infer else 0.0)
        recall = (self.num_correct / self.num_label
                  if self.num_label else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return precision, recall, f1


class CompositeMetric(MetricBase):
    """Bundle of metrics updated together (metrics.py:142)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics: List[MetricBase] = []

    def add_metric(self, metric: MetricBase):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class PrecisionRecall(MetricBase):
    """Multiclass streaming precision/recall/F1 (reference
    operators/metrics/precision_recall_op.cc: accumulates per-class
    TP/FP/FN and reports macro + micro averages)."""

    def __init__(self, num_classes: int, name=None):
        super().__init__(name)
        self.num_classes = num_classes
        self.reset()

    def reset(self):
        self.tp = np.zeros(self.num_classes, np.int64)
        self.fp = np.zeros(self.num_classes, np.int64)
        self.fn = np.zeros(self.num_classes, np.int64)

    def update(self, preds, labels):
        """preds: [N] predicted class ids (or [N, C] scores); labels [N]."""
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds.argmax(-1)
        preds = preds.astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        for c in range(self.num_classes):
            self.tp[c] += int(np.sum((preds == c) & (labels == c)))
            self.fp[c] += int(np.sum((preds == c) & (labels != c)))
            self.fn[c] += int(np.sum((preds != c) & (labels == c)))

    def eval(self):
        """Returns dict with macro/micro precision, recall, f1."""
        with np.errstate(divide="ignore", invalid="ignore"):
            prec = np.where(self.tp + self.fp > 0,
                            self.tp / np.maximum(self.tp + self.fp, 1), 0.0)
            rec = np.where(self.tp + self.fn > 0,
                           self.tp / np.maximum(self.tp + self.fn, 1), 0.0)
        f1 = np.where(prec + rec > 0, 2 * prec * rec
                      / np.maximum(prec + rec, 1e-12), 0.0)
        tp, fp, fn = self.tp.sum(), self.fp.sum(), self.fn.sum()
        micro_p = tp / max(tp + fp, 1)
        micro_r = tp / max(tp + fn, 1)
        micro_f = (2 * micro_p * micro_r / max(micro_p + micro_r, 1e-12)
                   if micro_p + micro_r else 0.0)
        return {"macro_precision": float(prec.mean()),
                "macro_recall": float(rec.mean()),
                "macro_f1": float(f1.mean()),
                "micro_precision": float(micro_p),
                "micro_recall": float(micro_r),
                "micro_f1": float(micro_f)}


class DetectionMAP(MetricBase):
    """Mean average precision for detection (reference metrics.py:566
    DetectionMAP + operators/detection_map_op.cc).

    update() takes per-image detections [[label, score, x1, y1, x2, y2],
    ...] and ground truth [[label, x1, y1, x2, y2], ...]; eval() returns
    mAP over classes using 11-point or integral interpolation.
    """

    def __init__(self, overlap_threshold: float = 0.5,
                 ap_version: str = "integral",
                 evaluate_difficult: bool = False, name=None):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError(f"unknown ap_version: {ap_version!r}")
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.evaluate_difficult = evaluate_difficult
        self.reset()

    def reset(self):
        # per class: list of (score, is_tp); and gt count
        self._scored: Dict[int, list] = {}
        self._npos: Dict[int, int] = {}

    @staticmethod
    def _iou(a, b):
        ax1, ay1, ax2, ay2 = a
        bx1, by1, bx2, by2 = b
        iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
        ih = max(0.0, min(ay2, by2) - max(ay1, by1))
        inter = iw * ih
        ua = max((ax2 - ax1) * (ay2 - ay1), 0) + \
            max((bx2 - bx1) * (by2 - by1), 0) - inter
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gts, difficult=None):
        detections = [list(map(float, d)) for d in np.asarray(detections)
                      .reshape(-1, 6)] if len(detections) else []
        gts = [list(map(float, g)) for g in np.asarray(gts).reshape(-1, 5)] \
            if len(gts) else []
        difficult = ([bool(d) for d in difficult] if difficult is not None
                     else [False] * len(gts))
        for (glabel, *_), diff in zip(gts, difficult):
            if self.evaluate_difficult or not diff:
                self._npos[int(glabel)] = self._npos.get(int(glabel), 0) + 1
        used = [False] * len(gts)
        for label, score, x1, y1, x2, y2 in sorted(
                detections, key=lambda d: -d[1]):
            label = int(label)
            if label < 0:
                continue
            best, best_j = 0.0, -1
            for j, (glabel, gx1, gy1, gx2, gy2) in enumerate(gts):
                if int(glabel) != label or used[j]:
                    continue
                ov = self._iou((x1, y1, x2, y2), (gx1, gy1, gx2, gy2))
                if ov > best:
                    best, best_j = ov, j
            tp = best >= self.overlap_threshold and best_j >= 0
            if tp and not (difficult[best_j] and not self.evaluate_difficult):
                used[best_j] = True
                self._scored.setdefault(label, []).append((score, 1))
            elif tp:
                pass  # difficult match: neither tp nor fp
            else:
                self._scored.setdefault(label, []).append((score, 0))

    def eval(self):
        aps = []
        for label, npos in self._npos.items():
            scored = sorted(self._scored.get(label, []), key=lambda s: -s[0])
            if not scored or npos == 0:
                aps.append(0.0)
                continue
            tps = np.cumsum([t for _, t in scored])
            fps = np.cumsum([1 - t for _, t in scored])
            rec = tps / npos
            prec = tps / np.maximum(tps + fps, 1)
            if self.ap_version == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if np.any(rec >= t) else 0.0
                    ap += p / 11
            else:
                # integral: sum precision deltas at each recall step
                mrec = np.concatenate([[0.0], rec])
                ap = float(np.sum((mrec[1:] - mrec[:-1]) * prec))
            aps.append(float(ap))
        return float(np.mean(aps)) if aps else 0.0

from paddle_tpu.metrics.metrics import (
    Accuracy, Auc, ChunkEvaluator, CompositeMetric, EditDistance, MetricBase,
    Precision, Recall, accuracy, auc,
)

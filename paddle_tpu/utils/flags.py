"""Global flag registry with environment-variable override.

Capability-equivalent of the reference's gflags system (108 DEFINE_* flags
surfaced to Python via `FLAGS_*` env vars; reference:
python/paddle/fluid/__init__.py:126-165, paddle/fluid/platform/init.cc:40).

TPU-first design: flags are plain Python values resolved once at import from
`FLAGS_<name>` environment variables, with typed definitions and a process-wide
singleton registry. No C++ gflags needed — XLA's own tuning knobs are reached
through XLA_FLAGS which we deliberately do not wrap.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _FlagDef:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class FlagRegistry:
    """Process-wide typed flag registry. Thread-safe."""

    def __init__(self) -> None:
        self._defs: Dict[str, _FlagDef] = {}
        self._values: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help: str = "",
               parser: Optional[Callable[[str], Any]] = None) -> None:
        if parser is None:
            if isinstance(default, bool):
                parser = _parse_bool
            elif isinstance(default, int):
                parser = int
            elif isinstance(default, float):
                parser = float
            else:
                parser = str
        with self._lock:
            if name in self._defs:
                return  # idempotent re-import
            self._defs[name] = _FlagDef(name, default, parser, help)
            env = os.environ.get(f"FLAGS_{name}")
            self._values[name] = parser(env) if env is not None else default

    def get(self, name: str) -> Any:
        with self._lock:
            if name not in self._values:
                raise KeyError(f"undefined flag: {name}")
            return self._values[name]

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._defs:
                raise KeyError(f"undefined flag: {name}")
            self._values[name] = value

    def all(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._values)


FLAGS = FlagRegistry()

# Core flags mirroring the reference's capability surface.
FLAGS.define("check_nan_inf", False,
             "Check outputs of every op for NaN/Inf (debug). "
             "Analog of reference FLAGS_check_nan_inf.")
FLAGS.define("deterministic", False,
             "Force deterministic execution (seeded RNG streams, "
             "XLA deterministic reductions where possible). Analog of "
             "FLAGS_cudnn_deterministic/FLAGS_cpu_deterministic.")
FLAGS.define("executor_cache_capacity", 256,
             "Max compiled (program, signature) entries an Executor "
             "retains (LRU eviction). <=0 disables the bound. Analog of "
             "the reference's executor program-cache, which grows "
             "unboundedly (executor.py prepared-context cache).", int)
FLAGS.define("rpc_deadline", 180000,
             "Deadline (ms) for control-plane RPCs (checkpoint notify etc.).")
FLAGS.define("profile_dir", "",
             "If set, enable jax.profiler traces into this directory.")
FLAGS.define("benchmark", False, "Print per-step timing in trainers.")
FLAGS.define("allocator_strategy", "default",
             "Kept for config parity; XLA owns device memory on TPU.")
FLAGS.define("eager_delete_tensor_gb", 0.0,
             "Kept for config parity; XLA buffer liveness handles GC.")
FLAGS.define("fraction_of_gpu_memory_to_use", 0.92,
             "Kept for config parity with the reference flag surface.")


def get_flags() -> Dict[str, Any]:
    return FLAGS.all()


def set_flags(d: Dict[str, Any]) -> None:
    for k, v in d.items():
        FLAGS.set(k, v)

"""Observability utilities: device memory stats, HLO dumps, module trees.

Capability-equivalent of the reference's introspection surface:
- memory_stats ≈ paddle.fluid.core get_mem_usage
  (/root/reference/paddle/fluid/pybind/pybind.cc:131) and
  contrib/memory_usage_calc.py;
- dump_hlo ≈ Program.to_string / debugger.draw_block_graphviz
  (/root/reference/python/paddle/fluid/framework.py:406,
  debugger.py) — here the "program" is the XLA computation, so the dump
  tiers are jaxpr, StableHLO, and post-optimization HLO;
- module_tree ≈ the Program/Block pretty printer + net_drawer.py.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax

from paddle_tpu.core.module import Module


def memory_stats(device=None) -> Dict[str, Any]:
    """Per-device live-buffer statistics.

    Returns {device, bytes_in_use, peak_bytes_in_use, num_allocs, ...} from
    the runtime allocator when the backend exposes them (TPU does), falling
    back to a live-buffer walk on CPU. ≈ reference get_mem_usage
    (pybind.cc:131) / memory_usage_calc.py.
    """
    devs = [device] if device is not None else jax.local_devices()
    out = {}
    for d in devs:
        stats: Dict[str, Any]
        try:
            stats = dict(d.memory_stats() or {})
        except Exception:
            stats = {}
        if not stats:
            live = [b for b in jax.live_arrays() if d in b.devices()]
            stats = {
                "bytes_in_use": sum(int(b.nbytes) for b in live),
                "num_live_buffers": len(live),
                "source": "live_arrays_walk",
            }
        out[str(d)] = stats
    return out if device is None else out[str(devs[0])]


def executor_cache_stats():
    """Compile-cache stats over all live Executors (entries/hits/misses/
    evictions per cache) — the host-side complement to memory_stats'
    device-allocator numbers. Kept separate so memory_stats' return stays
    a pure device→stats mapping."""
    from paddle_tpu.core.executor import executor_cache_stats as _stats
    return _stats()


def dump_hlo(fn: Callable, *args, stage: str = "stablehlo",
             static_argnums=(), **kwargs) -> str:
    """Text dump of the compiled form of `fn(*args)`.

    stage: "jaxpr" (traced jaxpr), "stablehlo" (lowered portable IR), or
    "optimized" (backend-optimized HLO — what actually runs, post-fusion;
    the analog of inspecting the reference's fused graph after its pass
    pipeline, ir/graph_viz_pass.cc).
    """
    if stage == "jaxpr":
        return str(jax.make_jaxpr(fn, static_argnums=static_argnums)(
            *args, **kwargs))
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(
        *args, **kwargs)
    if stage == "stablehlo":
        return lowered.as_text()
    if stage == "optimized":
        return lowered.compile().as_text()
    raise ValueError(f"unknown stage {stage!r}; "
                     "use jaxpr | stablehlo | optimized")


def op_census(fn: Callable, *args, stage: str = "optimized",
              static_argnums=(), **kwargs) -> Dict[str, int]:
    """Op-type frequency table of the compiled program, most frequent
    first (≈ the reference's benchmark/op_frequence.py op census — there
    over ProgramDesc ops, here over the HLO/StableHLO that actually runs;
    useful for spotting fusion regressions or unexpected op explosions).
    """
    return census_from_text(dump_hlo(fn, *args, stage=stage,
                                     static_argnums=static_argnums,
                                     **kwargs))


def census_from_text(text: str) -> Dict[str, int]:
    """op_census over already-lowered HLO/StableHLO text (e.g. a
    Compiled.as_text() the caller is holding anyway)."""
    import re

    counts: Dict[str, int] = {}
    # HLO: "%name = <type> opcode(...)" where <type> may be a tuple
    # "(s32[], f32[8,8]{1,0:T(8,128)})" — the opcode is the first
    # lowercase identifier directly followed by "(" after the "=" (tile
    # annotations like T(8,128) start uppercase, so they don't match).
    hlo_op = re.compile(r"=\s+[^=]*?\s([a-z][a-z0-9_\-]*)\(")
    for line in text.splitlines():
        line = line.strip()
        op = None
        if "stablehlo." in line or "mhlo." in line:
            # StableHLO (MLIR): "%0 = stablehlo.opcode ..."
            for tok in line.replace("(", " ").split():
                if tok.startswith(("stablehlo.", "mhlo.")):
                    op = tok.split(".", 1)[1].rstrip('"')
                    break
        elif "= " in line and not line.startswith(("HloModule", "ENTRY",
                                                   "//", "#")):
            m = hlo_op.search(line)
            if m:
                op = m.group(1)
        if op:
            counts[op] = counts.get(op, 0) + 1
    return dict(sorted(counts.items(), key=lambda kv: -kv[1]))


def _unwrap_params(variables: Optional[Dict]) -> Dict:
    """Accept a full variables dict or a bare params tree."""
    return (variables or {}).get("params", variables) or {}


def _count_params(p: Any) -> int:
    return sum(getattr(v, "size", 0) for v in jax.tree.leaves(
        p if isinstance(p, dict) else {}))


def module_tree(module: Module, variables: Optional[Dict] = None,
                _name: str = "", _indent: int = 0) -> str:
    """Pretty-print a module hierarchy with parameter shapes/counts.

    ≈ the reference Program printer (framework.py:406 to_string) and
    debugger.py's block dump, at module granularity.
    """
    lines: List[str] = []
    params = _unwrap_params(variables)

    def walk(m: Module, name: str, p: Any, indent: int):
        own = {k: v for k, v in (p or {}).items()
               if not isinstance(v, dict)} if isinstance(p, dict) else {}
        n_params = _count_params(p)
        head = "  " * indent + (name or type(m).__name__)
        desc = type(m).__name__
        extra = f" params={n_params:,}" if n_params else ""
        lines.append(f"{head}: {desc}{extra}")
        for k, v in own.items():
            shape = tuple(getattr(v, "shape", ()))
            lines.append("  " * (indent + 1) + f".{k} {shape}")
        for cname, child in m.children().items():
            cp = p.get(cname) if isinstance(p, dict) else None
            walk(child, cname, cp, indent + 1)

    walk(module, _name, params, _indent)
    return "\n".join(lines)


def module_tree_dot(module: Module, variables: Optional[Dict] = None) -> str:
    """Graphviz dot source for a module hierarchy.

    ≈ the reference's graph visualizers (ir/graph_viz_pass.cc dot dump,
    python net_drawer.py / debugger.draw_block_graphviz): render with
    `dot -Tpng` or any online viewer. Node labels carry the module class
    and parameter counts.
    """
    params = _unwrap_params(variables)
    lines = ["digraph module_tree {",
             '  node [shape=box, fontname="monospace", fontsize=10];']
    counter = [0]

    def walk(m: Module, name: str, p: Any) -> str:
        nid = f"n{counter[0]}"
        counter[0] += 1
        n_params = _count_params(p)
        label = f"{name or type(m).__name__}\\n{type(m).__name__}"
        if n_params:
            label += f"\\nparams={n_params:,}"
        lines.append(f'  {nid} [label="{label}"];')
        for cname, child in m.children().items():
            cp = p.get(cname) if isinstance(p, dict) else None
            cid = walk(child, cname, cp)
            lines.append(f"  {nid} -> {cid};")
        return nid

    walk(module, "", params)
    lines.append("}")
    return "\n".join(lines)

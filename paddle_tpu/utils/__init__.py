from paddle_tpu.utils.flags import FLAGS
from paddle_tpu.utils import log

from paddle_tpu.utils.flags import FLAGS
from paddle_tpu.utils import log
from paddle_tpu.utils.debug import dump_hlo, memory_stats, module_tree

"""Leveled logging (VLOG-style) + the structured event streams.

Analog of the reference's glog `VLOG(n)` + InitGLOG (platform/init.cc:165)
and pretty_log (string/pretty_log.h). Verbosity comes from FLAGS_v /
GLOG_v, re-read PER CALL (and overridable at runtime via
`set_verbosity`), so tests and operators can raise it mid-run —
the old import-time read froze the level for the process lifetime.

Also hosts the unified EVENT EMITTER: every stream (`resilience`,
`serve`, `obs`) emits single-line JSON records on STDOUT
(`{"evt": "preempt", ...}`) so subprocess cluster tests — which only
see a worker's captured stdout — and log scrapers consume one format.
Every record is stamped with a monotonic `ts` (seconds,
time.monotonic — comparable within a process, immune to wall-clock
steps) and a per-stream `seq`, so post-hoc latency analysis and
loss-detection work from logs alone. `evt` always sorts first, so a
grep for '{"evt": "rollback"' keeps working.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

_LOGGER = logging.getLogger("paddle_tpu")
if not _LOGGER.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(levelname).1s %(asctime)s paddle_tpu %(message)s", "%H:%M:%S"))
    _LOGGER.addHandler(_h)
    _LOGGER.setLevel(logging.INFO)
    _LOGGER.propagate = False

# runtime override; None defers to the env (read per call)
_VERBOSITY_OVERRIDE: Optional[int] = None


def get_verbosity() -> int:
    if _VERBOSITY_OVERRIDE is not None:
        return _VERBOSITY_OVERRIDE
    try:
        return int(os.environ.get("FLAGS_v", os.environ.get("GLOG_v", "0")))
    except ValueError:
        return 0


def set_verbosity(level: Optional[int]) -> Optional[int]:
    """Set the VLOG threshold at runtime (None reverts to the env
    vars). Returns the previous override so callers can restore it."""
    global _VERBOSITY_OVERRIDE
    prev = _VERBOSITY_OVERRIDE
    _VERBOSITY_OVERRIDE = None if level is None else int(level)
    return prev


def vlog(level: int, msg: str, *args) -> None:
    if level <= get_verbosity():
        _LOGGER.info(msg, *args)


def info(msg: str, *args) -> None:
    _LOGGER.info(msg, *args)


def warning(msg: str, *args) -> None:
    _LOGGER.warning(msg, *args)


def error(msg: str, *args) -> None:
    _LOGGER.error(msg, *args)


# -- unified event streams ---------------------------------------------------

class _StdoutHandler(logging.Handler):
    """Writes to whatever sys.stdout is AT EMIT TIME (not at import):
    pytest's capsys and subprocess pipes both swap sys.stdout, and a
    handler bound to the import-time stream would bypass them."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            stream = sys.stdout
            stream.write(record.getMessage() + "\n")
            stream.flush()
        except Exception:
            pass  # logging must never take the run down


_STREAMS: Dict[str, logging.Logger] = {}
_SEQ: Dict[str, int] = {}
_SEQ_LOCK = threading.Lock()

# In-process event taps: callables invoked with (stream, record) for
# every emitted event AFTER it hits stdout. The flight recorder
# (obs/flightrec.py) rides this to keep a postmortem ring of recent
# serve/resilience events without touching any emit site. Taps run
# OUTSIDE _SEQ_LOCK and exceptions are swallowed — a broken tap must
# never take the run down or reorder sequence numbers.
_TAPS: List[Callable[[str, dict], None]] = []   # guarded-by: _TAPS_LOCK
_TAPS_LOCK = threading.Lock()


def add_event_tap(fn: Callable[[str, dict], None]) -> None:
    """Register a tap called with (stream, record) for every event."""
    with _TAPS_LOCK:
        if fn not in _TAPS:
            _TAPS.append(fn)


def remove_event_tap(fn: Callable[[str, dict], None]) -> None:
    """Unregister a tap; unknown taps are ignored."""
    with _TAPS_LOCK:
        try:
            _TAPS.remove(fn)
        except ValueError:
            pass


def _stream_logger(stream: str) -> logging.Logger:
    lg = _STREAMS.get(stream)
    if lg is None:
        lg = logging.getLogger(f"paddle_tpu.{stream}")
        if not lg.handlers:
            lg.addHandler(_StdoutHandler())
            lg.setLevel(logging.INFO)
            lg.propagate = False
        _STREAMS[stream] = lg
    return lg


def emit_event(stream: str, evt: str, **fields) -> dict:
    """One single-line JSON record on stdout; returns the dict.

    "evt" sorts first so a grep for '{"evt": "rollback"' works;
    `ts` (monotonic seconds) and `seq` (per-stream, 0-based,
    gap-free) are stamped LAST so existing prefix-greps and field
    consumers stay valid; non-JSON-native values go through str().
    """
    with _SEQ_LOCK:
        seq = _SEQ.get(stream, 0)
        _SEQ[stream] = seq + 1
    rec = {"evt": evt, **fields}
    rec["ts"] = round(time.monotonic(), 6)
    rec["seq"] = seq
    _stream_logger(stream).info(
        json.dumps(rec, sort_keys=False, default=str))
    with _TAPS_LOCK:
        taps = list(_TAPS)
    for tap in taps:
        try:
            tap(stream, rec)
        except Exception:
            pass  # a broken tap must never take the run down
    return rec


def resilience_event(evt: str, **fields) -> dict:
    """Resilience stream (logger `paddle_tpu.resilience`). Canonical
    events: `preempt`, `ckpt_reject`, `bad_step_skip`, `rollback`,
    `retry`, `chaos_inject`, `hang`."""
    return emit_event("resilience", evt, **fields)


def serve_event(evt: str, **fields) -> dict:
    """Serve stream (logger `paddle_tpu.serve`, ENGINE.md §events).
    Canonical events: `serve_admit` (queue depth at admission),
    `serve_prefill` / `serve_decode` (per-step batch shape + KV-cache
    occupancy), `serve_preempt` (pool exhaustion eviction),
    `serve_done` (per-request TTFT ms, decode tokens/sec, token
    count)."""
    return emit_event("serve", evt, **fields)


def obs_event(evt: str, **fields) -> dict:
    """Telemetry stream (logger `paddle_tpu.obs`, OBSERVABILITY.md).
    Canonical events: `obs_snapshot` (periodic metrics-registry dump,
    obs/metrics.py Snapshotter)."""
    return emit_event("obs", evt, **fields)


class scoped_timer:
    """`with scoped_timer("phase"):` — logs wall time of the block at VLOG(1)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        vlog(1, "%s took %.3fs", self.name, time.perf_counter() - self.t0)
        return False

"""Leveled logging (VLOG-style) for the framework.

Analog of the reference's glog `VLOG(n)` + InitGLOG (platform/init.cc:165)
and pretty_log (string/pretty_log.h). Verbosity from FLAGS_v / GLOG_v env.

Also hosts the `resilience` event stream: single-line JSON records on
STDOUT (`{"evt": "preempt", ...}`) so subprocess cluster tests — which
only see a worker's captured stdout — can assert on recovery behavior
(preemption, checkpoint rejection, bad-step skips, rollbacks, retries)
without any side channel.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_LOGGER = logging.getLogger("paddle_tpu")
if not _LOGGER.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(levelname).1s %(asctime)s paddle_tpu %(message)s", "%H:%M:%S"))
    _LOGGER.addHandler(_h)
    _LOGGER.setLevel(logging.INFO)
    _LOGGER.propagate = False

_VERBOSITY = int(os.environ.get("FLAGS_v", os.environ.get("GLOG_v", "0")))


def vlog(level: int, msg: str, *args) -> None:
    if level <= _VERBOSITY:
        _LOGGER.info(msg, *args)


def info(msg: str, *args) -> None:
    _LOGGER.info(msg, *args)


def warning(msg: str, *args) -> None:
    _LOGGER.warning(msg, *args)


def error(msg: str, *args) -> None:
    _LOGGER.error(msg, *args)


# -- resilience event stream ------------------------------------------------

class _StdoutHandler(logging.Handler):
    """Writes to whatever sys.stdout is AT EMIT TIME (not at import):
    pytest's capsys and subprocess pipes both swap sys.stdout, and a
    handler bound to the import-time stream would bypass them."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            stream = sys.stdout
            stream.write(record.getMessage() + "\n")
            stream.flush()
        except Exception:
            pass  # logging must never take the run down


_RESILIENCE = logging.getLogger("paddle_tpu.resilience")
if not _RESILIENCE.handlers:
    _RESILIENCE.addHandler(_StdoutHandler())
    _RESILIENCE.setLevel(logging.INFO)
    _RESILIENCE.propagate = False


def resilience_event(evt: str, **fields) -> dict:
    """Emit one single-line JSON record on stdout and return it.

    Canonical events: `preempt`, `ckpt_reject`, `bad_step_skip`,
    `rollback`, `retry`, `chaos_inject`, `hang`. "evt" sorts first so a
    grep for '{"evt": "rollback"' works; non-JSON-native values go
    through str().
    """
    rec = {"evt": evt, **fields}
    _RESILIENCE.info(json.dumps(rec, sort_keys=False, default=str))
    return rec


# -- serve event stream ------------------------------------------------------
# The online inference engine's observability channel (ENGINE.md §events):
# same single-line-JSON-on-stdout convention as the resilience stream so
# serve_bench / log scrapers / tests all consume one format.

_SERVE = logging.getLogger("paddle_tpu.serve")
if not _SERVE.handlers:
    _SERVE.addHandler(_StdoutHandler())
    _SERVE.setLevel(logging.INFO)
    _SERVE.propagate = False


def serve_event(evt: str, **fields) -> dict:
    """One single-line JSON serve record on stdout; returns the dict.

    Canonical events: `serve_admit` (queue depth at admission),
    `serve_prefill` / `serve_decode` (per-step batch shape + KV-cache
    occupancy), `serve_preempt` (pool exhaustion eviction),
    `serve_done` (per-request TTFT ms, decode tokens/sec, token count).
    """
    rec = {"evt": evt, **fields}
    _SERVE.info(json.dumps(rec, sort_keys=False, default=str))
    return rec


class scoped_timer:
    """`with scoped_timer("phase"):` — logs wall time of the block at VLOG(1)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        vlog(1, "%s took %.3fs", self.name, time.perf_counter() - self.t0)
        return False

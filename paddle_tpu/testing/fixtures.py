"""Shared saved-model fixture helpers.

The export-then-verify dance (save_inference_model → InferencePredictor
→ assert served == direct apply) was growing copies in
tests/test_serving.py, examples/quantize_int8_serve.py, and the engine
tests; this is the single implementation.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np


def export_servable(path: str, model, variables,
                    example_inputs: Sequence[Any],
                    input_names: Optional[Sequence[str]] = None,
                    serve_meta: Optional[dict] = None,
                    verify: bool = False) -> str:
    """Export `model` as a servable directory at `path`; with
    verify=True, round-trip the example inputs through an
    InferencePredictor and assert the served outputs match the direct
    apply() — the exported artifact provably computes the same function.
    Returns `path`."""
    import jax.numpy as jnp

    from paddle_tpu.io.inference import (InferencePredictor,
                                         save_inference_model)

    save_inference_model(path, model, variables, example_inputs,
                         input_names=input_names, serve_meta=serve_meta)
    if verify:
        served = InferencePredictor(path).run(
            [np.asarray(x) for x in example_inputs])[0]
        direct = np.asarray(model.apply(
            variables, *[jnp.asarray(x) for x in example_inputs],
            training=False))
        np.testing.assert_allclose(served, direct, rtol=1e-6, atol=1e-6)
    return path


def export_causal_lm(path: str, vocab: int = 61, model_dim: int = 16,
                     num_heads: int = 2, num_layers: int = 2,
                     ffn_dim: int = 32, max_len: int = 64,
                     num_kv_heads: Optional[int] = None, seed: int = 0):
    """Tiny servable CausalLM for engine tests/benches: init with a
    fixed seed, export with the manifest `serve` block, return
    (path, model, variables)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.engine.engine import serve_metadata
    from paddle_tpu.io.inference import save_inference_model
    from paddle_tpu.models.transformer import CausalLM

    model = CausalLM(vocab=vocab, model_dim=model_dim, num_heads=num_heads,
                     num_layers=num_layers, ffn_dim=ffn_dim, dropout=0.0,
                     max_len=max_len, num_kv_heads=num_kv_heads)
    variables = model.init(jax.random.PRNGKey(seed),
                           jnp.zeros((1, 4), jnp.int32))
    save_inference_model(  # export the forward; engine rebuilds from serve
        path, model, variables, [jnp.zeros((1, 4), jnp.int32)],
        input_names=["tokens"], serve_meta=serve_metadata(model))
    return path, model, variables

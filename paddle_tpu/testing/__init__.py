"""Test harnesses (numeric-gradient OpTest; reference op_test.py:43,414)."""

from paddle_tpu.testing.fixtures import export_causal_lm, export_servable
from paddle_tpu.testing.op_test import check_grad, check_output, numeric_grad

__all__ = ["check_grad", "check_output", "numeric_grad",
           "export_servable", "export_causal_lm"]

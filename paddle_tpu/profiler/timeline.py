"""Timeline merge tool (≈ /root/reference/tools/timeline.py).

The reference converts profiler.proto dumps from several trainers into one
Chrome trace (`--profile_path trainer1=f1,trainer2=f2`, timeline.py:25-36).
Here profiles are the Chrome-trace jsons written by
`profiler.save_profile` (host spans) — `merge_profiles` re-pids each
process's events into a single trace viewable in chrome://tracing or
perfetto. Device traces (jax.profiler trace dirs) are already
TensorBoard-mergeable by pointing TensorBoard at the parent logdir.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


class Timeline:
    """Accumulates events from named profiles into one Chrome trace."""

    def __init__(self):
        self._events: List[dict] = []
        self._pid = 0

    def add_profile(self, name: str, profile: dict) -> None:
        pid = self._pid
        self._pid += 1
        self._events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
        for ev in profile.get("traceEvents", []):
            # the source's process_name is superseded by `name`, but
            # thread_name rows (e.g. the request tracer's "req N"
            # labels) must survive the merge
            if ev.get("ph") == "M" and ev.get("name") != "thread_name":
                continue
            ev = dict(ev)
            ev["pid"] = pid
            self._events.append(ev)

    def trace(self) -> dict:
        return {"traceEvents": self._events, "displayTimeUnit": "ms"}

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.trace(), f)


def merge_profiles(profile_paths: Dict[str, str],
                   output_path: Optional[str] = None) -> dict:
    """Merge `{process_name: chrome_trace_json_path}` into one trace.

    ≈ timeline.py's `--profile_path trainer1=f1,trainer2=f2` CLI.
    """
    tl = Timeline()
    for name, path in profile_paths.items():
        with open(path) as f:
            tl.add_profile(name, json.load(f))
    if output_path:
        tl.save(output_path)
    return tl.trace()


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    p = argparse.ArgumentParser(description="merge paddle_tpu profiles")
    p.add_argument("--profile_path", required=True,
                   help="name1=path1,name2=path2,...")
    p.add_argument("--timeline_path", required=True)
    args = p.parse_args(argv)
    paths = dict(kv.split("=", 1) for kv in args.profile_path.split(","))
    merge_profiles(paths, args.timeline_path)


if __name__ == "__main__":
    main()

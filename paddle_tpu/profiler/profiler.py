"""Event profiler + device-trace wrappers.

Host tier ≈ reference RecordEvent/EnableProfiler
(/root/reference/paddle/fluid/platform/profiler.h:72,117-126; tables
printed by DisableProfiler with a sort key). Device tier wraps
jax.profiler (≈ CUPTI device tracer, platform/device_tracer.h:39) — the
captured trace dir is TensorBoard/perfetto-loadable.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
from typing import Dict, List, Optional

import jax

from paddle_tpu.utils.log import vlog

_lock = threading.Lock()
_events: List[dict] = []          # completed spans: name/ts/dur/tid (us)
_enabled = False
_trace_dir: Optional[str] = None
# Wall-clock anchor for the monotonic counter: timestamps are epoch-based
# microseconds so profiles from different processes merge on a common
# timeline (tools/timeline.py multi-trainer merge needs comparable ts).
_EPOCH_NS = time.time_ns() - time.perf_counter_ns()


def now_us() -> float:
    """Epoch-anchored monotonic microseconds — the shared timestamp
    base for host spans AND the request tracer (obs/tracing.py), so
    their Chrome traces merge on one timeline."""
    return (_EPOCH_NS + time.perf_counter_ns()) / 1e3


_now_us = now_us


class RecordEvent:
    """RAII host-side span (≈ platform/profiler.h:72 RecordEvent).

    Usable as a context manager. Spans are recorded only while the
    profiler is enabled (between start_profiler and stop_profiler) —
    matching the reference's g_state gate.
    """

    def __init__(self, name: str):
        self.name = name
        self._start = 0.0

    def __enter__(self):
        self._start = _now_us()
        return self

    def __exit__(self, *exc):
        if not _enabled:
            return False
        end = _now_us()
        with _lock:
            _events.append({
                "name": self.name,
                "ts": self._start,
                "dur": end - self._start,
                "tid": threading.get_ident() & 0xFFFF,
            })
        return False


record_event = RecordEvent


def record_function(name: Optional[str] = None):
    """Decorator wrapping a function body in a RecordEvent span."""

    def deco(fn):
        ev_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with RecordEvent(ev_name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the DEVICE trace (jax.profiler.TraceAnnotation) and
    the host event list — the named_scope analog of the reference's
    RecordEvent-around-kernel-launch."""
    with jax.profiler.TraceAnnotation(name), RecordEvent(name):
        yield


def start_profiler(trace_dir: Optional[str] = None) -> None:
    """Enable host-span recording; if trace_dir is given, also start a
    jax.profiler device trace into it (≈ EnableProfiler(kAll))."""
    global _enabled, _trace_dir
    with _lock:
        _events.clear()
    _enabled = True
    _trace_dir = trace_dir
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
    vlog(1, f"profiler started (trace_dir={trace_dir})")


def stop_profiler(sorted_key: str = "total",
                  profile_path: Optional[str] = None,
                  print_table: bool = True) -> List[dict]:
    """Stop profiling; print the aggregated op-time table and optionally
    dump the raw events as a Chrome-trace json (≈ DisableProfiler's
    sorted table + profiler.proto dump, profiler.h:117-126).

    sorted_key in {"total", "calls", "max", "min", "ave"}.
    Returns the aggregated rows.
    """
    global _enabled, _trace_dir
    _enabled = False
    if _trace_dir:
        jax.profiler.stop_trace()
        _trace_dir = None
    rows = profile_table(sorted_key)
    if print_table and rows:
        print(format_table(rows, sorted_key))
    if profile_path:
        save_profile(profile_path)
    return rows


def reset_profiler() -> None:
    with _lock:
        _events.clear()


def get_events() -> List[dict]:
    with _lock:
        return list(_events)


@contextlib.contextmanager
def profiler(sorted_key: str = "total", profile_path: Optional[str] = None,
             trace_dir: Optional[str] = None):
    """Context manager form (≈ fluid.profiler.profiler)."""
    start_profiler(trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key=sorted_key, profile_path=profile_path)


def profile_table(sorted_key: str = "total") -> List[dict]:
    """Aggregate recorded spans into per-name stats rows."""
    agg: Dict[str, dict] = {}
    for ev in get_events():
        row = agg.setdefault(ev["name"], {
            "name": ev["name"], "calls": 0, "total": 0.0,
            "min": float("inf"), "max": 0.0,
        })
        row["calls"] += 1
        row["total"] += ev["dur"]
        row["min"] = min(row["min"], ev["dur"])
        row["max"] = max(row["max"], ev["dur"])
    rows = []
    grand_total = sum(r["total"] for r in agg.values()) or 1.0
    for row in agg.values():
        row["ave"] = row["total"] / row["calls"]
        row["ratio"] = row["total"] / grand_total
        rows.append(row)
    key = {"total": "total", "calls": "calls", "max": "max", "min": "min",
           "ave": "ave"}.get(sorted_key, "total")
    rows.sort(key=lambda r: r[key], reverse=True)
    return rows


def format_table(rows: List[dict], sorted_key: str = "total") -> str:
    lines = [
        f"------------------->  Profiling Report (sorted by {sorted_key})"
        "  <-------------------",
        f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}{'Min(us)':>12}"
        f"{'Max(us)':>12}{'Ave(us)':>12}{'Ratio':>8}",
    ]
    for r in rows:
        lines.append(
            f"{r['name'][:39]:<40}{r['calls']:>8}{r['total']:>14.1f}"
            f"{r['min']:>12.1f}{r['max']:>12.1f}{r['ave']:>12.1f}"
            f"{r['ratio']:>8.3f}")
    return "\n".join(lines)


def events_to_chrome_trace(events: Optional[List[dict]] = None,
                           pid: int = 0) -> dict:
    """Render host spans as Chrome trace format (chrome://tracing /
    perfetto), ≈ tools/timeline.py:36 _ChromeTraceFormatter."""
    events = get_events() if events is None else events
    trace = [{
        "name": ev["name"], "ph": "X", "cat": "host",
        "ts": ev["ts"], "dur": ev["dur"], "pid": pid, "tid": ev["tid"],
        "args": {},
    } for ev in events]
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"process {pid}"}}]
    return {"traceEvents": meta + trace, "displayTimeUnit": "ms"}


def save_profile(path: str, pid: int = 0) -> None:
    """Dump recorded host events as a Chrome-trace json file."""
    with open(path, "w") as f:
        json.dump(events_to_chrome_trace(pid=pid), f)
    vlog(1, f"profile written to {path}")

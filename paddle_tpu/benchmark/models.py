"""Benchmark model zoo — mirrors /root/reference/benchmark/fluid/models/
(mnist, vgg, resnet, se_resnext, machine_translation, stacked_dynamic_lstm)
plus the CTR model (dist_ctr capability) and the extra nets the reference
publishes baselines for (AlexNet, GoogLeNet: benchmark/README.md,
IntelOptimizedPaddle.md).

Each spec builds (trainer, state, batch) on synthetic data with the
reference's benchmark shapes, then hands off to harness.bench_trainer.
Published reference numbers ride along as `baseline` so every result
carries a vs_baseline ratio.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.benchmark.harness import (BenchResult, bench_trainer,
                                           chain_k)
from paddle_tpu.core.executor import Trainer, supervised_loss
from paddle_tpu.metrics import accuracy
from paddle_tpu.ops import functional as F
from paddle_tpu.optim.optimizer import Adam, Momentum

# Published reference numbers (BASELINE.md). value = items/s unless ms.
BASELINES = {
    "resnet50": 81.69,        # imgs/s bs=64, 2x Xeon MKL-DNN
    "vgg16": 28.46,           # VGG-19 imgs/s bs=64 (closest published)
    "alexnet": 399.00,        # imgs/s bs=64
    "googlenet": 250.46,      # imgs/s bs=64
    "stacked_lstm": 184.0,    # ms/batch bs=64 hidden=512, K40m
}


def _trainer_for(model, loss_fn, optimizer, mesh=None, strategy=None,
                 rules=None):
    if mesh is not None:
        from paddle_tpu.parallel.trainer import MeshTrainer
        return MeshTrainer(model, optimizer, loss_fn, mesh,
                           strategy=strategy, rules=rules)
    return Trainer(model, optimizer, loss_fn)


def _put(trainer, batch):
    if hasattr(trainer, "put_batch"):
        return trainer.put_batch(batch)
    return jax.device_put(batch)


def _image_spec(model_ctor, img: int = 224, classes: int = 1000,
                default_bs: int = 64):
    def build(name, batch_size, dtype, mesh, strategy, rules, min_time):
        bs = batch_size or default_bs
        model = model_ctor(num_classes=classes, dtype=dtype)
        loss_fn = supervised_loss(
            lambda lg, y: F.softmax_with_cross_entropy(
                lg.astype(jnp.float32), y),
            metrics={"acc": accuracy})
        trainer = _trainer_for(model, loss_fn, Momentum(0.1, momentum=0.9),
                               mesh, strategy, rules)
        rs = np.random.RandomState(0)
        x = rs.randn(bs, img, img, 3).astype(np.float32)
        y = rs.randint(0, classes, bs).astype(np.int64)
        ts = trainer.init_state(jnp.zeros((bs, img, img, 3)))
        batch = _put(trainer, (x, y))
        return bench_trainer(name, trainer, ts, batch, items_per_step=bs,
                             unit="imgs/s", batch_size=bs, min_time=min_time,
                             baseline=BASELINES.get(name))
    return build


def _mnist(name, batch_size, dtype, mesh, strategy, rules, min_time):
    from paddle_tpu.models import LeNet
    bs = batch_size or 128
    model = LeNet(num_classes=10, dtype=dtype)
    loss_fn = supervised_loss(
        lambda lg, y: F.softmax_with_cross_entropy(lg.astype(jnp.float32), y),
        metrics={"acc": accuracy})
    trainer = _trainer_for(model, loss_fn, Adam(1e-3), mesh, strategy, rules)
    rs = np.random.RandomState(0)
    x = rs.randn(bs, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 10, bs).astype(np.int64)
    ts = trainer.init_state(jnp.zeros((bs, 28, 28, 1)))
    batch = _put(trainer, (x, y))
    return bench_trainer(name, trainer, ts, batch, items_per_step=bs,
                         unit="imgs/s", batch_size=bs, min_time=min_time)


def _transformer(name, batch_size, dtype, mesh, strategy, rules, min_time,
                 seq_len: int = 256, vocab: int = 32000,
                 fused_qkv: bool = False, raw_ce: bool = False,
                 fused_ce: bool = False):
    """Transformer-base WMT (machine_translation.py / dist_transformer.py):
    tokens/s on the teacher-forced train step.

    fused_qkv / raw_ce / fused_ce are perf-variant knobs
    (tools/profile_transformer.py A/B sweep): Megatron-packed projections;
    feeding bf16 logits straight to the internally-promoting CE instead of
    materializing an f32 [B,T,V] copy first; and the chunked
    linear_cross_entropy that never materializes [B,T,V] at all
    (ops/fused_ce.py)."""
    from paddle_tpu.models.transformer import Transformer
    bs = batch_size or 32
    dim = 512
    model = Transformer(src_vocab=vocab, trg_vocab=vocab, model_dim=dim,
                        num_heads=8, num_layers=6, ffn_dim=2048,
                        dropout=0.0, max_len=seq_len + 1, dtype=dtype,
                        fused_qkv=fused_qkv)

    def loss_fn(module, variables, batch, rng, training):
        src, trg_in, trg_out = batch
        if fused_ce:
            from paddle_tpu.ops.fused_ce import linear_cross_entropy
            hid, mut = module.apply(variables, src, trg_in,
                                    training=training, rngs=rng,
                                    mutable=True, return_hidden=True)
            head = variables["params"]["head"]
            loss = jnp.mean(linear_cross_entropy(
                hid, head["weight"].astype(hid.dtype), trg_out,
                head["bias"].astype(hid.dtype)))
            return (loss, {}), mut.get("state", {})
        logits, mut = module.apply(variables, src, trg_in, training=training,
                                   rngs=rng, mutable=True)
        if not raw_ce:
            logits = logits.astype(jnp.float32)
        loss = jnp.mean(F.softmax_with_cross_entropy(logits, trg_out))
        return (loss, {}), mut.get("state", {})

    trainer = _trainer_for(model, loss_fn, Adam(1e-4), mesh, strategy, rules)
    rs = np.random.RandomState(0)
    src = rs.randint(0, vocab, (bs, seq_len)).astype(np.int32)
    trg = rs.randint(0, vocab, (bs, seq_len + 1)).astype(np.int32)
    ts = trainer.init_state(jnp.asarray(src), jnp.asarray(trg[:, :-1]))
    batch = _put(trainer, (src, trg[:, :-1], trg[:, 1:]))
    tokens = bs * seq_len
    extra_flops = 0.0
    if fused_ce:
        from paddle_tpu.ops.fused_ce import mfu_flops_correction
        extra_flops = mfu_flops_correction(tokens, dim, vocab)
    return bench_trainer(name, trainer, ts, batch, items_per_step=tokens,
                         unit="tokens/s", batch_size=bs, min_time=min_time,
                         extra_flops=extra_flops)


# CausalLM size shared by the lm_longctx train bench and bench.py's
# _decode_bench ("same model size" must stay true by construction)
LM_BASE = dict(model_dim=512, num_heads=8, num_layers=6, ffn_dim=2048,
               dropout=0.0)
LM_VOCAB = 32000


def _lm_longctx(name, batch_size, dtype, mesh, strategy, rules, min_time,
                seq_len: int = 16384, vocab: int = LM_VOCAB):
    """Single-chip long-context causal-LM train step: CausalLM with
    block-causal Pallas flash attention (O(T) score memory) + the
    chunked fused CE (no [T, V] logits) — the pairing that makes
    16k-token LM training fit one chip at all. tokens/s + MFU headline
    for SURVEY §5.7's long-context story; the dense-attention
    alternative at this length would materialize a [1, 8, 16k, 16k]
    score tensor (2 TB-scale traffic) and a 1 GB logits round-trip.

    MFU accounting: the flash kernel is a custom call XLA's cost
    analysis scores at ZERO flops (measured), and the fused-CE scan
    body is counted once — both corrected analytically on the
    model-FLOPs basis (causal attention at half the full matmul count,
    recompute excluded; see bench_trainer.extra_flops)."""
    from paddle_tpu.kernels.attention import would_use_flash
    from paddle_tpu.models.transformer import CausalLM
    from paddle_tpu.ops.fused_ce import (linear_cross_entropy,
                                         mfu_flops_correction)

    bs = batch_size or 1
    dim = LM_BASE["model_dim"]
    heads, layers = LM_BASE["num_heads"], LM_BASE["num_layers"]
    model = CausalLM(vocab, max_len=seq_len, dtype=dtype, **LM_BASE)

    def loss_fn(module, variables, batch, rng, training):
        inp, tgt = batch
        hid, mut = module.apply(variables, inp, training=training,
                                rngs=rng, mutable=True, return_hidden=True)
        w, b_ = module.head_weights(variables)
        loss = jnp.mean(linear_cross_entropy(
            hid, w.astype(hid.dtype), tgt,
            None if b_ is None else b_.astype(hid.dtype)))
        return (loss, {}), mut.get("state", {})

    trainer = _trainer_for(model, loss_fn, Adam(1e-4), mesh, strategy, rules)
    rs = np.random.RandomState(0)
    tok = rs.randint(0, vocab, (bs, seq_len + 1)).astype(np.int32)
    ts = trainer.init_state(jnp.asarray(tok[:, :-1]))
    batch = _put(trainer, (tok[:, :-1], tok[:, 1:]))
    tokens = bs * seq_len

    # fused-CE scan correction (model basis, tied head => no bias)
    extra_flops = mfu_flops_correction(tokens, dim, vocab)
    # flash custom-call correction: cost analysis scores it 0 (measured,
    # PERF_NOTES). Causal model flops = fwd 2BT^2D + bwd 4BT^2D per
    # layer. Applied exactly when the kernel dispatches (the shared mha
    # gate); on the XLA dense path the T^2 matmuls ARE counted.
    qkv_shape = (bs, seq_len, heads, dim // heads)
    if would_use_flash(qkv_shape, qkv_shape):
        extra_flops += 6.0 * bs * float(seq_len) ** 2 * dim * layers
    return bench_trainer(name, trainer, ts, batch, items_per_step=tokens,
                         unit="tokens/s", batch_size=bs, min_time=min_time,
                         extra_flops=extra_flops)


def _stacked_lstm(name, batch_size, dtype, mesh, strategy, rules, min_time,
                  seq_len: int = 100, vocab: int = 10000):
    """Stacked-LSTM text classifier (stacked_dynamic_lstm.py; the LSTM
    headline number README.md:112-120 is ms/batch bs=64 hidden=512)."""
    from paddle_tpu.models.nlp import TextClassifier
    bs = batch_size or 64
    model = TextClassifier(vocab=vocab, embed_dim=128, hidden=512, layers=2)
    loss_fn = supervised_loss(
        lambda lg, y: F.softmax_with_cross_entropy(lg.astype(jnp.float32), y),
        metrics={"acc": accuracy})
    trainer = _trainer_for(model, loss_fn, Adam(1e-3), mesh, strategy, rules)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, vocab, (bs, seq_len)).astype(np.int32)
    y = rs.randint(0, 2, bs).astype(np.int64)
    ts = trainer.init_state(jnp.asarray(toks))
    batch = _put(trainer, (toks, y))
    return bench_trainer(name, trainer, ts, batch,
                         items_per_step=bs * seq_len, unit="tokens/s",
                         batch_size=bs, min_time=min_time,
                         baseline=BASELINES.get(name), baseline_is_ms=True)


def _bert(name, batch_size, dtype, mesh, strategy, rules, min_time,
          seq_len: int = 128, vocab: int = 30522, model_dim: int = 768,
          num_layers: int = 12, num_heads: int = 12, ffn_dim: int = 3072,
          mask_frac: float = 0.15, fused_qkv: bool = False):
    """BERT-base MLM pretraining step (BASELINE BERT row: pod-scale
    allreduce / 8->32 chip scaling). Static masked-position count keeps
    the step one compile."""
    from paddle_tpu.models.transformer import BertEncoder
    bs = batch_size or 32
    k = max(1, int(seq_len * mask_frac))
    model = BertEncoder(vocab=vocab, model_dim=model_dim,
                        num_heads=num_heads, num_layers=num_layers,
                        ffn_dim=ffn_dim, max_len=seq_len, dropout=0.0,
                        dtype=dtype, fused_qkv=fused_qkv)

    def loss_fn(module, variables, batch, rng, training):
        tokens, positions, labels = batch
        logits, mut = module.apply(variables, tokens, positions,
                                   training=training, rngs=rng,
                                   mutable=True)
        loss = jnp.mean(F.softmax_with_cross_entropy(
            logits.astype(jnp.float32), labels))
        return (loss, {}), mut.get("state", {})

    trainer = _trainer_for(model, loss_fn, Adam(1e-4), mesh, strategy,
                           rules)
    rs = np.random.RandomState(0)
    tokens = rs.randint(0, vocab, (bs, seq_len)).astype(np.int32)
    positions = np.sort(
        rs.rand(bs, seq_len).argsort(axis=1)[:, :k], axis=1).astype(np.int32)
    labels = rs.randint(0, vocab, (bs, k)).astype(np.int32)
    ts = trainer.init_state(jnp.asarray(tokens), jnp.asarray(positions))
    batch = _put(trainer, (tokens, positions, labels))
    return bench_trainer(name, trainer, ts, batch,
                         items_per_step=bs * seq_len, unit="tokens/s",
                         batch_size=bs, min_time=min_time)


def _bert_tiny(name, batch_size, dtype, mesh, strategy, rules, min_time):
    """Small-config BERT for CPU-mesh scaling CI (same code path)."""
    return _bert(name, batch_size, dtype, mesh, strategy, rules, min_time,
                 seq_len=32, vocab=1024, model_dim=64, num_layers=2,
                 num_heads=4, ffn_dim=128)


def _deepfm(name, batch_size, dtype, mesh, strategy, rules, min_time,
            fields: int = 26, vocab_per_field: int = 1000, dense_dim: int = 13):
    """DeepFM CTR (dist_ctr capability; BASELINE DeepFM target)."""
    from paddle_tpu.models.nlp import DeepFM
    bs = batch_size or 512
    model = DeepFM(num_fields=fields, vocab_per_field=vocab_per_field,
                   dense_dim=dense_dim)

    def loss_fn(module, variables, batch, rng, training):
        dense, sparse, y = batch
        logit, mut = module.apply(variables, dense, sparse,
                                  training=training, rngs=rng, mutable=True)
        loss = jnp.mean(F.sigmoid_cross_entropy_with_logits(logit, y))
        return (loss, {}), mut.get("state", {})

    trainer = _trainer_for(model, loss_fn, Adam(1e-3), mesh, strategy, rules)
    rs = np.random.RandomState(0)
    dense = rs.randn(bs, dense_dim).astype(np.float32)
    sparse = rs.randint(0, vocab_per_field, (bs, fields)).astype(np.int32)
    y = rs.randint(0, 2, bs).astype(np.float32)
    ts = trainer.init_state(jnp.asarray(dense), jnp.asarray(sparse))
    batch = _put(trainer, (dense, sparse, y))
    return bench_trainer(name, trainer, ts, batch, items_per_step=bs,
                         unit="samples/s", batch_size=bs, min_time=min_time)


def _registry() -> Dict[str, Callable]:
    from paddle_tpu.models import vision as V
    return {
        "mnist": _mnist,
        "mlp": _image_spec(lambda num_classes, dtype: V.MLP(
            num_classes=num_classes, dtype=dtype), img=28, classes=10,
            default_bs=128),
        "alexnet": _image_spec(
            lambda num_classes, dtype: V.AlexNet(num_classes, dtype=dtype)),
        "vgg16": _image_spec(
            lambda num_classes, dtype: V.vgg16(num_classes, dtype=dtype)),
        "resnet50": _image_spec(
            lambda num_classes, dtype: V.resnet50(num_classes, dtype=dtype)),
        "se_resnext50": _image_spec(
            lambda num_classes, dtype: V.se_resnext50(num_classes,
                                                      dtype=dtype)),
        "googlenet": _image_spec(
            lambda num_classes, dtype: V.GoogLeNet(num_classes, dtype=dtype)),
        "transformer": _transformer,
        "lm_longctx": _lm_longctx,
        "bert": _bert,
        "bert_tiny": _bert_tiny,
        "stacked_lstm": _stacked_lstm,
        "deepfm": _deepfm,
    }


MODELS = _registry()


def run_model(name: str, batch_size: Optional[int] = None,
              dtype=jnp.float32, mesh=None, strategy=None, rules=None,
              min_time: float = 2.0, **model_kwargs) -> BenchResult:
    if name not in MODELS:
        raise ValueError(f"unknown benchmark model {name!r}; "
                         f"choose from {sorted(MODELS)}")
    return MODELS[name](name, batch_size, dtype, mesh, strategy, rules,
                        min_time, **model_kwargs)


# Published reference INFERENCE numbers (BASELINE.md: Xeon E5-2650v4,
# MKL-DNN): imgs/s at the listed batch size.
INFER_BASELINES = {
    ("resnet50", 1): 107.83,
    ("resnet50", 16): 217.69,
    ("googlenet", 16): 600.94,
    ("alexnet", 16): 850.51,
    ("vgg16", 1): 75.07,        # VGG-19 figure; closest published
}

def _infer_models():
    from paddle_tpu.models import vision as V
    return {
        "resnet50": lambda d: V.resnet50(1000, dtype=d),
        "googlenet": lambda d: V.GoogLeNet(1000, dtype=d),
        "alexnet": lambda d: V.AlexNet(1000, dtype=d),
        "vgg16": lambda d: V.vgg16(1000, dtype=d),
    }


# derived from the ctor table so the CLI gate and run_infer can
# never drift apart
INFER_MODELS = tuple(sorted(_infer_models()))


def run_infer(name: str, batch_size: int = 16, dtype=jnp.float32,
              min_time: float = 2.0, img: int = 224) -> BenchResult:
    """Inference throughput (reference IntelOptimizedPaddle.md infer
    table; served-model path: eval-mode forward, no grads)."""
    from paddle_tpu.benchmark.harness import (compiled_flops,
                                              device_peak_flops, run_timed)
    ctors = _infer_models()
    if name not in ctors:
        raise ValueError(f"unknown infer model {name!r}; "
                         f"choose from {sorted(ctors)}")
    model = ctors[name](dtype)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch_size, img, img, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x)

    # Two layers of chaining (run_timed caller contract, harness.chain_k):
    # K forwards chained INSIDE one program (amortizes per-dispatch pool
    # overhead that dominates a single small forward), and the carry
    # chained ACROSS steps (a fixed-input step would let the axon pool
    # fan independent calls across chips and report fleet throughput).
    K = 8 if jax.devices()[0].platform == "tpu" else 2
    kfwd_j = chain_k(
        lambda c, v, xx: model.apply(v, xx + c, training=False), K)

    def step(s):
        s2 = kfwd_j(s, variables, x)
        return s2, s2

    sec_k, steps, _ = run_timed(step, jnp.zeros((), x.dtype),
                                min_time=min_time)
    sec = sec_k / K
    steps *= K
    # XLA's cost analysis doesn't model fori_loop trip counts — the
    # chained program's body is counted ONCE — so the undivided figure
    # already equals one forward (plus a negligible carry add). Dividing
    # by K (as before) understated flops ~K-fold; recompiling the
    # unchained forward just for FLOPs would cost a second full compile.
    flops = compiled_flops(kfwd_j, jnp.zeros((), x.dtype), variables, x)
    peak = device_peak_flops()
    baseline = INFER_BASELINES.get((name, batch_size))
    value = batch_size / sec
    return BenchResult(
        model=f"{name}_infer", unit="imgs/s", value=value,
        ms_per_step=sec * 1e3, steps=steps, batch_size=batch_size,
        flops_per_step=flops,
        tflops_per_sec=(flops / sec / 1e12) if flops else None,
        mfu=(flops / sec / peak) if (flops and peak) else None,
        device=getattr(jax.devices()[0], "device_kind",
                       jax.devices()[0].platform),
        vs_baseline=(value / baseline) if baseline else None)

"""Benchmark harness: timed training windows with MFU accounting.

Capability-equivalent of the reference benchmark CLI
(/root/reference/benchmark/fluid/fluid_benchmark.py:139 train(), which
times passes over a model zoo and prints imgs/s) — extended with what a
TPU benchmark must report to be honest:

- a timed window >= `min_time` seconds (adaptive step count), fully
  synchronized with `jax.block_until_ready` at the window edges only, so
  the async dispatch pipeline stays filled inside the window;
- FLOPs per step taken from XLA's own cost analysis of the compiled
  executable (not a hand model), giving MFU = flops/sec vs the chip's
  published peak for the matmul dtype.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

# Published bf16 peak matmul throughput per chip, FLOP/s. Keyed by
# substring of jax.devices()[0].device_kind (lowercased).
PEAK_FLOPS_BF16 = {
    "v6e": 918e12,          # Trillium
    "v6 lite": 918e12,
    "v5p": 459e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v4": 275e12,
    "v3": 123e12,           # per chip (2 cores)
    "v2": 46e12,
}


def device_peak_flops(dtype_bits: int = 16) -> Optional[float]:
    """Peak FLOP/s of device 0, or None if unknown (e.g. CPU)."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for key, peak in PEAK_FLOPS_BF16.items():
        if key in kind:
            return peak if dtype_bits <= 16 else peak / 2
    return None


def retry_transient(fn: Callable[[], Any], attempts: int = 2) -> Any:
    """Run fn(); retry on failure. The axon tunnel's remote-compile
    channel occasionally drops mid-read ("response body closed") — a
    transient that must not cost a recorded benchmark an entry. Shared by
    bench.py and the tools/ profilers so the guard can't drift.

    The first failure is PRINTED before retrying: deterministic failures
    (OOM, shape errors) inevitably fail twice, and a silent first attempt
    would both hide that a retry happened and make the failure look
    twice as slow as it was."""
    import sys
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — any transient counts
            last = e
            if i + 1 < attempts:
                print(f"[retry_transient] attempt {i + 1}/{attempts} failed: "
                      f"{type(e).__name__}: {e}; retrying",
                      file=sys.stderr, flush=True)
    raise last


def chain_k(fn: Callable, k: int):
    """Jitted K-iteration chained step for run_timed's caller contract.

    `fn(carry, *args) -> array or tuple of arrays` runs K times inside
    ONE program (amortizing per-dispatch pool overhead), with a scalar
    carry derived from EVERY output threaded into the next iteration —
    touching all outputs so XLA cannot dead-code-eliminate any of them,
    scaled by 1e-30 rather than 0 because a mul-by-zero fold would sever
    the loop-carried dependence and let the body be eliminated silently.
    The returned jitted callable maps (carry, *args) -> carry; divide the
    measured step time by K.
    """
    def kstep(s, *args):
        def body(i, c):
            outs = fn(c, *args)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            carry = outs[0].ravel()[0]
            for o in outs[1:]:
                carry = carry + o.ravel()[0].astype(carry.dtype)
            return (carry * 1e-30).astype(s.dtype)
        return jax.lax.fori_loop(0, k, body, s)
    return jax.jit(kstep)


_SUSTAINED: Optional[float] = None


def sustained_matmul_flops(min_time: float = 1.5) -> Optional[float]:
    """Sustained single-chip bf16 matmul rate (FLOP/s), cached per
    process (first call's measurement wins).

    State-chained 8192x8192 matmul chains (step k+1 consumes step k's
    output — see the run_timed caller contract; a fixed-input probe on
    the axon pool measures multi-chip fleet throughput, not the chip).
    Measured ~149 TFLOP/s on v5e = 76% of the published 197 peak, which
    calibrates what fraction of the datasheet a perfectly matmul-dense
    program can actually reach. Returns None off-TPU.
    """
    global _SUSTAINED
    if _SUSTAINED is not None:
        return _SUSTAINED
    if jax.devices()[0].platform != "tpu":
        return None
    import jax.numpy as jnp
    n, chain = 8192, 10
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.randn(n, n) * 0.01, jnp.bfloat16)
    b = jnp.asarray(rs.randn(n, n) * 0.01, jnp.bfloat16)
    g = jax.jit(lambda s, b: jax.lax.fori_loop(
        0, chain, lambda i, c: (c @ b).astype(jnp.bfloat16), s))
    sec, _, _ = run_timed(lambda s: (g(s, b),) * 2, a, min_time=min_time)
    _SUSTAINED = chain * 2 * n ** 3 / sec
    return _SUSTAINED


def compiled_flops(jitted, *args) -> Optional[float]:
    """FLOPs per invocation from the compiled executable's cost analysis."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = cost.get("flops")
        return float(f) if f else None
    except Exception:
        return None


@dataclasses.dataclass
class BenchResult:
    model: str
    unit: str                       # "imgs/s", "tokens/s", "samples/s"
    value: float                    # items per second
    ms_per_step: float
    steps: int
    batch_size: int
    flops_per_step: Optional[float]
    tflops_per_sec: Optional[float]
    mfu: Optional[float]            # fraction of chip peak
    device: str
    vs_baseline: Optional[float]

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in d.items()}


def _sync(out) -> None:
    """Force real device execution, not just dispatch.

    jax.block_until_ready is NOT sufficient on tunneled/async backends
    (measured on the axon TPU tunnel: block_until_ready returns after
    dispatch, reporting 40 PFLOP/s fantasy numbers); fetching a value is.
    Pull one leaf back to the host — it transitively forces everything it
    depends on.
    """
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if isinstance(l, jax.Array)]
    if leaves:
        np.asarray(jax.device_get(leaves[0]))


def run_timed(step_once: Callable[[Any], Tuple[Any, Any]], state,
              min_time: float = 2.0, warmup: int = 3
              ) -> Tuple[float, int, Any]:
    """Time `state, out = step_once(state)` by two-window subtraction.

    The host→device→host sync at a window edge has a large fixed cost on
    tunneled backends (~135 ms measured on axon, vs ~1 ms steps), so a
    single window overstates step time badly. Instead time a small window
    T_A (N_A steps + sync) and a large one T_B (N_B steps + sync):
    per_step = (T_B - T_A) / (N_B - N_A) cancels the fixed cost exactly.
    N_B grows (doubling) until the subtracted window covers >= min_time.

    CALLER CONTRACT: step k+1's computation must CONSUME step k's output
    (thread it through `state`). The axon pool dispatches INDEPENDENT
    calls concurrently across chips — measured: a fixed-input matmul loop
    reporting 4,094 TFLOP/s on a 197 TFLOP/s chip — so a fixed-input step
    measures fleet throughput, not the device. Training steps chain their
    TrainState naturally; for inference/kernel timing, fold a scalar from
    the previous output back into the input (see run_infer).

    Returns (seconds_per_step, steps_timed_total, final_state).
    """
    out = None
    for _ in range(max(warmup, 1)):
        state, out = step_once(state)
    _sync(out)

    n_a = 8
    t0 = time.perf_counter()
    for _ in range(n_a):
        state, out = step_once(state)
    _sync(out)
    t_a = time.perf_counter() - t0

    # upper-bound estimate of per-step time picks the first N_B try
    est = t_a / n_a
    n_b = max(4 * n_a, int(min_time / max(est, 1e-9)))
    total_steps = n_a
    while True:
        n_b = min(n_b, 1_000_000)
        t0 = time.perf_counter()
        for _ in range(n_b):
            state, out = step_once(state)
        _sync(out)
        t_b = time.perf_counter() - t0
        total_steps += n_b
        if t_b - t_a >= min_time or n_b >= 1_000_000:
            break
        n_b *= 4
    per_step = (t_b - t_a) / (n_b - n_a)
    return max(per_step, 1e-12), total_steps, state


def bench_trainer(name: str, trainer, ts, batch, items_per_step: int,
                  unit: str, batch_size: int, min_time: float = 2.0,
                  baseline: Optional[float] = None,
                  baseline_is_ms: bool = False,
                  extra_flops: float = 0.0) -> BenchResult:
    """Benchmark one (trainer, state, batch): the common wrapper used by
    every model spec in models.py. `trainer` is core.executor.Trainer or
    parallel.trainer.MeshTrainer (same train_step contract).

    extra_flops: analytic correction added to the compiled-executable
    count for FLOPs XLA's cost analysis structurally misses — it counts a
    scan/fori_loop body ONCE regardless of trip count (see PERF_NOTES
    measurement-integrity notes), so steps that loop over matmul chunks
    (ops/fused_ce.py) pass the known per-iteration matmul FLOPs x the
    uncounted iterations here. Keep corrections analytic and
    matmul-only — never estimates of fused elementwise work."""
    rng = jax.random.key(0)

    def step_once(state):
        return trainer.train_step(state, batch, rng=rng)

    sec_per_step, steps, _ = run_timed(step_once, ts, min_time=min_time)

    flops = None
    jitted = getattr(trainer, "_train_step", None)
    if jitted is not None:
        flops = compiled_flops(jitted, ts, batch, rng)
        if flops:
            flops += extra_flops

    tflops = (flops / sec_per_step / 1e12) if flops else None
    peak = device_peak_flops()
    mfu = (flops / sec_per_step / peak) if (flops and peak) else None
    value = items_per_step / sec_per_step
    vs = None
    if baseline:
        vs = (baseline / (sec_per_step * 1e3) if baseline_is_ms
              else value / baseline)
    return BenchResult(
        model=name, unit=unit, value=value,
        ms_per_step=sec_per_step * 1e3, steps=steps,
        batch_size=batch_size,
        flops_per_step=flops, tflops_per_sec=tflops, mfu=mfu,
        device=getattr(jax.devices()[0], "device_kind",
                       jax.devices()[0].platform),
        vs_baseline=vs)

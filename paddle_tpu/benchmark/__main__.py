"""Benchmark CLI — the fluid_benchmark.py equivalent.

Usage (mirrors /root/reference/benchmark/fluid/fluid_benchmark.py +
args.py flag surface, TPU-first):

    python -m paddle_tpu.benchmark --model resnet50 --batch_size 64
    python -m paddle_tpu.benchmark --model all --min_time 2
    python -m paddle_tpu.benchmark --model transformer --dp 4 --tp 2

--dp/--fsdp/--tp build a jax.sharding mesh and run the model under
MeshTrainer (the reference's --update_method local/pserver/nccl2 maps to
mesh axes + sharding rules here; multi-host comes from jax.distributed,
see paddle_tpu.parallel.distributed).
"""

from __future__ import annotations

import argparse
import json
import sys

import jax.numpy as jnp


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="paddle_tpu.benchmark",
                                description=__doc__)
    p.add_argument("--model", default="resnet50",
                   help="model name, comma list, or 'all'")
    p.add_argument("--batch_size", type=int, default=None,
                   help="global batch size (default: per-model)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="compute dtype (params stay fp32)")
    p.add_argument("--min_time", type=float, default=2.0,
                   help="minimum timed window in seconds")
    p.add_argument("--dp", type=int, default=0, help="data-parallel axis")
    p.add_argument("--fsdp", type=int, default=0, help="ZeRO/fsdp axis")
    p.add_argument("--tp", type=int, default=0, help="tensor-parallel axis")
    p.add_argument("--gradient_accumulation", type=int, default=1)
    p.add_argument("--json", action="store_true",
                   help="one JSON object per line instead of a table")
    p.add_argument("--infer", action="store_true",
                   help="inference throughput (eval forward) instead of "
                        "training; mirrors the reference's infer tables")
    p.add_argument("--scaling", default=None, metavar="SIZES",
                   help="weak-scaling sweep over dp mesh sizes, e.g. "
                        "'1,2,4,8': per-chip throughput + efficiency "
                        "(per-chip batch from --batch_size, default 32)")
    p.add_argument("--resume_file", default=None, metavar="PATH",
                   help="preemption-safe sweeps: append each finished "
                        "model's name here and skip names already present "
                        "on relaunch; SIGTERM between models exits with "
                        "the reschedulable preemption code "
                        "(resilience/supervisor.py)")
    args = p.parse_args(argv)

    from paddle_tpu.benchmark.models import MODELS, run_model

    if args.infer and args.scaling:
        p.error("--infer and --scaling are mutually exclusive")

    if args.scaling:
        from paddle_tpu.benchmark.scaling import run_scaling
        sizes = [int(s) for s in args.scaling.split(",")]
        dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
        rows = run_scaling(args.model if args.model != "all" else "mlp",
                           sizes=sizes,
                           per_chip_batch=args.batch_size or 32,
                           dtype=dtype, min_time=args.min_time)
        for row in rows:
            if args.json:
                print(json.dumps(row))
            elif "skipped" in row:
                print(f"dp={row['dp']:<3} skipped ({row['skipped']})")
            else:
                print(f"dp={row['dp']:<3} {row['value']:12.1f} "
                      f"{row['unit']:<9} per-chip {row['per_chip']:10.1f}  "
                      f"eff {row['efficiency'] * 100:6.1f}%  "
                      f"[{row['platform']}]")
        return 0

    if args.infer and (args.dp or args.fsdp or args.tp
                       or args.gradient_accumulation != 1):
        p.error("--infer benchmarks single-device eval throughput; "
                "mesh/accumulation flags do not apply")

    mesh = strategy = rules = None
    if args.dp or args.fsdp or args.tp:
        from paddle_tpu.parallel import DistStrategy, MeshConfig, make_mesh
        from paddle_tpu.parallel.sharding import (
            fsdp_rules, transformer_tp_rules)
        mesh = make_mesh(MeshConfig(dp=max(args.dp, 1),
                                    fsdp=max(args.fsdp, 1),
                                    tp=max(args.tp, 1)))
        strategy = DistStrategy(
            gradient_accumulation_steps=args.gradient_accumulation)
        rules = (transformer_tp_rules() if args.tp > 1
                 else fsdp_rules() if args.fsdp > 1 else None)

    names = (sorted(MODELS) if args.model == "all"
             else [m.strip() for m in args.model.split(",")])
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    # Preemption-safe sweep: a long `--model all` run on a preemptible
    # slice records progress per model and checks for a delivered
    # SIGTERM/SIGINT at each model boundary (mid-model state is
    # worthless — a timing window is only meaningful complete).
    supervisor = None
    done: set = set()
    if args.resume_file:
        import os

        from paddle_tpu.resilience.supervisor import RunSupervisor
        if os.path.exists(args.resume_file):
            with open(args.resume_file) as f:
                done = {line.strip() for line in f if line.strip()}
        supervisor = RunSupervisor().install()

    results = []
    for name in names:
        if name in done:
            print(f"{name:>14}  (done in {args.resume_file}; skipped)")
            continue
        if supervisor is not None:
            supervisor.maybe_preempt_exit(None, len(results))
        if args.infer:
            from paddle_tpu.benchmark.models import INFER_MODELS, run_infer
            if name not in INFER_MODELS:
                print(f"{name:>14}  (no inference benchmark; skipped)")
                continue
            r = run_infer(name, batch_size=args.batch_size or 16,
                          dtype=dtype, min_time=args.min_time)
        else:
            r = run_model(name, batch_size=args.batch_size, dtype=dtype,
                          mesh=mesh, strategy=strategy, rules=rules,
                          min_time=args.min_time)
        results.append(r)
        if args.json:
            print(json.dumps(r.to_dict()))
        else:
            mfu = f"{r.mfu * 100:5.1f}%" if r.mfu is not None else "  n/a"
            tf = (f"{r.tflops_per_sec:7.1f}" if r.tflops_per_sec is not None
                  else "    n/a")
            vs = (f"{r.vs_baseline:8.2f}x" if r.vs_baseline is not None
                  else "     n/a")
            print(f"{name:>14}  {r.value:12.1f} {r.unit:<9} "
                  f"{r.ms_per_step:8.2f} ms/step  {tf} TF/s  MFU {mfu}  "
                  f"vs_ref {vs}  [{r.device}]")
        if args.resume_file:
            with open(args.resume_file, "a") as f:
                f.write(name + "\n")
    if supervisor is not None:
        supervisor.uninstall()
    return 0


if __name__ == "__main__":
    sys.exit(main())

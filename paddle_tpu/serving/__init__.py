"""C++ serving shim build + ctypes driver.

Reference: the inference C++ API consumed by serving applications
(/root/reference/paddle/fluid/inference/api/paddle_api.h,
api/analysis_predictor.h:44,61, api/demo_ci/). `serving.cc` is the
library; `demo.cc` a standalone C++ consumer; this module compiles both on
demand (g++ + libpython; no pybind11 in this image) and provides
`CPredictor`, a ctypes driver over the same C ABI — used by tests and by
Python hosts that want the C contract.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
from typing import List, Optional, Sequence

import numpy as np

from paddle_tpu.utils.native import cache_dir as _cache_dir

_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "int8",
           "bool", "bfloat16", "float16"]
_SRC_DIR = os.path.dirname(os.path.abspath(__file__))


def _py_flags() -> List[str]:
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_python_version()
    return [f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
            f"-Wl,-rpath,{libdir}"]


def _build(src: str, out_name: str, shared: bool,
           extra: Sequence[str] = ()) -> Optional[str]:
    out = os.path.join(_cache_dir(), out_name)
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", src, "-o", tmp]
    if shared:
        cmd[2:2] = ["-shared", "-fPIC"]
    cmd += list(extra) + _py_flags()
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, out)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def build_library() -> Optional[str]:
    """Compile libptpu_serving.so; returns its path (cached) or None."""
    return _build(os.path.join(_SRC_DIR, "serving.cc"),
                  "libptpu_serving.so", shared=True)


def build_demo() -> Optional[str]:
    """Compile the standalone C++ demo binary (api/demo_ci capability)."""
    lib = build_library()
    if lib is None:
        return None
    return _build(os.path.join(_SRC_DIR, "demo.cc"), "ptpu_demo",
                  shared=False, extra=[lib, f"-Wl,-rpath,{_cache_dir()}"])


def build_train_demo() -> Optional[str]:
    """Compile the standalone C++ *training* demo (reference
    train/demo/demo_trainer.cc capability: a native app owning the train
    loop, feeding C buffers zero-copy and checkpointing at the end)."""
    return _build(os.path.join(_SRC_DIR, "train_demo.cc"),
                  "ptpu_train_demo", shared=False)


class _Tensor(ctypes.Structure):
    _fields_ = [("dtype", ctypes.c_int), ("rank", ctypes.c_int),
                ("shape", ctypes.POINTER(ctypes.c_int64)),
                ("data", ctypes.c_void_p)]


class CPredictor:
    """ctypes driver over the serving C ABI (same contract a C++ host
    uses; ≈ PaddlePredictor::Run through paddle_api.h)."""

    def __init__(self, model_dir: str, sys_path: Optional[str] = None,
                 _cloned_from: Optional["CPredictor"] = None):
        if _cloned_from is not None:
            if not _cloned_from._h:
                raise RuntimeError("cannot clone a closed CPredictor")
            self._lib = _cloned_from._lib
            self._h = self._lib.ptpu_clone(_cloned_from._h)
            if not self._h:
                raise RuntimeError("ptpu_clone failed")
            return
        lib_path = build_library()
        if lib_path is None:
            raise RuntimeError("cannot build serving library (no g++?)")
        lib = ctypes.CDLL(lib_path)
        lib.ptpu_create.restype = ctypes.c_void_p
        lib.ptpu_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.ptpu_clone.restype = ctypes.c_void_p
        lib.ptpu_clone.argtypes = [ctypes.c_void_p]
        lib.ptpu_ok.argtypes = [ctypes.c_void_p]
        lib.ptpu_last_error.restype = ctypes.c_char_p
        lib.ptpu_last_error.argtypes = [ctypes.c_void_p]
        lib.ptpu_run.argtypes = [ctypes.c_void_p, ctypes.POINTER(_Tensor),
                                 ctypes.c_int]
        for name in ("ptpu_num_inputs", "ptpu_num_outputs",
                     "ptpu_output_rank", "ptpu_output_dtype"):
            getattr(lib, name).argtypes = [ctypes.c_void_p] + (
                [ctypes.c_int] if "output_" in name else [])
        lib.ptpu_output_rank.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpu_output_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpu_output_shape.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptpu_output_shape.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpu_output_data.restype = ctypes.c_void_p
        lib.ptpu_output_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpu_output_nbytes.restype = ctypes.c_int64
        lib.ptpu_output_nbytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptpu_destroy.argtypes = [ctypes.c_void_p]
        self._lib = lib
        repo_root = os.path.dirname(os.path.dirname(_SRC_DIR))
        sp = sys_path if sys_path is not None else repo_root
        self._h = lib.ptpu_create(model_dir.encode(), sp.encode())
        if not lib.ptpu_ok(self._h):
            err = lib.ptpu_last_error(self._h).decode()
            lib.ptpu_destroy(self._h)
            self._h = None
            raise RuntimeError(f"ptpu_create failed: {err}")

    def run(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        if not self._h:   # NULL would segfault inside ptpu_run
            raise RuntimeError("CPredictor is closed")
        tensors = (_Tensor * len(arrays))()
        keep = []
        for i, a in enumerate(arrays):
            a = np.ascontiguousarray(a)
            keep.append(a)
            shape = (ctypes.c_int64 * a.ndim)(*a.shape)
            keep.append(shape)
            tensors[i] = _Tensor(
                _DTYPES.index(a.dtype.name), a.ndim, shape,
                a.ctypes.data_as(ctypes.c_void_p))
        if self._lib.ptpu_run(self._h, tensors, len(arrays)) != 0:
            raise RuntimeError(
                f"ptpu_run: {self._lib.ptpu_last_error(self._h).decode()}")
        outs = []
        for i in range(self._lib.ptpu_num_outputs(self._h)):
            rank = self._lib.ptpu_output_rank(self._h, i)
            shape = [self._lib.ptpu_output_shape(self._h, i)[d]
                     for d in range(rank)]
            dtype = _DTYPES[self._lib.ptpu_output_dtype(self._h, i)]
            nbytes = self._lib.ptpu_output_nbytes(self._h, i)
            buf = ctypes.string_at(self._lib.ptpu_output_data(self._h, i),
                                   nbytes)
            outs.append(np.frombuffer(buf, dtype=dtype).reshape(shape)
                        .copy())
        return outs

    def clone(self) -> "CPredictor":
        """Per-thread handle sharing the loaded model (≈
        PaddlePredictor::Clone): a CPredictor is NOT thread-safe (run
        rewrites its output slots) — clone one per serving thread."""
        return CPredictor("", _cloned_from=self)

    def close(self) -> None:
        if self._h:
            self._lib.ptpu_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


__all__ = ["CPredictor", "build_demo", "build_library"]

// C++ serving shim — native predictor API over exported models.
//
// Capability-equivalent of the reference inference C++ API
// (/root/reference/paddle/fluid/inference/api/paddle_api.h PaddlePredictor
// + PaddleTensor; api/analysis_predictor.h:44 AnalysisPredictor::Run :52,
// ZeroCopyRun :61; api/demo_ci standalone consumer): a C++ application
// links this library, loads a model directory exported by
// paddle_tpu.io.inference.save_inference_model (StableHLO + params), and
// serves it with zero-copy input buffers.
//
// Architecture (TPU-first, not a port): the reference's AnalysisPredictor
// wraps its own C++ graph executor; here the XLA runtime IS the executor,
// reached through an embedded CPython interpreter driving
// paddle_tpu.io.inference.InferencePredictor. Input tensors cross the
// C boundary as zero-copy memoryviews (numpy.frombuffer); outputs are
// exposed through the buffer protocol and stay valid until the next Run —
// the ZeroCopyTensor lifetime contract.
//
// Flat C ABI (pybind11 absent in this image; ctypes/C callers both work):
//   ptpu_create(model_dir, sys_path)       -> handle | NULL
//   ptpu_clone(h)                          -> handle (shares the model)
//   ptpu_last_error(h)                     -> const char*
//   ptpu_num_inputs/ptpu_input_name/_rank/_shape/_dtype(h, i)
//   ptpu_run(h, tensors, n)                -> 0 | -1
//   ptpu_num_outputs/_output_rank/_output_shape/_output_dtype/
//   ptpu_output_data/_output_nbytes(h, i)
//   ptpu_destroy(h)
//
// Threading contract (same as the reference PaddlePredictor: one
// predictor per thread, created via Clone, paddle_api.h): a handle is
// NOT thread-safe — ptpu_run rewrites its output slots. For concurrent
// serving, ptpu_clone one handle per thread; clones share the loaded
// model + compiled executable (cheap) but own their outputs. Python-
// driving work serializes on the GIL; JAX releases it while blocked on
// device execution/transfers, so cloned handles overlap device compute
// (measured throughput in README §serving).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 serving.cc \
//            $(python3-config --includes) -lpython3.12 -o libptpu_serving.so

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

// dtype codes of the C ABI (stable, documented for C callers)
const char* kDtypeNames[] = {"float32", "float64", "int32",   "int64",
                             "uint8",   "int8",    "bool",    "bfloat16",
                             "float16"};
constexpr int kNumDtypes = 9;

int dtype_code(const std::string& name) {
  for (int i = 0; i < kNumDtypes; i++)
    if (name == kDtypeNames[i]) return i;
  return -1;
}

struct Output {
  std::vector<int64_t> shape;
  int dtype = -1;
  PyObject* array = nullptr;  // owned contiguous ndarray keeping data alive
  void* data = nullptr;
  int64_t nbytes = 0;
};

struct Handle {
  PyObject* predictor = nullptr;
  PyObject* np = nullptr;
  std::vector<Output> outputs;
  std::vector<std::string> in_names;
  std::vector<std::vector<int64_t>> in_shapes;
  std::vector<int> in_dtypes;
  std::string error;
};

bool g_we_initialized = false;

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

void set_py_error(Handle* h, const char* what) {
  h->error = what;
  if (PyErr_Occurred()) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (value) {
      PyObject* s = PyObject_Str(value);
      if (s) {
        h->error += ": ";
        h->error += PyUnicode_AsUTF8(s);
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
  }
}

void clear_outputs(Handle* h) {
  for (auto& o : h->outputs) Py_XDECREF(o.array);
  h->outputs.clear();
}

}  // namespace

extern "C" {

typedef struct {
  int dtype;             // kDtypeNames index
  int rank;
  const int64_t* shape;
  const void* data;      // row-major contiguous, not copied (zero-copy in)
} PtpuTensor;

void* ptpu_create(const char* model_dir, const char* extra_sys_path) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    PyEval_SaveThread();  // release the GIL so Gil{} works uniformly
  }
  Gil gil;
  Handle* h = new Handle();

  if (extra_sys_path && *extra_sys_path) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    std::string paths(extra_sys_path);
    size_t start = 0;
    while (start <= paths.size()) {
      size_t sep = paths.find(':', start);
      std::string p = paths.substr(
          start, sep == std::string::npos ? std::string::npos : sep - start);
      if (!p.empty()) {
        PyObject* ps = PyUnicode_FromString(p.c_str());
        PyList_Insert(sys_path, 0, ps);
        Py_DECREF(ps);
      }
      if (sep == std::string::npos) break;
      start = sep + 1;
    }
  }

  h->np = PyImport_ImportModule("numpy");
  PyObject* mod =
      h->np ? PyImport_ImportModule("paddle_tpu.io.inference") : nullptr;
  PyObject* cls =
      mod ? PyObject_GetAttrString(mod, "InferencePredictor") : nullptr;
  if (cls) {
    h->predictor = PyObject_CallFunction(cls, "s", model_dir);
  }
  Py_XDECREF(cls);
  Py_XDECREF(mod);
  if (!h->predictor) {
    set_py_error(h, "failed to create InferencePredictor");
    // keep the handle so the caller can read the error; predictor==NULL
    return h;
  }

  // cache the input signature for C-side introspection
  PyObject* sig = PyObject_GetAttrString(h->predictor, "signature");
  if (sig) {
    PyObject* names = PyDict_GetItemString(sig, "input_names");  // borrowed
    PyObject* inputs = PyDict_GetItemString(sig, "inputs");
    if (names && inputs) {
      Py_ssize_t n = PyList_Size(names);
      for (Py_ssize_t i = 0; i < n; i++) {
        h->in_names.push_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
        PyObject* item = PyList_GetItem(inputs, i);
        PyObject* shp = PyDict_GetItemString(item, "shape");
        PyObject* dt = PyDict_GetItemString(item, "dtype");
        std::vector<int64_t> dims;
        for (Py_ssize_t d = 0; d < PyList_Size(shp); d++)
          dims.push_back(PyLong_AsLongLong(PyList_GetItem(shp, d)));
        h->in_shapes.push_back(dims);
        h->in_dtypes.push_back(dtype_code(PyUnicode_AsUTF8(dt)));
      }
    }
    Py_DECREF(sig);
  }
  return h;
}

void* ptpu_clone(void* hp) {
  // ≈ AnalysisPredictor::Clone (analysis_predictor.h): per-thread handle
  // sharing the loaded model; the Python predictor object is stateless
  // across run() calls (a pure compiled function + static signature), so
  // clones share it by reference and own only their output slots.
  Handle* src = (Handle*)hp;
  if (!src || !src->predictor) return nullptr;  // closed/NULL handle
  Gil gil;
  Handle* h = new Handle();
  Py_INCREF(src->predictor);
  h->predictor = src->predictor;
  Py_INCREF(src->np);
  h->np = src->np;
  h->in_names = src->in_names;
  h->in_shapes = src->in_shapes;
  h->in_dtypes = src->in_dtypes;
  return h;
}

const char* ptpu_last_error(void* hp) {
  return ((Handle*)hp)->error.c_str();
}

int ptpu_ok(void* hp) { return ((Handle*)hp)->predictor != nullptr; }

int ptpu_num_inputs(void* hp) {
  return (int)((Handle*)hp)->in_names.size();
}

const char* ptpu_input_name(void* hp, int i) {
  return ((Handle*)hp)->in_names[i].c_str();
}

int ptpu_input_rank(void* hp, int i) {
  return (int)((Handle*)hp)->in_shapes[i].size();
}

const int64_t* ptpu_input_shape(void* hp, int i) {
  return ((Handle*)hp)->in_shapes[i].data();
}

int ptpu_input_dtype(void* hp, int i) {
  return ((Handle*)hp)->in_dtypes[i];
}

int ptpu_run(void* hp, const PtpuTensor* tensors, int n) {
  Handle* h = (Handle*)hp;
  if (!h->predictor) {
    h->error = "predictor not initialized";
    return -1;
  }
  Gil gil;
  clear_outputs(h);
  h->error.clear();

  PyObject* feed = PyList_New(n);
  for (int i = 0; i < n; i++) {
    const PtpuTensor& t = tensors[i];
    int64_t elems = 1;
    for (int d = 0; d < t.rank; d++) elems *= t.shape[d];
    if (t.dtype < 0 || t.dtype >= kNumDtypes) {
      Py_DECREF(feed);
      h->error = "bad input dtype code";
      return -1;
    }
    // itemsize via numpy dtype (handles bfloat16 through ml_dtypes,
    // which importing paddle_tpu/jax registered)
    PyObject* dt = PyObject_CallMethod(h->np, "dtype", "s",
                                       kDtypeNames[t.dtype]);
    if (!dt) {
      Py_DECREF(feed);
      set_py_error(h, "unknown dtype");
      return -1;
    }
    PyObject* isz = PyObject_GetAttrString(dt, "itemsize");
    int64_t nbytes = elems * PyLong_AsLongLong(isz);
    Py_DECREF(isz);

    PyObject* mv = PyMemoryView_FromMemory((char*)t.data, nbytes, PyBUF_READ);
    PyObject* flat =
        PyObject_CallMethod(h->np, "frombuffer", "OO", mv, dt);
    Py_DECREF(mv);
    Py_DECREF(dt);
    PyObject* arr = nullptr;
    if (flat) {
      PyObject* shape = PyTuple_New(t.rank);
      for (int d = 0; d < t.rank; d++)
        PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
      arr = PyObject_CallMethod(flat, "reshape", "O", shape);
      Py_DECREF(shape);
      Py_DECREF(flat);
    }
    if (!arr) {
      Py_DECREF(feed);
      set_py_error(h, "failed to wrap input buffer");
      return -1;
    }
    PyList_SET_ITEM(feed, i, arr);  // steals
  }

  PyObject* outs = PyObject_CallMethod(h->predictor, "run", "O", feed);
  Py_DECREF(feed);
  if (!outs) {
    set_py_error(h, "predictor.run failed");
    return -1;
  }

  Py_ssize_t n_out = PySequence_Size(outs);
  for (Py_ssize_t i = 0; i < n_out; i++) {
    PyObject* o = PySequence_GetItem(outs, i);  // new ref
    PyObject* contig =
        PyObject_CallMethod(h->np, "ascontiguousarray", "O", o);
    Py_DECREF(o);
    if (!contig) {
      Py_DECREF(outs);
      set_py_error(h, "output not convertible");
      return -1;
    }
    Output out;
    out.array = contig;
    PyObject* shp = PyObject_GetAttrString(contig, "shape");
    for (Py_ssize_t d = 0; d < PyTuple_Size(shp); d++)
      out.shape.push_back(PyLong_AsLongLong(PyTuple_GetItem(shp, d)));
    Py_DECREF(shp);
    PyObject* dt = PyObject_GetAttrString(contig, "dtype");
    PyObject* dname = PyObject_GetAttrString(dt, "name");
    out.dtype = dtype_code(PyUnicode_AsUTF8(dname));
    Py_DECREF(dname);
    Py_DECREF(dt);
    PyObject* iface = PyObject_GetAttrString(contig, "ctypes");
    PyObject* ptr = PyObject_GetAttrString(iface, "data");
    out.data = (void*)PyLong_AsUnsignedLongLong(ptr);
    Py_DECREF(ptr);
    Py_DECREF(iface);
    PyObject* nb = PyObject_GetAttrString(contig, "nbytes");
    out.nbytes = PyLong_AsLongLong(nb);
    Py_DECREF(nb);
    h->outputs.push_back(out);
  }
  Py_DECREF(outs);
  return 0;
}

int ptpu_num_outputs(void* hp) {
  return (int)((Handle*)hp)->outputs.size();
}

int ptpu_output_rank(void* hp, int i) {
  return (int)((Handle*)hp)->outputs[i].shape.size();
}

const int64_t* ptpu_output_shape(void* hp, int i) {
  return ((Handle*)hp)->outputs[i].shape.data();
}

int ptpu_output_dtype(void* hp, int i) {
  return ((Handle*)hp)->outputs[i].dtype;
}

const void* ptpu_output_data(void* hp, int i) {
  return ((Handle*)hp)->outputs[i].data;
}

int64_t ptpu_output_nbytes(void* hp, int i) {
  return ((Handle*)hp)->outputs[i].nbytes;
}

void ptpu_destroy(void* hp) {
  Handle* h = (Handle*)hp;
  if (Py_IsInitialized()) {
    Gil gil;
    clear_outputs(h);
    Py_XDECREF(h->predictor);
    Py_XDECREF(h->np);
  }
  delete h;
}

}  // extern "C"

"""Built-in datasets.

Capability-equivalent of python/paddle/dataset/ (mnist, cifar, uci_housing,
imdb, imikolov, wmt, movielens, ... 27 files): each dataset exposes
`train()`/`test()` reader factories yielding numpy samples.

This environment has zero network egress, so each dataset has two paths:
1. If the raw files exist under FLAGS_data_dir (user-provided), load them
   (MNIST idx format, CIFAR pickle, housing csv — same formats the
   reference's download cache stores).
2. Otherwise fall back to a *deterministic synthetic* generator with the
   exact shapes/dtypes/cardinalities of the real dataset, so every model,
   test and benchmark runs hermetically. Synthetic data is seeded and
   learnable (labels correlate with inputs) so convergence tests are
   meaningful, mirroring how the reference's CI uses tiny subsets.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from paddle_tpu.utils.flags import FLAGS

FLAGS.define("data_dir", os.path.expanduser("~/.cache/paddle_tpu/dataset"),
             "Directory holding raw dataset files (reference: "
             "paddle.dataset.common.DATA_HOME).")


# ----------------------------------------------------------------- synthetic

def _synthetic_classification(n: int, shape: Tuple[int, ...], num_classes: int,
                              seed: int, template_seed: int = 1234) -> Callable:
    """Learnable synthetic data: label = argmax over class-template dot
    products + noise. A linear probe reaches high accuracy, so convergence
    tests exercise real optimisation dynamics. `template_seed` fixes the
    class templates so train/test splits (different `seed`) share the same
    underlying concept — like real dataset splits do."""
    def reader() -> Iterator:
        dim = int(np.prod(shape))
        templates = np.random.RandomState(
            template_seed + dim * 31 + num_classes).randn(
            num_classes, dim).astype(np.float32)
        rng = np.random.RandomState(seed)
        for start in range(0, n, 256):
            m = min(256, n - start)
            noise = rng.randn(m, dim).astype(np.float32)
            labels = rng.randint(0, num_classes, size=m)
            x = 0.6 * templates[labels] + noise
            for i in range(m):
                yield x[i].reshape(shape), np.int64(labels[i])
    return reader


def _synthetic_regression(n: int, dim: int, seed: int) -> Callable:
    def reader() -> Iterator:
        rng = np.random.RandomState(seed)
        w = rng.randn(dim).astype(np.float32)
        for _ in range(n):
            x = rng.randn(dim).astype(np.float32)
            y = np.float32(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)
    return reader


# --------------------------------------------------------------------- MNIST

def _mnist_files(prefix: str):
    d = FLAGS.get("data_dir")
    img = os.path.join(d, "mnist", f"{prefix}-images-idx3-ubyte.gz")
    lbl = os.path.join(d, "mnist", f"{prefix}-labels-idx1-ubyte.gz")
    return (img, lbl) if os.path.exists(img) and os.path.exists(lbl) else None


def _mnist_reader(img_path: str, lbl_path: str) -> Callable:
    """Parse the idx format (reference: dataset/mnist.py reader_creator)."""
    def reader() -> Iterator:
        with gzip.open(img_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(lbl_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8)
        for i in range(len(labels)):
            img = images[i].astype(np.float32) / 127.5 - 1.0
            yield img.reshape(28, 28, 1), np.int64(labels[i])
    return reader


def mnist_train(synthetic_n: int = 8192) -> Callable:
    files = _mnist_files("train")
    if files:
        return _mnist_reader(*files)
    return _synthetic_classification(synthetic_n, (28, 28, 1), 10, seed=0)


def mnist_test(synthetic_n: int = 1024) -> Callable:
    files = _mnist_files("t10k")
    if files:
        return _mnist_reader(*files)
    return _synthetic_classification(synthetic_n, (28, 28, 1), 10, seed=1)


# --------------------------------------------------------------------- CIFAR

def cifar10_train(synthetic_n: int = 8192) -> Callable:
    return _synthetic_classification(synthetic_n, (32, 32, 3), 10, seed=2)


def cifar10_test(synthetic_n: int = 1024) -> Callable:
    return _synthetic_classification(synthetic_n, (32, 32, 3), 10, seed=3)


def flowers_train(synthetic_n: int = 2048, image_size: int = 224) -> Callable:
    return _synthetic_classification(
        synthetic_n, (image_size, image_size, 3), 102, seed=4)


# ------------------------------------------------------------------- housing

def uci_housing_train(synthetic_n: int = 404) -> Callable:
    """fit_a_line dataset (reference dataset/uci_housing.py: 13 features)."""
    return _synthetic_regression(synthetic_n, 13, seed=5)


def uci_housing_test(synthetic_n: int = 102) -> Callable:
    return _synthetic_regression(synthetic_n, 13, seed=6)


# ------------------------------------------------------------------ language

def _synthetic_lm(n: int, vocab: int, seq_len: int, seed: int) -> Callable:
    """Markov-chain token streams: next token depends on current, so language
    models have real signal to learn (≈ imikolov capability)."""
    def reader() -> Iterator:
        rng = np.random.RandomState(seed)
        trans = rng.dirichlet(np.ones(vocab) * 0.05, size=vocab)
        for _ in range(n):
            seq = np.empty(seq_len + 1, np.int64)
            seq[0] = rng.randint(vocab)
            for t in range(1, seq_len + 1):
                seq[t] = rng.choice(vocab, p=trans[seq[t - 1]])
            yield seq[:-1], seq[1:]
    return reader


def imikolov_train(vocab: int = 2048, seq_len: int = 20,
                   synthetic_n: int = 4096) -> Callable:
    return _synthetic_lm(synthetic_n, vocab, seq_len, seed=7)


def imdb_train(vocab: int = 5000, seq_len: int = 128,
               synthetic_n: int = 2048) -> Callable:
    """Sentiment classification: ragged sequences + binary label.

    Yields (tokens[int64 seq_len], length, label); label correlates with the
    prevalence of a "positive" token subset so classifiers can learn.
    """
    def reader() -> Iterator:
        rng = np.random.RandomState(8)
        pos_tokens = rng.choice(vocab, vocab // 8, replace=False)
        pos_mask = np.zeros(vocab, bool)
        pos_mask[pos_tokens] = True
        for _ in range(synthetic_n):
            length = rng.randint(seq_len // 4, seq_len + 1)
            label = rng.randint(2)
            if label:
                probs = np.where(pos_mask, 4.0, 1.0)
            else:
                probs = np.where(pos_mask, 0.25, 1.0)
            probs = probs / probs.sum()
            toks = rng.choice(vocab, size=length, p=probs)
            padded = np.zeros(seq_len, np.int64)
            padded[:length] = toks
            yield padded, np.int64(length), np.int64(label)
    return reader


def wmt_synthetic(src_vocab: int = 4096, trg_vocab: int = 4096,
                  seq_len: int = 32, synthetic_n: int = 2048,
                  seed: int = 9) -> Callable:
    """Translation pairs where target is a learnable function of source
    (token-wise affine map mod vocab) — stands in for wmt14/16."""
    def reader() -> Iterator:
        rng = np.random.RandomState(seed)
        perm = rng.permutation(src_vocab) % trg_vocab
        for _ in range(synthetic_n):
            n = rng.randint(seq_len // 2, seq_len + 1)
            src = np.zeros(seq_len, np.int64)
            trg = np.zeros(seq_len, np.int64)
            toks = rng.randint(1, src_vocab, size=n)
            src[:n] = toks
            trg[:n] = perm[toks]
            yield src, np.int64(n), trg
    return reader


# ----------------------------------------------------------------------- CTR

def ctr_synthetic(num_fields: int = 26, vocab_per_field: int = 1000,
                  dense_dim: int = 13, synthetic_n: int = 8192,
                  seed: int = 10) -> Callable:
    """Criteo-style CTR rows: dense features + sparse categorical ids +
    click label (≈ dataset used by dist_ctr.py / DeepFM in BASELINE)."""
    def reader() -> Iterator:
        rng = np.random.RandomState(seed)
        field_w = rng.randn(num_fields, vocab_per_field).astype(np.float32)
        dense_w = rng.randn(dense_dim).astype(np.float32)
        for _ in range(synthetic_n):
            dense = rng.randn(dense_dim).astype(np.float32)
            ids = rng.randint(0, vocab_per_field, size=num_fields)
            logit = dense @ dense_w * 0.3 + field_w[
                np.arange(num_fields), ids].sum() * 0.3
            label = np.int64(rng.rand() < 1 / (1 + np.exp(-logit)))
            yield dense, ids.astype(np.int64), label
    return reader

from paddle_tpu.data import readers, datasets
from paddle_tpu.data.readers import (
    batch, buffered, cache, chain, compose, firstn, map_readers, shuffle,
    xmap_readers,
)
from paddle_tpu.data.feeder import DataFeeder, device_prefetch

"""Fused vocab-projection + softmax cross-entropy (chunked over V).

The reference computes LM losses as two graph ops — a [N, D] x [D, V]
`mul` producing full logits, then `softmax_with_cross_entropy`
(softmax_with_cross_entropy_op.cc) — so the [N, V] logits tensor (and its
gradient) round-trips HBM twice per step. At Transformer-base WMT scale
(N = 64x256 tokens, V = 32k) that is ~1 GB bf16 of pure bandwidth each
way on a chip whose usual limiter IS bandwidth.

This op never materializes [N, V]: it scans the vocabulary in chunks,
keeping an online (running-max, running-sum-of-exp) softmax state — the
same trick flash attention plays over keys, applied to the classifier
axis. The backward pass recomputes each chunk's logits from the saved
activations and the forward's logsumexp, forming (softmax - onehot) * g
one chunk at a time. Peak extra memory is O(N * chunk) instead of
O(N * V); matmul FLOPs are identical to the unfused pair.

Numerics: chunk logits are accumulated on the MXU in f32
(`preferred_element_type`), the online-softmax state is f32, and the
chunked-backward matmuls cast (softmax - onehot) to the activation dtype
— the same precision story as the unfused bf16-matmul + f32-CE path it
replaces (ops/functional.py softmax_with_cross_entropy).

Hard labels only (`ignore_index` rows contribute zero loss and zero
gradient); soft labels would force a second [N, V] operand, defeating
the point.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["linear_cross_entropy", "effective_chunk", "DEFAULT_CHUNK"]

DEFAULT_CHUNK = 8192  # default vocab tile width

_NEG = -1e30  # effectively -inf for padded vocab columns, exp() == 0


def _num_chunks(v: int, chunk: int) -> int:
    return -(-v // chunk)


def effective_chunk(v: int, chunk: int = DEFAULT_CHUNK) -> int:
    """The vocab tile width linear_cross_entropy will actually scan for a
    V-column classifier: `chunk` clamped to V rounded up to the 256-lane
    granule. Single source of truth for FLOPs accounting (benchmark/
    models.py MFU correction) — keep in sync with linear_cross_entropy."""
    return min(chunk, _num_chunks(v, 256) * 256)


def mfu_flops_correction(n_tokens: int, dim: int, vocab: int,
                         chunk: int = DEFAULT_CHUNK) -> float:
    """Analytic FLOPs to ADD to a compiled-executable count so a step
    using linear_cross_entropy reports MFU on the same model-FLOPs basis
    as the unfused head (remat convention: recompute is not useful work).

    Unfused head path = 6*N*D*V (fwd logits + two bwd matmuls). XLA's
    cost analysis counts each fused-CE scan body exactly once: fwd
    2*N*D*chunk + bwd 6*N*D*chunk (recompute, dl@wc^T, h^T@dl) =
    8*N*D*chunk already counted. Negative when the whole vocab fits one
    chunk (counted recompute exceeds the model basis) — still correct."""
    c = effective_chunk(vocab, chunk)
    return float(n_tokens) * dim * (6.0 * vocab - 8.0 * c)


def _vma_up(x, *refs):
    """Inside a check_vma=True shard_map region (pipeline_stream_1f1b),
    scan carries must enter with the varying-axes type the body
    produces; pcast the invariant init zeros up to the union of the
    data operands' vma. A no-op everywhere else (empty vma)."""
    try:
        have = jax.typeof(x).vma
        need = frozenset().union(
            *[jax.typeof(r).vma for r in refs if r is not None]) - have
    except Exception:  # older jax: no vma tracking
        return x
    if not need:
        return x
    return lax.pcast(x, tuple(sorted(need)), to="varying")


def _chunk_logits(h, w, b, i, chunk):
    """f32 logits for vocab chunk i: [N, chunk], padded cols forced to
    -inf. w is pre-padded to a chunk multiple by the wrapper."""
    wc = lax.dynamic_slice_in_dim(w, i * chunk, chunk, axis=1)
    logits = lax.dot_general(h, wc, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + lax.dynamic_slice_in_dim(
            b, i * chunk, chunk).astype(jnp.float32)
    return logits


def _pad_v(w, b, v_pad):
    v = w.shape[1]
    if v_pad == v:
        return w, b
    w = jnp.pad(w, ((0, 0), (0, v_pad - v)))
    # bias carries the -inf for padded columns so every chunk is handled
    # uniformly (no per-chunk column masking)
    b = jnp.zeros((v,), jnp.float32) if b is None else b.astype(jnp.float32)
    b = jnp.pad(b, (0, v_pad - v), constant_values=_NEG)
    return w, b


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _lce(h, w, b, labels, chunk, ignore_index):
    loss, _ = _lce_fwd(h, w, b, labels, chunk, ignore_index)
    return loss


def _lce_fwd(h, w, b, labels, chunk, ignore_index):
    n = h.shape[0]
    v = w.shape[1]
    v_pad = _num_chunks(v, chunk) * chunk
    wp, bp = _pad_v(w, b, v_pad)
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0).astype(jnp.int32)

    def body(carry, i):
        m, s, tgt = carry
        logits = _chunk_logits(h, wp, bp, i, chunk)          # [N, chunk] f32
        cmax = jnp.max(logits, axis=1)
        nm = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - nm) + jnp.sum(jnp.exp(logits - nm[:, None]),
                                          axis=1)
        loc = safe - i * chunk
        hit = (loc >= 0) & (loc < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(loc, 0, chunk - 1)[:, None], axis=1)[:, 0]
        tgt = jnp.where(hit, picked, tgt)
        return (nm, s, tgt), None

    init = tuple(_vma_up(x, h, w, b, labels) for x in (
        jnp.full((n,), _NEG, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32)))
    (m, s, tgt), _ = lax.scan(body, init,
                              jnp.arange(_num_chunks(v, chunk)))
    lse = m + jnp.log(s)
    loss = jnp.where(valid, lse - tgt, 0.0)
    return loss, (h, w, b, safe, valid, lse)


def _lce_bwd(chunk, ignore_index, res, g):
    h, w, b, safe, valid, lse = res
    v = w.shape[1]
    v_pad = _num_chunks(v, chunk) * chunk
    wp, bp = _pad_v(w, b, v_pad)
    gv = (g * valid).astype(jnp.float32)

    def body(carry, i):
        dh, dw = carry
        logits = _chunk_logits(h, wp, bp, i, chunk)          # recompute
        p = jnp.exp(logits - lse[:, None])                   # softmax chunk
        loc = safe - i * chunk
        hit = (loc >= 0) & (loc < chunk)
        onehot = (jax.nn.one_hot(jnp.clip(loc, 0, chunk - 1), chunk,
                                 dtype=jnp.float32)
                  * hit[:, None].astype(jnp.float32))
        dl = ((p - onehot) * gv[:, None]).astype(h.dtype)    # [N, chunk]
        wc = lax.dynamic_slice_in_dim(wp, i * chunk, chunk, axis=1)
        dh = dh + lax.dot_general(dl, wc, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dwc = lax.dot_general(h, dl, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        dw = lax.dynamic_update_slice_in_dim(dw, dwc, i * chunk, axis=1)
        return (dh, dw), jnp.sum(dl.astype(jnp.float32), axis=0)

    init = tuple(_vma_up(x, h, w, b, g, safe) for x in (
        jnp.zeros(h.shape, jnp.float32),
        jnp.zeros((h.shape[1], v_pad), jnp.float32)))
    (dh, dw), dbs = lax.scan(body, init,
                             jnp.arange(_num_chunks(v, chunk)))
    db = None if b is None else dbs.reshape(-1)[:v].astype(b.dtype)
    return (dh.astype(h.dtype), dw[:, :v].astype(w.dtype), db, None)


_lce.defvjp(_lce_fwd, _lce_bwd)


def linear_cross_entropy(h, w, labels, b=None, *, chunk: int = DEFAULT_CHUNK,
                         ignore_index: int = -100):
    """Per-token CE of `softmax(h @ w + b)` against hard `labels`,
    without materializing the [N, V] logits.

    h: [..., D] activations; w: [D, V]; b: [V] or None; labels: [...]
    int. Returns f32 loss shaped like `labels`. `chunk` is the vocab
    tile width (padded internally when V % chunk != 0). Equivalent to
    ``softmax_with_cross_entropy(h @ w + b, labels)`` (tested to 2e-3
    in bf16, 1e-5 in f32) at O(N * chunk) extra memory.
    """
    lead = labels.shape
    d = h.shape[-1]
    if h.shape[:-1] != lead:
        raise ValueError(f"h leading dims {h.shape[:-1]} != labels "
                         f"shape {lead}")
    chunk = effective_chunk(w.shape[1], chunk)
    loss = _lce(h.reshape(-1, d), w,
                None if b is None else b,
                labels.reshape(-1).astype(jnp.int32), chunk, ignore_index)
    return loss.reshape(lead)

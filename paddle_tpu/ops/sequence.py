"""Ragged-sequence ops — the TPU-native replacement for LoD.

Capability-equivalent of the reference's LoD machinery + sequence op family:
- LoDTensor offset tables (framework/lod_tensor.h:58): variable-length
  sequences concatenated with nesting offsets. TPU idiom: EITHER dense
  padded [batch, max_len, ...] with `lengths`, OR packed [total, ...] with
  `segment_ids` — both static-shaped, XLA-friendly; conversions below.
- operators/sequence_ops/ (18 ops): sequence_pool, sequence_softmax,
  sequence_expand, sequence_concat, sequence_reverse, sequence_pad/unpad,
  sequence_mask, sequence_first/last_step, sequence_erase,
  sequence_enumerate, sequence_conv, sequence_slice, sequence_scatter.

All functions are jit-safe with static shapes; `num_segments`/`maxlen` are
static ints. Masked/segment formulations replace the reference's per-sequence
C++ loops with vectorised MXU/VPU-friendly compute.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e9


# --------------------------------------------------------- padded <-> packed

class Ragged(NamedTuple):
    """Packed ragged batch: rows of all sequences concatenated.

    data: [total, ...]; segment_ids: [total] int32 (row -> sequence index,
    padding rows get `num_segments`); lengths: [batch].
    Same information as a level-1 LoD (lod_tensor.h:44-58 offsets), in the
    segment-id form every TPU sparse/ragged kernel expects.
    """
    data: jax.Array
    segment_ids: jax.Array
    lengths: jax.Array

    @property
    def num_segments(self) -> int:
        return self.lengths.shape[0]


def sequence_mask(lengths, maxlen: int, dtype=jnp.bool_):
    """[B] lengths -> [B, maxlen] mask (operators/sequence_ops/
    sequence_mask_op.cc)."""
    pos = jnp.arange(maxlen)
    return (pos[None, :] < lengths[:, None]).astype(dtype)


def pack_padded(x, lengths) -> Ragged:
    """Dense [B, T, ...] + lengths -> packed Ragged with total = B*T rows
    (padding rows keep segment_id == B so segment ops drop them)."""
    b, t = x.shape[0], x.shape[1]
    mask = sequence_mask(lengths, t)
    seg = jnp.where(mask, jnp.arange(b, dtype=jnp.int32)[:, None], b)
    return Ragged(data=x.reshape((b * t,) + x.shape[2:]),
                  segment_ids=seg.reshape(-1),
                  lengths=lengths)


def pad_packed(r: Ragged, maxlen: int):
    """Packed -> dense [B, maxlen, ...] + mask (sequence_pad_op.cc)."""
    b = r.num_segments
    total = r.data.shape[0]
    # position of each row within its sequence
    onehot = (r.segment_ids[:, None] == jnp.arange(b)[None, :])
    pos_in_seq = jnp.cumsum(onehot, axis=0) - 1
    pos = jnp.take_along_axis(pos_in_seq, r.segment_ids[:, None] % b,
                              axis=1)[:, 0]
    out = jnp.zeros((b, maxlen) + r.data.shape[1:], r.data.dtype)
    valid = r.segment_ids < b
    idx_b = jnp.where(valid, r.segment_ids, 0)
    idx_t = jnp.where(valid, jnp.minimum(pos, maxlen - 1), 0)
    upd = jnp.where(
        valid.reshape((-1,) + (1,) * (r.data.ndim - 1)), r.data, 0)
    out = out.at[idx_b, idx_t].add(upd)
    return out, sequence_mask(r.lengths, maxlen)


# ------------------------------------------------------------- pooling/steps

def sequence_pool(x, lengths, pool_type: str = "sum"):
    """Pool over time of a padded batch [B, T, D] (sequence_pool_op.cc:
    sum/average/sqrt/max/last/first)."""
    t = x.shape[1]
    mask = sequence_mask(lengths, t, x.dtype)[..., None]
    if pool_type == "sum":
        return jnp.sum(x * mask, axis=1)
    if pool_type in ("average", "mean"):
        denom = jnp.maximum(lengths, 1).astype(x.dtype)[:, None]
        return jnp.sum(x * mask, axis=1) / denom
    if pool_type == "sqrt":
        denom = jnp.sqrt(jnp.maximum(lengths, 1).astype(x.dtype))[:, None]
        return jnp.sum(x * mask, axis=1) / denom
    if pool_type == "max":
        neg = jnp.where(mask > 0, x, NEG_INF)
        return jnp.max(neg, axis=1)
    if pool_type == "first":
        return x[:, 0]
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(x, lengths):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths):
    return sequence_pool(x, lengths, "last")


def segment_pool(r: Ragged, pool_type: str = "sum"):
    """Pool a packed Ragged [total, D] -> [B, D] via segment ops (the packed
    counterpart of sequence_pool; XLA lowers segment_sum to one-hot matmul
    on TPU which rides the MXU)."""
    b = r.num_segments
    if pool_type == "sum":
        return jax.ops.segment_sum(r.data, r.segment_ids, num_segments=b + 1
                                   )[:b]
    if pool_type in ("average", "mean"):
        s = jax.ops.segment_sum(r.data, r.segment_ids, num_segments=b + 1)[:b]
        return s / jnp.maximum(r.lengths, 1).astype(s.dtype)[:, None]
    if pool_type == "max":
        return jax.ops.segment_max(r.data, r.segment_ids, num_segments=b + 1
                                   )[:b]
    raise ValueError(f"unknown pool_type {pool_type!r}")


# ---------------------------------------------------------------- softmax

def sequence_softmax(x, lengths):
    """Masked softmax over time [B, T] or [B, T, D]-last-dim=scores
    (sequence_softmax_op.cc)."""
    t = x.shape[1]
    mask = sequence_mask(lengths, t, jnp.bool_)
    shape = (mask.shape[0], t) + (1,) * (x.ndim - 2)
    m = mask.reshape(shape)
    z = jnp.where(m, x, NEG_INF)
    z = z - jnp.max(z, axis=1, keepdims=True)
    e = jnp.exp(z) * m.astype(x.dtype)
    return e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-12)


# ------------------------------------------------------- expand/concat/etc.

def sequence_expand_padded(x, ref_lengths, maxlen: int):
    """x: [B, D] -> [B, maxlen, D] with rows masked beyond ref_lengths."""
    mask = sequence_mask(ref_lengths, maxlen, x.dtype)
    return x[:, None, :] * mask[..., None]


def sequence_expand_as(x, ref_lengths, maxlen: int):
    """Reference sequence_expand_as op (sequence_expand_as_op.cc): repeat
    row i of x ref_lengths[i] times. Padded form: [B, D] -> [B, maxlen, D]
    with positions beyond ref_lengths[i] zeroed (same contract as
    sequence_expand_padded, kept as a named alias for API parity)."""
    return sequence_expand_padded(x, ref_lengths, maxlen)


def sequence_reshape(x, lengths, new_dim: int):
    """Reference sequence_reshape op (sequence_reshape_op.cc): reinterpret
    each sequence's [len_i, D] payload as [len_i*D/new_dim, new_dim].
    Padded form: [B, T, D] -> [B, T*D//new_dim, new_dim] + new lengths.
    Requires (T*D) % new_dim == 0 for the padded buffer."""
    b, t, d = x.shape
    if (t * d) % new_dim != 0:
        raise ValueError("padded payload must divide new_dim")
    new_t = t * d // new_dim
    out = x.reshape(b, new_t, new_dim)
    new_lengths = (lengths * d) // new_dim
    mask = sequence_mask(new_lengths, new_t, x.dtype)
    return out * mask[..., None], new_lengths


def sequence_scatter(x, index, updates, updates_lengths):
    """Reference sequence_scatter op (sequence_scatter_op.cc): per sample i,
    x[i, index[i, j]] += updates[i, j] for j < updates_lengths[i].
    x: [B, N]; index/updates: [B, T]."""
    b, t = index.shape
    mask = sequence_mask(updates_lengths, t, updates.dtype)
    upd = updates * mask
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    return x.at[bidx, index.astype(jnp.int32)].add(upd)


def sequence_reverse(x, lengths):
    """Reverse valid prefix of each row [B, T, ...]
    (sequence_reverse_op.cc)."""
    t = x.shape[1]
    pos = jnp.arange(t)
    rev_idx = lengths[:, None] - 1 - pos[None, :]
    idx = jnp.where(rev_idx >= 0, rev_idx, pos[None, :])
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1)


def sequence_concat(xs, lengths_list, maxlen: int):
    """Concatenate per-sample sequences from several padded batches
    (sequence_concat_op.cc). Returns padded [B, maxlen, D] + new lengths."""
    b = xs[0].shape[0]
    d_shape = xs[0].shape[2:]
    out = jnp.zeros((b, maxlen) + d_shape, xs[0].dtype)
    total = jnp.zeros((b,), lengths_list[0].dtype)
    for x, lens in zip(xs, lengths_list):
        t = x.shape[1]
        mask = sequence_mask(lens, t, jnp.bool_)
        tpos = total[:, None] + jnp.arange(t)[None, :]
        idx_t = jnp.where(mask, tpos, maxlen - 1).astype(jnp.int32)
        upd = jnp.where(mask.reshape(mask.shape + (1,) * len(d_shape)), x, 0)
        bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
        out = out.at[bidx, idx_t].add(upd)
        total = total + lens
    return out, total


def sequence_slice(x, lengths, offset, length):
    """Per-sequence slice (sequence_slice_op.cc): take `length[i]` steps from
    `offset[i]` of each row. Output padded to static max `length` bound."""
    t = x.shape[1]
    max_out = int(length) if jnp.ndim(length) == 0 else t
    starts = jnp.broadcast_to(jnp.asarray(offset), lengths.shape)
    lens = jnp.broadcast_to(jnp.asarray(length), lengths.shape)
    pos = jnp.arange(max_out)
    idx = jnp.minimum(starts[:, None] + pos[None, :], t - 1).astype(jnp.int32)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return out, lens


def sequence_erase(tokens, lengths, erase_tokens):
    """Remove given token values, left-compacting each row; returns new
    padded tokens + new lengths (sequence_erase_op.cc). tokens: [B, T]."""
    t = tokens.shape[1]
    keep = sequence_mask(lengths, t, jnp.bool_)
    for e in erase_tokens:
        keep = keep & (tokens != e)
    new_len = jnp.sum(keep, axis=1)
    # stable left-compaction: target position of each kept token
    target = jnp.cumsum(keep, axis=1) - 1
    out = jnp.zeros_like(tokens)
    bidx = jnp.broadcast_to(jnp.arange(tokens.shape[0])[:, None],
                            tokens.shape)
    tgt = jnp.where(keep, target, t - 1).astype(jnp.int32)
    upd = jnp.where(keep, tokens, 0)
    out = out.at[bidx, tgt].max(upd)
    # zero any tail garbage
    out = out * sequence_mask(new_len, t, tokens.dtype)
    return out, new_len


def sequence_enumerate(tokens, lengths, win_size: int, pad_value: int = 0):
    """Sliding windows of ids (sequence_enumerate_op.cc): [B, T] ->
    [B, T, win_size]; positions past each row's length get pad_value."""
    t = tokens.shape[1]
    idx = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]
    valid = idx < lengths[:, None, None]
    idxc = jnp.minimum(idx, t - 1)
    wins = tokens[:, idxc]
    return jnp.where(valid, wins, pad_value)


def sequence_conv(x, lengths, filter_w, context_size: int = 3,
                  context_start: Optional[int] = None):
    """Context-window convolution over time (sequence_conv_op.cc +
    math/context_project.h): concatenate a window of steps then project.
    x: [B, T, D]; filter_w: [context_size*D, out]. Windows never cross
    sequence boundaries (padding is masked)."""
    b, t, d = x.shape
    start = -(context_size // 2) if context_start is None else context_start
    mask = sequence_mask(lengths, t, x.dtype)[..., None]
    xm = x * mask
    cols = []
    for k in range(context_size):
        shift = start + k
        rolled = jnp.roll(xm, -shift, axis=1)
        pos = jnp.arange(t) + shift
        ok = ((pos >= 0) & (pos < t)).astype(x.dtype)[None, :, None]
        cols.append(rolled * ok)
    ctx = jnp.concatenate(cols, axis=-1)          # [B, T, ctx*D]
    out = jnp.einsum("btc,co->bto", ctx, filter_w)
    return out * mask


# ---------------------------------------------------------------- shrinking

def shrink_memory(state, step: int, rank_lengths):
    """DynamicRNN memory-shrink capability (shrink_memory op,
    control_flow.py:963): zero out rows whose sequence already ended at
    `step` — in static-shape land we mask instead of physically shrinking."""
    alive = (rank_lengths > step)
    shape = (state.shape[0],) + (1,) * (state.ndim - 1)
    return state * alive.reshape(shape).astype(state.dtype)

from paddle_tpu.ops.functional import *  # noqa: F401,F403
from paddle_tpu.ops import functional, sequence
from paddle_tpu.ops.beam_search import BeamResult, beam_search, tile_beams

from paddle_tpu.ops.functional import *  # noqa: F401,F403
from paddle_tpu.ops import control_flow, detection, functional, sequence
from paddle_tpu.ops.beam_search import BeamResult, beam_search, tile_beams
from paddle_tpu.ops.control_flow import (
    case, cond, fori_loop, piecewise, static_rnn, switch, while_loop)

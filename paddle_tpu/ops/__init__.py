from paddle_tpu.ops.functional import *  # noqa: F401,F403
from paddle_tpu.ops import functional

"""Self-speculative drafting: prompt-lookup / n-gram proposal.

Decode is memory-bound — every step streams the whole KV working set
to emit ONE token per sequence — so the ragged step has compute to
spare. Speculative decoding spends that headroom: propose k tokens
cheaply, verify all k in one batched launch (the same multi-token
StepRow shape a prefill chunk uses), and emit every accepted token.
The net is fewer steps per token at EXACTLY the same output
(engine.py's verification accepts a draft token only when it equals
the token the target distribution would have sampled anyway).

This drafter is MODEL-FREE (no second network, no extra weights in
HBM): it proposes by PROMPT LOOKUP — find the most recent earlier
occurrence of the sequence's own trailing n-gram and propose the
tokens that followed it. That exploits the repetition structure real
serving traffic is full of (quoted context in RAG answers, code
identifiers, boilerplate, chat turns echoing the prompt): when the
model is about to copy a span it has already seen, the lookup predicts
it perfectly and a whole span verifies in one step. When history never
repeats, the drafter proposes nothing and the engine falls back to
plain one-token decode — speculation can make a step emit more, never
make output different.

Pure host code on Python lists; nothing here touches jax, so drafting
can never add a compile or a device sync.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class NgramDrafter:
    """Prompt-lookup drafter: longest-match-first over the request's
    own token history.

    `propose(tokens)` scans for PRIOR occurrences of the history's
    trailing n-gram, trying n = max_ngram down to min_ngram (a longer
    match is stronger evidence the continuation repeats), and returns
    up to `k` tokens that followed the chosen occurrence. Among
    occurrences of the same n, the most recent one with a FULL k-token
    continuation wins — recent repetition predicts the immediate
    future better than distant repetition, but a match flush against
    the tail only has the tail's leftovers to offer (a constant run
    would draft a single token forever), so matches whose continuation
    is cut short by the end of history defer to earlier ones that can
    fill the window. When no occurrence has a full window, the longest
    available continuation wins (most recent on ties). Deterministic
    throughout.

    Returns [] when nothing matches; the scheduler then plans a plain
    1-token decode row.
    """

    def __init__(self, k: int = 4, max_ngram: int = 3,
                 min_ngram: int = 1):
        if k < 1:
            raise ValueError(f"k {k} < 1")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram ({min_ngram}) <= max_ngram "
                f"({max_ngram})")
        self.k = k
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, tokens: Sequence[int],
                max_tokens: Optional[int] = None) -> List[int]:
        """Draft up to min(k, max_tokens) continuation tokens for a
        sequence whose full history (prompt + generated) is `tokens`."""
        cap = self.k if max_tokens is None else min(self.k, max_tokens)
        n_hist = len(tokens)
        if cap < 1 or n_hist < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_hist - 1),
                       self.min_ngram - 1, -1):
            pattern = tuple(tokens[n_hist - n:])
            best: List[int] = []
            # most recent PRIOR occurrence with a full cap-token
            # continuation; the match must end before the history's
            # tail so at least one continuation token exists
            for i in range(n_hist - n - 1, -1, -1):
                if tuple(tokens[i:i + n]) == pattern:
                    cont = list(tokens[i + n:i + n + cap])
                    if len(cont) == cap:
                        return cont
                    if len(cont) > len(best):
                        best = cont
            if best:
                return best
        return []

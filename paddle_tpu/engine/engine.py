"""ServeEngine: the online inference serve loop.

Ties the subsystem together (ENGINE.md): a `PagedKVCache` holds KV
state in block pools, a `Scheduler` plans one prefill or decode batch
per step, and this engine compiles + executes the steps, samples
tokens host-side, streams them to per-request callbacks, and emits
structured `serve_event` JSON (utils/log.py) for observability.

Shape discipline — the one-compilation rule: continuous batching
mutates batch membership every step, which naively means a fresh XLA
compile every step. Instead every device call runs at a FIXED shape:

- decode is always [max_batch_size] rows; empty rows are padding that
  reads/writes the reserved scratch block 0 (context_len 1, slot 0) so
  they can never touch a live sequence. One compile, ever.
- prefill is always [max_batch_size, T] with T bucketed to the next
  power of two — one compile per bucket, O(log max_seq_len) total.

Padding rows cost FLOPs but rows of a batch are computed independently
by every op in the model, so a request's logits are bit-identical
whether it shares the batch or runs alone — this is what makes
continuous batching safe to verify token-for-token against sequential
decode (tests/test_engine.py), not just "close".

Sampling runs on host from the [B, V] logits (greedy / temperature /
top-k). Stochastic sampling derives its rng stream from
(request seed, absolute position), never from batch composition, so
scheduling decisions can't change a request's output.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Context, _CtxCore
from paddle_tpu.engine.paged_cache import PagedKVCache
from paddle_tpu.engine.scheduler import Request, Scheduler
from paddle_tpu.utils.log import serve_event


def _fresh_cx(variables) -> Context:
    return Context(_CtxCore(mode="apply", variables=variables, mutated={},
                            rng=None, rng_count=0, training=False))


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def serve_metadata(model) -> dict:
    """Introspect a CausalLM into the manifest `serve` block
    (io/inference.py `save_inference_model(..., serve_meta=...)`):
    everything `ServeEngine.from_saved_model` needs to rebuild the
    module and size its KV pools without touching the checkpoint."""
    attn = model.blocks[0].attn
    return {
        "model_type": "causal_lm",
        "vocab": model.vocab,
        "model_dim": model.model_dim,
        "num_heads": attn.num_heads,
        "num_kv_heads": attn.num_kv_heads,
        "head_dim": attn.head_dim,
        "num_layers": len(model.blocks),
        "ffn_dim": model.blocks[0].ffn.fc1.features,
        "max_len": model.max_len,
        "tie_embeddings": model.tie_embeddings,
        "fused_qkv": attn.fused_qkv,
    }


def _sample(logits: np.ndarray, req: Request, pos: int) -> int:
    """Host-side sampling for one row. Deterministic in (req.seed, pos):
    the same request samples the same token at the same position no
    matter what batch it rode in."""
    if req.temperature <= 0.0:
        return int(np.argmax(logits))
    z = logits.astype(np.float64) / req.temperature
    if 0 < req.top_k < z.size:
        kth = np.partition(z, -req.top_k)[-req.top_k]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    rng = np.random.default_rng([req.seed & 0x7FFFFFFF, pos])
    return int(rng.choice(z.size, p=p))


class ServeEngine:
    """Continuous-batching serve loop over a CausalLM.

    add_request() enqueues; step() advances the world by one scheduler
    plan (one prefill or decode batch); run() drains the queue. Token
    callbacks fire as tokens are sampled — streaming falls out of
    iteration-level scheduling for free.
    """

    def __init__(self, model, variables, max_batch_size: int = 4,
                 block_size: int = 16, num_blocks: int = 256,
                 max_seq_len: Optional[int] = None,
                 max_prefill_tokens: int = 512,
                 min_prefill_bucket: int = 16):
        self.model = model
        self.variables = variables
        attn = model.blocks[0].attn
        self.max_seq_len = min(max_seq_len or model.max_len, model.max_len)
        self.max_batch_size = max_batch_size
        self.min_prefill_bucket = min_prefill_bucket
        self.cache = PagedKVCache(
            num_layers=len(model.blocks), num_blocks=num_blocks,
            block_size=block_size, num_kv_heads=attn.num_kv_heads,
            head_dim=attn.head_dim, dtype=model.dtype)
        self.max_blocks_per_seq = self.cache.blocks_for(self.max_seq_len)
        self.scheduler = Scheduler(
            self.cache, max_batch_size=max_batch_size,
            max_prefill_tokens=max_prefill_tokens,
            max_seq_len=self.max_seq_len - 1)  # leave room for >=1 new token
        self.scheduler.on_preempt = self._on_preempt
        self.finished: Dict[int, Request] = {}
        self.steps = 0

        model_ = model

        @jax.jit
        def _prefill(variables, tokens, last_pos):
            logits, kvs = model_.prefill_paged(_fresh_cx(variables), tokens,
                                               last_pos)
            return logits, kvs

        @jax.jit
        def _scatter(pools, kvs, slots):
            new_pools = []
            for (kp, vp), (k, v) in zip(pools, kvs):
                flat = (kp.shape[0] * kp.shape[1],) + kp.shape[2:]
                kf = k.reshape((-1,) + k.shape[2:]).astype(kp.dtype)
                vf = v.reshape((-1,) + v.shape[2:]).astype(vp.dtype)
                new_pools.append((
                    kp.reshape(flat).at[slots].set(kf).reshape(kp.shape),
                    vp.reshape(flat).at[slots].set(vf).reshape(vp.shape)))
            return new_pools

        @jax.jit
        def _decode(variables, tokens, positions, pools, block_tables,
                    context_lens, slots):
            return model_.decode_step_paged(
                _fresh_cx(variables), tokens, positions, pools,
                block_tables, context_lens, slots)

        self._prefill = _prefill
        self._scatter = _scatter
        self._decode = _decode

    # -- construction from an exported artifact ---------------------------
    @classmethod
    def from_saved_model(cls, model_dir: str, **engine_kwargs):
        """Build model + engine from a save_inference_model() directory
        whose manifest carries the `serve` block (serve_metadata)."""
        import json
        import os

        from paddle_tpu.io.checkpoint import load_checkpoint
        from paddle_tpu.models.transformer import CausalLM

        with open(os.path.join(model_dir, "signature.json")) as f:
            sig = json.load(f)
        meta = sig.get("serve")
        if meta is None:
            raise ValueError(
                f"{model_dir} has no `serve` metadata in its manifest; "
                "re-export with save_inference_model(..., "
                "serve_meta=serve_metadata(model))")
        model = CausalLM(
            vocab=meta["vocab"], model_dim=meta["model_dim"],
            num_heads=meta["num_heads"], num_layers=meta["num_layers"],
            ffn_dim=meta["ffn_dim"], dropout=0.0, max_len=meta["max_len"],
            tie_embeddings=meta["tie_embeddings"],
            fused_qkv=meta["fused_qkv"],
            num_kv_heads=meta["num_kv_heads"])
        variables = load_checkpoint(os.path.join(model_dir, "params"))
        engine_kwargs.setdefault("max_seq_len", meta["max_len"])
        return cls(model, variables, **engine_kwargs)

    # -- intake -----------------------------------------------------------
    def add_request(self, prompt: List[int], max_new_tokens: int = 32,
                    temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                    eos_id: Optional[int] = None,
                    callback: Optional[Callable[[int], None]] = None
                    ) -> Request:
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + 1 > self.max_seq_len:
            raise ValueError(f"prompt len {len(prompt)} leaves no room to "
                             f"generate under max_seq_len {self.max_seq_len}")
        if len(prompt) > self.scheduler.max_prefill_tokens:
            raise ValueError(
                f"prompt len {len(prompt)} exceeds max_prefill_tokens "
                f"{self.scheduler.max_prefill_tokens}; it could never admit")
        req = Request(prompt=list(prompt), max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k, seed=seed,
                      eos_id=eos_id, callback=callback)
        req.enqueue_time = time.monotonic()
        self.scheduler.add(req)
        serve_event("serve_admit", req_id=req.req_id,
                    prompt_len=len(prompt),
                    queue_depth=self.scheduler.queue_depth)
        return req

    # -- serve loop --------------------------------------------------------
    def step(self) -> bool:
        """Advance one scheduler plan. Returns False when idle."""
        plan = self.scheduler.next_batch()
        if plan is None:
            return False
        kind, reqs = plan
        self.steps += 1
        if kind == "prefill":
            self._step_prefill(reqs)
        else:
            self._step_decode(reqs)
        return True

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue; returns {req_id: generated token ids}."""
        while self.step():
            pass
        return {rid: self._generated_of(r)
                for rid, r in self.finished.items()}

    # -- internals ---------------------------------------------------------
    def _step_prefill(self, reqs: List[Request]) -> None:
        n = self.max_batch_size
        t_real = max(len(r.tokens) for r in reqs)
        t_pad = max(_next_pow2(t_real), self.min_prefill_bucket)
        t_pad = min(t_pad, self.model.max_len)   # bucket cap: pe table length
        tokens = np.zeros((n, t_pad), np.int32)
        last_pos = np.zeros((n,), np.int32)
        # padded rows / positions scatter into scratch block 0 (slot < bs)
        slots = np.zeros((n * t_pad,), np.int32)
        for i, r in enumerate(reqs):
            toks = r.tokens
            tokens[i, :len(toks)] = toks
            last_pos[i] = len(toks) - 1
            for p in range(len(toks)):
                slots[i * t_pad + p] = self.cache.slot_of(r.req_id, p)
        logits, kvs = self._prefill(self.variables, jnp.asarray(tokens),
                                    jnp.asarray(last_pos))
        self.cache.pools = self._scatter(self.cache.pools, kvs,
                                         jnp.asarray(slots))
        logits = np.asarray(logits)
        now = time.monotonic()
        for i, r in enumerate(reqs):
            tok = _sample(logits[i], r, len(r.tokens))
            if not r.first_token_time:
                r.first_token_time = now
            self._emit_token(r, tok)
        serve_event("serve_prefill", batch=len(reqs), padded_t=t_pad,
                    step=self.steps, occupancy=round(self.cache.occupancy(), 4),
                    queue_depth=self.scheduler.queue_depth)

    def _step_decode(self, reqs: List[Request]) -> None:
        b = self.max_batch_size
        mb = self.max_blocks_per_seq
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        context_lens = np.ones((b,), np.int32)   # pad rows: 1 token of scratch
        block_tables = np.zeros((b, mb), np.int32)
        slots = np.zeros((b,), np.int32)
        for i, r in enumerate(reqs):
            pos = self.cache.seq_len(r.req_id)   # next-token position
            tokens[i] = r.generated[-1]
            positions[i] = pos
            context_lens[i] = pos + 1
            block_tables[i] = self.cache.padded_table(r.req_id, mb)
            slots[i] = self.cache.slot_of(r.req_id, pos)
        logits, self.cache.pools = self._decode(
            self.variables, jnp.asarray(tokens), jnp.asarray(positions),
            self.cache.pools, jnp.asarray(block_tables),
            jnp.asarray(context_lens), jnp.asarray(slots))
        logits = np.asarray(logits)
        for i, r in enumerate(reqs):
            self.cache.advance(r.req_id)
            tok = _sample(logits[i], r, self.cache.seq_len(r.req_id))
            self._emit_token(r, tok)
        serve_event("serve_decode", batch=len(reqs), step=self.steps,
                    occupancy=round(self.cache.occupancy(), 4),
                    queue_depth=self.scheduler.queue_depth)

    def _emit_token(self, req: Request, tok: int) -> None:
        req.generated.append(tok)
        if req.callback is not None:
            req.callback(tok)
        hit_eos = req.eos_id is not None and tok == req.eos_id
        out_of_room = (len(req.tokens) >= self.max_seq_len - 1)
        if hit_eos or req.num_generated >= req.max_new_tokens or out_of_room:
            self._finish(req, "eos" if hit_eos else "length")

    def _finish(self, req: Request, reason: str) -> None:
        req.finish_time = time.monotonic()
        self.scheduler.finish(req, reason)
        self.finished[req.req_id] = req
        ttft_ms = (req.first_token_time - req.enqueue_time) * 1e3
        decode_s = max(req.finish_time - req.first_token_time, 1e-9)
        n_gen = req.num_generated
        serve_event("serve_done", req_id=req.req_id, reason=reason,
                    tokens=n_gen, ttft_ms=round(ttft_ms, 3),
                    decode_tok_s=round(max(n_gen - 1, 0) / decode_s, 2),
                    preemptions=req.preemptions)

    def _on_preempt(self, req: Request) -> None:
        serve_event("serve_preempt", req_id=req.req_id,
                    kept_tokens=len(req.prompt),
                    occupancy=round(self.cache.occupancy(), 4))

    # -- convenience --------------------------------------------------------
    def generate(self, prompts: List[List[int]], max_new_tokens: int = 32,
                 **kwargs) -> List[List[int]]:
        """Batch-submit prompts, drain, return generations in order."""
        reqs = [self.add_request(p, max_new_tokens=max_new_tokens, **kwargs)
                for p in prompts]
        self.run()
        return [self._generated_of(r) for r in reqs]

    @staticmethod
    def _generated_of(req: Request) -> List[int]:
        """All tokens generated for a request, reassembling the ones a
        preemption folded into the prompt."""
        if req.preempt_carry:
            carried = req.prompt[len(req.prompt) - req.preempt_carry:]
            return list(carried) + list(req.generated)
        return list(req.generated)

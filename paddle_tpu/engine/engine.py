"""ServeEngine: the online inference serve loop.

Ties the subsystem together (ENGINE.md): a refcounted `PagedKVCache`
holds KV state in block pools (prefix-shared, copy-on-write), a
`Scheduler` plans one MIXED batch per step (decode rows + prefill
chunks), and this engine compiles + executes the steps, samples tokens
host-side, streams them to per-request callbacks, and emits structured
`serve_event` JSON (utils/log.py) for observability.

Shape discipline — the one-compilation rule: continuous batching
mutates batch membership every step, which naively means a fresh XLA
compile every step. Instead every device call runs at a FIXED shape:

- EVERY step is one flat ragged launch: the step's rows — decode rows
  (a 1-token window) and prefill chunks (a budget-bounded window of
  the prompt) — are packed into a single [T] token array, T =
  round_up(chunk_budget, tile_q) + max_batch_size * tile_q, with each
  row's tokens in a tile_q-aligned segment. Per-tile metadata maps
  tiles back to rows (kernels/paged_attention.py
  `ragged_paged_attention`). Row membership, chunk boundaries and
  prefix-cache hits only change int32 operands, never the shape: ONE
  compile, ever — no more pow2 chunk buckets and no separate decode
  step. Pad positions scatter to the reserved scratch block 0
  (context_len 1, slot 0) so they can never touch a live sequence.
- COW block copies run through one fixed-width compiled
  gather/scatter (`_copy_blocks`); unused lanes copy scratch block 0
  onto itself.

Padding rows cost FLOPs but rows of a batch are computed independently
by every op in the model, so a request's logits are bit-identical
whether it shares the batch or runs alone — this is what makes
continuous batching safe to verify token-for-token against sequential
decode (tests/test_engine.py), not just "close". Prefix sharing keeps
the same guarantee: a shared block's KV was computed from the same
tokens at the same positions by the same compiled chunk step, and
masked attention lanes underflow to exact zero, so reusing it is
bit-identical to recomputing it (tests/test_prefix_cache.py).

Sampling runs on host from the [B, spec_len, V] logits (greedy /
temperature / top-k). Stochastic sampling derives its rng stream from
(request seed, absolute position), never from batch composition, so
scheduling decisions can't change a request's output.

Two features ride that determinism with zero new compiled paths:

- SPECULATIVE DECODING (spec_k > 0, engine/draft.py): a model-free
  prompt-lookup drafter proposes up to k tokens per decode-ready
  sequence; the scheduler widens that row's window to 1 + k tokens (the
  same multi-token shape a prefill chunk uses) so the ONE compiled step
  scores all positions in a single launch. Verification accepts the
  longest draft prefix where draft[j] equals what _sample would have
  produced anyway — exact under greedy AND temperature, because a
  deterministic point-mass proposal degenerates rejection sampling to a
  token-identity test. Rejected positions roll back by simply not
  advancing the cache: the stale KV past _lens is re-reserved and
  overwritten by later appends.
- PARALLEL SAMPLING (add_request(n=...)): a finished prefill forks into
  n candidates sharing every prompt block (refcount bump + COW), each
  decoding under seed + i; candidate streams are bit-identical to solo
  runs with those seeds.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.module import Context, _CtxCore
from paddle_tpu.engine.kvtier import HostKVTier, prefix_digest
from paddle_tpu.engine.paged_cache import PagedKVCache
from paddle_tpu.engine.scheduler import (RUNNING, Request, Scheduler,
                                         StepRow)
from paddle_tpu.obs.metrics import MetricsRegistry, default_registry
from paddle_tpu.obs.tracing import RequestTracer
from paddle_tpu.quant.int8_compute import dequantize_block, quantize_block
from paddle_tpu.utils.log import serve_event

_COPY_LANES = 8     # COW copies flushed through one fixed-shape call
_TIER_LANES = 8     # host-tier revivals flushed per fixed-shape write
# in-device KV compression: a committed block untouched this many steps
# is cold enough for the proactive quantize sweep (compress_cold)
_COMPRESS_IDLE_STEPS = 4


def _fresh_cx(variables) -> Context:
    return Context(_CtxCore(mode="apply", variables=variables, mutated={},
                            rng=None, rng_count=0, training=False))


def serve_metadata(model) -> dict:
    """Introspect a CausalLM into the manifest `serve` block
    (io/inference.py `save_inference_model(..., serve_meta=...)`):
    everything `ServeEngine.from_saved_model` needs to rebuild the
    module and size its KV pools without touching the checkpoint."""
    attn = model.blocks[0].attn
    return {
        "model_type": "causal_lm",
        "vocab": model.vocab,
        "model_dim": model.model_dim,
        "num_heads": attn.num_heads,
        "num_kv_heads": attn.num_kv_heads,
        "head_dim": attn.head_dim,
        "num_layers": len(model.blocks),
        "ffn_dim": model.blocks[0].ffn.fc1.features,
        "max_len": model.max_len,
        "tie_embeddings": model.tie_embeddings,
        "fused_qkv": attn.fused_qkv,
    }


def _sample(logits: np.ndarray, req: Request, pos: int
            ) -> "tuple[int, float]":
    """Host-side sampling for one row: (token, log-probability of that
    token under the sampling distribution — greedy scores against the
    plain softmax). Deterministic in (req.seed, pos): the same request
    samples the same token at the same position no matter what batch
    it rode in — which is ALSO what makes speculative verification
    exact (a draft is accepted iff it equals this function's output at
    its position) and best-of-n forks reproducible (candidate i ==
    a solo run with seed + i). The logprob accumulates into
    Request.logprob_sum, the best_of ranking signal."""
    if req.temperature <= 0.0:
        tok = int(np.argmax(logits))
        z = logits.astype(np.float64)
        z = z - z.max()
        return tok, float(z[tok] - np.log(np.exp(z).sum()))
    z = logits.astype(np.float64) / req.temperature
    if 0 < req.top_k < z.size:
        kth = np.partition(z, -req.top_k)[-req.top_k]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    rng = np.random.default_rng([req.seed & 0x7FFFFFFF, pos])
    tok = int(rng.choice(z.size, p=p))
    return tok, float(np.log(p[tok]))


class ServeEngine:
    """Continuous-batching serve loop over a CausalLM.

    add_request() enqueues; step() advances the world by one scheduler
    plan — ONE mixed batch of decode rows and prefill chunks through a
    single compiled call; run() drains the queue. Token callbacks fire
    as tokens are sampled — streaming falls out of iteration-level
    scheduling for free.

    `max_prefill_tokens` is the per-step CHUNK budget: prompts longer
    than it are admitted anyway and prefilled across several steps,
    with decode rows riding the same steps. Budgets above the model's
    usable context are clamped (a chunk can never exceed max_seq_len
    anyway); budgets < 1 are rejected. `tile_q` is the ragged
    packing's query-tile granularity: every row occupies a
    tile_q-aligned segment of the flat step, so each planned row
    wastes at most tile_q - 1 query slots. `enable_prefix_cache=False`
    turns off block sharing (the serve_bench baseline)."""

    def __init__(self, model, variables, max_batch_size: int = 4,
                 block_size: int = 16, num_blocks: int = 256,
                 max_seq_len: Optional[int] = None,
                 max_prefill_tokens: int = 512,
                 tile_q: int = 8,
                 enable_prefix_cache: bool = True,
                 spec_k: int = 0,
                 drafter=None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[RequestTracer] = None,
                 host_tier_bytes: int = 0,
                 kv_tier_int8: bool = False,
                 tier_spill_dir: Optional[str] = None,
                 kv_compress_blocks: int = 0,
                 kv_promote_hits: int = 0,
                 tp_size: int = 1,
                 demote_finished: bool = False):
        self.model = model
        # telemetry (OBSERVABILITY.md): None -> the process registry /
        # a fresh tracer. serve_bench passes a private registry per
        # engine so its A/B cells don't pollute each other.
        self.obs = registry if registry is not None else default_registry()
        self.tracer = tracer if tracer is not None else RequestTracer()
        attn = model.blocks[0].attn
        # tensor-parallel serving (ENGINE.md "Tensor-parallel serving"):
        # tp_size > 1 builds a tp mesh over the first tp_size devices,
        # shards the weights (parallel.sharding.serve_tp_rules) and KV
        # pools over it, and pins the ONE compiled step's operand
        # shardings — model code runs at GLOBAL shapes throughout, so
        # tp=1 is exactly today's engine, bit for bit.
        self.tp_size = int(tp_size)
        self._serve_tp = None
        self._mesh = None
        if self.tp_size > 1:
            from paddle_tpu.parallel.mesh import MeshConfig, make_mesh
            from paddle_tpu.parallel.serve_collective import (ServeTP,
                                                              resolve_mode)
            from paddle_tpu.parallel.sharding import (serve_tp_rules,
                                                      shard_variables)
            devs = jax.devices()
            if len(devs) < self.tp_size:
                raise ValueError(
                    f"tp_size={self.tp_size} needs that many devices, "
                    f"have {len(devs)} — on CPU set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=<n> before "
                    "jax initializes (serve/replica.py --tp-size does "
                    "this for you)")
            if attn.num_heads % self.tp_size:
                raise ValueError(
                    f"num_heads={attn.num_heads} not divisible by "
                    f"tp_size={self.tp_size}")
            if attn.num_kv_heads % self.tp_size:
                raise ValueError(
                    f"num_kv_heads={attn.num_kv_heads} not divisible by "
                    f"tp_size={self.tp_size}: KV pools shard over "
                    "kv-heads so GQA groups stay device-local")
            ffn_dim = model.blocks[0].ffn.fc1.features
            if ffn_dim % self.tp_size:
                raise ValueError(
                    f"ffn_dim={ffn_dim} not divisible by "
                    f"tp_size={self.tp_size}")
            self._mesh = make_mesh(MeshConfig(tp=self.tp_size),
                                   devices=devs[:self.tp_size])
            self._serve_tp = ServeTP(self._mesh, self.tp_size,
                                     mode=resolve_mode())
            self._tp_rules = serve_tp_rules()
            variables = shard_variables(self._mesh, variables,
                                        self._tp_rules)
        self.variables = variables
        self.max_seq_len = min(max_seq_len or model.max_len, model.max_len)
        self.max_batch_size = max_batch_size
        if max_prefill_tokens < 1:
            raise ValueError(
                f"max_prefill_tokens {max_prefill_tokens} < 1: the chunk "
                "budget must admit at least one prompt token per step")
        if tile_q < 1:
            raise ValueError(f"tile_q {tile_q} < 1")
        if max_prefill_tokens > self.max_seq_len:
            # a single chunk can never exceed the usable context, so a
            # larger budget only inflates the compiled step shape —
            # clamp loudly instead of silently paying for dead tiles
            serve_event("serve_config_clamp", field="max_prefill_tokens",
                        requested=max_prefill_tokens,
                        clamped_to=self.max_seq_len)
            max_prefill_tokens = self.max_seq_len
        self.tile_q = tile_q
        # speculative decoding (engine/draft.py): spec_k > 0 turns
        # decode rows into multi-token verification windows of up to
        # 1 + spec_k tokens. The ONE compiled step absorbs that by
        # sizing each row's worst-case decode segment to the rounded
        # window (spec_k = 0 reproduces the old B * tile_q exactly) and
        # gathering spec_len logit positions per row instead of 1 —
        # draft length changes are int32-operand changes, never shape
        # changes.
        if spec_k < 0:
            raise ValueError(f"spec_k {spec_k} < 0")
        if drafter is None and spec_k > 0:
            from paddle_tpu.engine.draft import NgramDrafter
            drafter = NgramDrafter(k=spec_k)
        if drafter is not None:
            # the compiled shape must fit the drafter's longest window
            spec_k = max(spec_k, drafter.k)
        self.spec_k = spec_k
        self.spec_len = spec_k + 1          # logit positions per row
        self.drafter = drafter
        # flat step sizing: every row's segment is tile-aligned, so the
        # worst case is max_batch_size rows each wasting tile_q - 1
        # slots on top of the chunk budget (decode windows grow to
        # 1 + spec_k tokens under speculation)
        self.flat_tokens = (
            -(-max_prefill_tokens // tile_q) * tile_q
            + max_batch_size * (-(-self.spec_len // tile_q) * tile_q))
        self.num_tiles = self.flat_tokens // tile_q
        # host-RAM KV tier (engine/kvtier.py): a byte budget > 0 hangs
        # a second tier behind the pool — cached-free evictions and
        # preemptions demote block KV to host (int8-quantized when
        # kv_tier_int8), and admission revives it by DMA instead of
        # re-prefill. All tier traffic is host-side numpy plus eager
        # .at[].set() pool writes: the one-compile invariant holds.
        self.host_tier = (
            HostKVTier(host_tier_bytes, int8=kv_tier_int8,
                       registry=self.obs)
            if host_tier_bytes > 0 else None)
        # warm restart (RESILIENCE.md §fleet): a spill dir warm-starts
        # the tier from the previous process's drain spill — the blocks
        # are advertised on /kvprefixes again within one scrape
        # interval, so the router's fleet directory finds them. A
        # missing/partial/foreign spill loads 0 blocks and the tier
        # simply starts cold.
        # disaggregated serving (serve/kvxfer.py): a prefill-phase
        # replica demotes every finished request's committed blocks
        # into the host tier at _finish, so the prefix is advertised on
        # /kvprefixes and PULLABLE over GET /kvblocks/<digest> by the
        # decode replica that continues the stream. No-op without a
        # tier; demotion is host-side numpy (one-compile safe).
        self.demote_finished = bool(demote_finished)
        self.tier_spill_dir = tier_spill_dir
        if self.host_tier is not None and tier_spill_dir:
            loaded = self.host_tier.load_spill(tier_spill_dir)
            if loaded:
                serve_event("tier_warm_start", dir=tier_spill_dir,
                            blocks=loaded)
        # in-device KV compression (ENGINE.md "In-device KV
        # compression"): kv_compress_blocks > 0 gives the cache a
        # parallel int8 block pool cold prefix blocks quantize into at
        # ~half the bytes — the rung between device-fp and the host
        # tier. 0 reproduces today's behavior bit for bit. Compressed
        # hits are read IN PLACE by the mixed ragged step by default;
        # kv_promote_hits opts back into fp promotion (1 = always, the
        # PR-19 behavior; N > 1 = warm-up threshold).
        self.cache = PagedKVCache(
            num_layers=len(model.blocks), num_blocks=num_blocks,
            block_size=block_size, num_kv_heads=attn.num_kv_heads,
            head_dim=attn.head_dim, dtype=model.dtype,
            enable_prefix_cache=enable_prefix_cache, registry=self.obs,
            host_tier=self.host_tier,
            compress_blocks=kv_compress_blocks,
            promote_hits=kv_promote_hits, tp_size=self.tp_size,
            mesh=self._mesh)
        if self.host_tier is not None:
            # prime the eager kernels tier traffic dispatches — the
            # demote gather (pool[block] device_get) and the revival
            # scatter (_TIER_LANES-wide .at[].set) — with no-op writes
            # to scratch block 0, so the first real demotion/revival
            # never pays their one-time XLA compile mid-request.
            kp0, vp0 = self.cache.pools[0]
            lanes = jnp.zeros((_TIER_LANES,), jnp.int32)
            zero = jnp.zeros((_TIER_LANES,) + tuple(kp0.shape[1:]),
                             kp0.dtype)
            np.asarray(kp0[0])        # the demote gather's signature
            self.cache.pools[0] = (kp0.at[lanes].set(zero),
                                   vp0.at[lanes].set(zero))
        if self.cache.compress_enabled:
            # prime the compressed tier's fixed-lane eager kernels —
            # the quantize scatter (compress), the dequantize scatter
            # (promote), and the host-spill gather — with no-op scratch
            # traffic (fp block 0 <-> int8 slot 0), so the first real
            # compression/promotion never pays a mid-request compile.
            # Eager fixed-shape ops like the _TIER_LANES revival path:
            # no new jit entry points, the step's cache stays at 1.
            lanes = jnp.zeros((_TIER_LANES,), jnp.int32)
            kp0, vp0 = self.cache.pools[0]
            kq0, vq0 = self.cache.qpools[0]
            ks0, vs0 = self.cache.qscales[0]
            kq8, ksc = quantize_block(kp0[lanes])
            vq8, vsc = quantize_block(vp0[lanes])
            self.cache.qpools[0] = (kq0.at[lanes].set(kq8),
                                    vq0.at[lanes].set(vq8))
            self.cache.qscales[0] = (ks0.at[lanes].set(ksc),
                                     vs0.at[lanes].set(vsc))
            kq0, vq0 = self.cache.qpools[0]
            ks0, vs0 = self.cache.qscales[0]
            kfp = dequantize_block(kq0[lanes], ks0[lanes], kp0.dtype)
            vfp = dequantize_block(vq0[lanes], vs0[lanes], vp0.dtype)
            self.cache.pools[0] = (kp0.at[lanes].set(kfp),
                                   vp0.at[lanes].set(vfp))
            np.asarray(kq0[0])        # the host-spill gather signatures
            float(ks0[0])
        self.max_blocks_per_seq = self.cache.blocks_for(self.max_seq_len)
        self.scheduler = Scheduler(
            self.cache, max_batch_size=max_batch_size,
            max_prefill_tokens=max_prefill_tokens,
            max_seq_len=self.max_seq_len - 1,  # leave room for >=1 new token
            drafter=self.drafter)
        self.scheduler.on_preempt = self._on_preempt
        self.scheduler.on_admit = self._on_admit
        self.finished: Dict[int, Request] = {}
        self.steps = 0
        self.prefill_tokens_computed = 0
        self.peak_occupancy = 0.0
        self.max_chunk_tokens = 0       # largest prefill step actually run
        self._register_metrics()
        self._m_tp_size.set(float(self.tp_size))
        if self._serve_tp is not None:
            # one-shot collective microprobe at construction (host-side;
            # the compiled step itself is never host-timed) — gives a
            # scrape the fp-vs-int8 wire-cost comparison up front
            from paddle_tpu.parallel.serve_collective import \
                allreduce_probe_ms
            self._allreduce_probe_ms = allreduce_probe_ms(
                self._mesh, self._serve_tp.mode,
                shape=(1, model.model_dim))
            self._m_allreduce.labels(mode=self._serve_tp.mode).observe(
                self._allreduce_probe_ms)

        model_ = model
        serve_tp = self._serve_tp

        if serve_tp is None:
            jit_step = jax.jit
            jit_copy = jax.jit
        else:
            # pin the ONE compiled step's operand shardings so every
            # call reuses the same executable (TP004 / the one-compile
            # invariant): weights per serve_tp_rules, KV pools sharded
            # over kv-heads, int32 packing operands replicated. Model
            # code sees GLOBAL shapes; XLA partitions the ops, and the
            # explicit islands (sharded attention, the quantized fc2
            # reduce) run inside.
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self._mesh, P())
            pool_s = NamedSharding(self._mesh, P(None, None, "tp", None))
            nl = len(model.blocks)
            var_sh = self._tp_rules.tree_shardings(self._mesh,
                                                   self.variables)
            pools_sh = [(pool_s, pool_s)] * nl
            # int8 pools shard over kv-heads like the fp pools; the
            # per-block scales are head-independent scalars, replicated.
            # Compression off -> empty lists, a stable pytree prefix.
            qpools_sh = ([(pool_s, pool_s)] * nl
                         if self.cache.compress_enabled else [])
            qscales_sh = ([(rep, rep)] * nl
                          if self.cache.compress_enabled else [])
            jit_step = functools.partial(
                jax.jit,
                in_shardings=(var_sh, rep, rep, pools_sh, qpools_sh,
                              qscales_sh, rep, rep, rep, rep, rep, rep,
                              rep),
                out_shardings=(rep, pools_sh))
            jit_copy = functools.partial(
                jax.jit,
                in_shardings=(pools_sh, rep, rep),
                out_shardings=pools_sh)

        @jit_step
        def _step_fn(variables, tokens, positions, pools, qpools, qscales,
                     block_tables, context_lens, q_starts, tile_rows,
                     tile_offs, slots, last_idx):
            return model_.ragged_step_paged(
                _fresh_cx(variables), tokens, positions, pools,
                block_tables, context_lens, q_starts, tile_rows,
                tile_offs, slots, last_idx, tp=serve_tp,
                qpools=qpools, qscales=qscales)

        @jit_copy
        def _copy_blocks(pools, src, dst):
            # COW replay: dst blocks take src blocks' contents, every
            # layer; padding lanes are (0, 0) — scratch onto itself
            return [(kp.at[dst].set(kp[src]), vp.at[dst].set(vp[src]))
                    for kp, vp in pools]

        self._step_fn = _step_fn
        self._copy_blocks = _copy_blocks

    # -- construction from an exported artifact ---------------------------
    @classmethod
    def from_saved_model(cls, model_dir: str, **engine_kwargs):
        """Build model + engine from a save_inference_model() directory
        whose manifest carries the `serve` block (serve_metadata)."""
        import json
        import os

        from paddle_tpu.io.checkpoint import load_checkpoint
        from paddle_tpu.models.transformer import CausalLM

        with open(os.path.join(model_dir, "signature.json")) as f:
            sig = json.load(f)
        meta = sig.get("serve")
        if meta is None:
            raise ValueError(
                f"{model_dir} has no `serve` metadata in its manifest; "
                "re-export with save_inference_model(..., "
                "serve_meta=serve_metadata(model))")
        model = CausalLM(
            vocab=meta["vocab"], model_dim=meta["model_dim"],
            num_heads=meta["num_heads"], num_layers=meta["num_layers"],
            ffn_dim=meta["ffn_dim"], dropout=0.0, max_len=meta["max_len"],
            tie_embeddings=meta["tie_embeddings"],
            fused_qkv=meta["fused_qkv"],
            num_kv_heads=meta["num_kv_heads"])
        variables = load_checkpoint(os.path.join(model_dir, "params"))
        engine_kwargs.setdefault("max_seq_len", meta["max_len"])
        return cls(model, variables, **engine_kwargs)

    # -- telemetry --------------------------------------------------------
    def _register_metrics(self) -> None:
        """Metric families this engine records (OBSERVABILITY.md has
        the catalog). Families are get-or-create: engines sharing a
        registry share series. Everything here is host-side bookkeeping
        — instrumentation can never add a compile or device sync."""
        m = self.obs
        self._m_ttft = m.histogram(
            "ptpu_serve_ttft_ms", "Enqueue to first token (ms)")
        self._m_tpot = m.histogram(
            "ptpu_serve_tpot_ms",
            "Per-request mean decode latency per output token (ms)")
        self._m_queue_wait = m.histogram(
            "ptpu_serve_queue_wait_ms", "Enqueue to first admission (ms)")
        self._m_e2e = m.histogram(
            "ptpu_serve_e2e_ms", "Enqueue to finish (ms)")
        self._m_step = m.histogram(
            "ptpu_serve_step_ms", "Engine step wall time (ms)",
            labelnames=("kind",))        # kind=decode|prefill|mixed|spec
        self._m_reqs = m.counter(
            "ptpu_serve_requests_total", "Finished requests",
            labelnames=("reason",))      # reason=eos|length|cancelled
        self._m_tokens = m.counter(
            "ptpu_serve_tokens_total", "Token flow through the engine",
            labelnames=("kind",))        # kind=prefill|cached|generated
        self._m_steps = m.counter(
            "ptpu_engine_steps_total", "Compiled mixed steps executed")
        self._m_compiles = m.gauge(
            "ptpu_engine_compiles",
            "jit cache size of the unified step (the one-compile "
            "invariant: stays at 1 across arbitrary traffic)")
        self._m_occ = m.gauge(
            "ptpu_kv_occupancy", "Fraction of allocatable blocks in use")
        self._m_hit = m.gauge(
            "ptpu_kv_hit_rate",
            "Cumulative fraction of prompt tokens served from the "
            "prefix cache")
        self._m_shared = m.gauge(
            "ptpu_kv_shared_blocks", "Blocks with refcount > 1")
        self._m_compressed = m.gauge(
            "ptpu_kv_compressed_blocks",
            "Prefix blocks resident in the device int8 compressed pool")
        self._m_pool_eff = m.gauge(
            "ptpu_kv_pool_effective_bytes",
            "fp-equivalent KV bytes the device holds: the fp pool plus "
            "every compressed entry at the fp bytes it stands in for")
        self._m_queue_depth = m.gauge(
            "ptpu_sched_queue_depth", "Requests waiting for admission")
        self._m_running = m.gauge(
            "ptpu_sched_running", "Requests in the running set")
        self._m_decode_rows = m.gauge(
            "ptpu_sched_decode_rows", "Decode rows in the last step")
        self._m_prefill_rows = m.gauge(
            "ptpu_sched_prefill_rows", "Prefill chunks in the last step")
        self._m_budget_util = m.gauge(
            "ptpu_sched_chunk_budget_util",
            "Chunk tokens / max_prefill_tokens of the last "
            "prefill-bearing step")
        self._m_preempts = m.counter(
            "ptpu_sched_preemptions_total", "Recompute preemptions")
        # speculative decoding (acceptance telemetry; the step-latency
        # comparison rides ptpu_serve_step_ms{kind="spec"} vs "decode")
        self._m_spec_drafted = m.counter(
            "ptpu_spec_drafted_tokens_total",
            "Draft tokens proposed for batched verification")
        self._m_spec_accepted = m.counter(
            "ptpu_spec_accepted_tokens_total",
            "Draft tokens accepted (emitted beyond the base token)")
        self._m_spec_rejected = m.counter(
            "ptpu_spec_rejected_tokens_total",
            "Draft tokens rejected (their written KV rolled back)")
        self._m_spec_ratio = m.histogram(
            "ptpu_spec_acceptance_ratio",
            "Per-speculative-row accepted/drafted ratio")
        # tensor-parallel serving (engine tp_size knob)
        self._m_tp_size = m.gauge(
            "ptpu_serve_tp_size",
            "Tensor-parallel degree of the serving mesh (1 = "
            "single-device)")
        self._m_allreduce = m.histogram(
            "ptpu_serve_allreduce_ms",
            "Decode-MLP allreduce microprobe wall time at engine "
            "construction (ms)",
            labelnames=("mode",))        # mode=fp|int8

    def _on_admit(self, req: Request) -> None:
        """Scheduler hook: a request left the wait queue. Queue-wait is
        observed only on FIRST admission (a preemption re-admission is
        a scheduling artifact, not arrival latency)."""
        now = time.monotonic()
        if req.admit_time == 0.0:
            self._m_queue_wait.observe((now - req.enqueue_time) * 1e3)
        req.admit_time = now
        self.tracer.on_admit(req.req_id)
        self._set_sched_gauges()

    def _set_sched_gauges(self) -> None:
        """Refresh queue-depth/running on EVERY membership change
        (admit, finish, cancel, preempt, enqueue) — not only at step
        end. The replica router scrapes between steps; a gauge that
        lags until the next step() would route traffic on stale
        depth."""
        self._m_queue_depth.set(self.scheduler.queue_depth)
        self._m_running.set(len(self.scheduler.running))

    def metrics_text(self) -> str:
        """Prometheus exposition of this engine's registry (the
        /metrics body when no scrape server is mounted)."""
        return self.obs.render_prometheus()

    # -- intake -----------------------------------------------------------
    def add_request(self, prompt: List[int], max_new_tokens: int = 32,
                    temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                    eos_id: Optional[int] = None,
                    callback: Optional[Callable[[int], None]] = None,
                    deadline_ms: Optional[float] = None,
                    n: int = 1,
                    fork_callback: Optional[Callable] = None) -> Request:
        """Enqueue one completion. `n > 1` is parallel sampling: when
        this request's prefill finishes, the engine forks n - 1 sibling
        candidates off its prompt blocks (refcount bump, zero copies —
        PagedKVCache.fork_sequence), each sampling with seed + i, and
        all n decode concurrently. The returned primary is candidate 0;
        its `forks` list holds the siblings. fork_callback(i) -> token
        callback (or None for a silent candidate) wires sibling
        streams."""
        if not prompt:
            raise ValueError("empty prompt")
        if not 1 <= n <= self.max_batch_size:
            raise ValueError(
                f"n {n} not in [1, max_batch_size={self.max_batch_size}]: "
                "every candidate needs a batch slot to decode")
        if len(prompt) + 1 > self.max_seq_len:
            raise ValueError(f"prompt len {len(prompt)} leaves no room to "
                             f"generate under max_seq_len {self.max_seq_len}")
        if self.cache.blocks_for(len(prompt) + 1) > self.cache.num_blocks - 1:
            raise ValueError(
                f"prompt len {len(prompt)} cannot fit the KV pool even "
                f"alone ({self.cache.num_blocks - 1} blocks of "
                f"{self.cache.block_size}); raise num_blocks")
        req = Request(prompt=list(prompt), max_new_tokens=max_new_tokens,
                      temperature=temperature, top_k=top_k, seed=seed,
                      eos_id=eos_id, callback=callback,
                      n_candidates=n, fork_callback=fork_callback)
        req.enqueue_time = time.monotonic()
        if deadline_ms is not None:
            # absolute completion deadline: the scheduler preempts the
            # slackest request first, so a tight deadline shields KV
            # state under pool pressure
            req.deadline = req.enqueue_time + deadline_ms / 1e3
        self.scheduler.add(req)
        self.tracer.on_enqueue(req.req_id)
        self._set_sched_gauges()
        serve_event("serve_admit", req_id=req.req_id,
                    prompt_len=len(prompt),
                    queue_depth=self.scheduler.queue_depth)
        return req

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        """Tear a request down mid-flight (client disconnect): frees
        its KV blocks (shared prefix blocks drop one refcount), counts
        it under requests{reason=...}, and closes its trace. Returns
        False when it already finished. Engine-thread only, between
        steps — the HTTP front-end marshals disconnects through the
        serve loop (serve/frontend.py)."""
        if not self.scheduler.cancel(req):
            return False
        req.finish_time = time.monotonic()
        req.finish_reason = reason
        self.finished[req.req_id] = req
        self._m_reqs.labels(reason=reason).inc()
        self._set_sched_gauges()
        self._m_occ.set(self.cache.occupancy())
        self.tracer.on_finish(req.req_id, reason)
        serve_event("serve_cancel", req_id=req.req_id, reason=reason,
                    tokens=req.num_generated,
                    occupancy=round(self.cache.occupancy(), 4))
        return True

    def cancel_group(self, req: Request, reason: str = "cancelled") -> int:
        """Cancel a parallel-sampling group: the primary and every fork
        it spawned (a client disconnect must drop ALL n candidates'
        block references, returning shared-prompt refcounts to
        baseline). Safe for n == 1 (forks is empty) and before the fork
        happened (cancelling the still-prefilling primary means the
        siblings are simply never created). Returns how many candidates
        were actually cancelled."""
        return sum(1 for r in [req] + req.forks
                   if self.cancel(r, reason))

    # -- serve loop --------------------------------------------------------
    def step(self) -> bool:
        """Advance one scheduler plan (one mixed batch through the
        single compiled step). Returns False when idle."""
        t0 = time.perf_counter()
        rows = self.scheduler.next_batch()
        if rows is None:
            return False
        self.steps += 1
        # publish the coldness clock, then sweep: blocks the plan just
        # admitted are hot (touched at step_now), so only genuinely
        # idle prefix content stages quantize lanes for this step's
        # _flush_compress
        self.cache.step_now = self.steps
        if self.cache.compress_enabled:
            self.cache.compress_cold(_COMPRESS_IDLE_STEPS)
        n_chunks, n_decodes, chunk_tokens, n_drafted = \
            self._step_mixed(rows)
        self.peak_occupancy = max(self.peak_occupancy,
                                  self.cache.occupancy())
        # per-step telemetry: host-side gauge/histogram writes only
        # ("spec" wins over mixed/decode so the speculation-on latency
        # distribution is separable from plain decode's)
        kind = ("spec" if n_drafted
                else "mixed" if n_chunks and n_decodes
                else "prefill" if n_chunks else "decode")
        self._m_step.labels(kind=kind).observe(
            (time.perf_counter() - t0) * 1e3)
        self._m_steps.inc()
        self._m_compiles.set(self._step_fn._cache_size())
        self._m_occ.set(self.cache.occupancy())
        self._m_hit.set(self.cache.hit_rate())
        self._m_shared.set(self.cache.shared_blocks)
        self._m_compressed.set(float(self.cache.compressed_resident))
        self._m_pool_eff.set(float(self.cache.effective_pool_bytes()))
        self._m_queue_depth.set(self.scheduler.queue_depth)
        self._m_running.set(len(self.scheduler.running))
        self._m_decode_rows.set(n_decodes)
        self._m_prefill_rows.set(n_chunks)
        if n_chunks:
            self._m_budget_util.set(
                chunk_tokens / self.scheduler.max_prefill_tokens)
        return True

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue; returns {req_id: generated token ids}."""
        while self.step():
            pass
        return {rid: self._generated_of(r)
                for rid, r in self.finished.items()}

    # -- internals ---------------------------------------------------------
    def _flush_cow(self) -> None:
        """Replay queued copy-on-write block copies on the device pools
        BEFORE the step that writes the fresh blocks, through one
        fixed-shape compiled call per _COPY_LANES batch."""
        copies = self.cache.drain_copies()
        for i in range(0, len(copies), _COPY_LANES):
            batch = copies[i:i + _COPY_LANES]
            src = np.zeros((_COPY_LANES,), np.int32)
            dst = np.zeros((_COPY_LANES,), np.int32)
            for j, (s, d) in enumerate(batch):
                src[j], dst[j] = s, d
            self.cache.pools = self._copy_blocks(
                self.cache.pools, jnp.asarray(src), jnp.asarray(dst))

    def _flush_tier_loads(self) -> None:
        """Write staged host-tier revivals into the device pools —
        BEFORE _flush_cow (a just-revived block can be a same-plan COW
        src) and before the step reads them. Eager functional
        .at[blocks].set(...) writes in FIXED-WIDTH _TIER_LANES batches
        (unused lanes write zeros to scratch block 0, the _flush_cow
        idiom) — the shape signature never varies with revival size,
        so XLA compiles the scatter exactly once. No new jit entry
        points: the jit cache stays at 1."""
        loads = self.cache.drain_host_loads()
        for i in range(0, len(loads), _TIER_LANES):
            batch = loads[i:i + _TIER_LANES]
            idx = np.zeros((_TIER_LANES,), np.int32)
            for j, (b, _) in enumerate(batch):
                idx[j] = b       # pad lanes write zeros to scratch block 0
            blocks = jnp.asarray(idx)
            for li, (kp, vp) in enumerate(self.cache.pools):
                kd = np.zeros((_TIER_LANES,) + tuple(kp.shape[1:]),
                              np.float32)
                vd = np.zeros((_TIER_LANES,) + tuple(vp.shape[1:]),
                              np.float32)
                for j, (_, layers) in enumerate(batch):
                    kd[j], vd[j] = layers[li]
                self.cache.pools[li] = (
                    kp.at[blocks].set(jnp.asarray(kd, kp.dtype)),
                    vp.at[blocks].set(jnp.asarray(vd, vp.dtype)))

    def _flush_compress(self) -> None:
        """Quantize staged cold fp blocks into the int8 pool — FIRST
        among the pre-step flushes, so the quantize lanes read every
        src block's content before promotions, host loads, or COW
        copies can overwrite it. Fixed _TIER_LANES-wide eager
        gather-quantize-scatter per batch (pad lanes read fp scratch
        block 0 and write int8 scratch slot 0), primed at construction:
        no new jit entry points, the step's compile cache stays at 1."""
        jobs = self.cache.drain_compress()
        for i in range(0, len(jobs), _TIER_LANES):
            batch = jobs[i:i + _TIER_LANES]
            src = np.zeros((_TIER_LANES,), np.int32)   # fp blocks
            dst = np.zeros((_TIER_LANES,), np.int32)   # int8 slots
            for j, (b, s) in enumerate(batch):
                src[j], dst[j] = b, s
            bsrc, bdst = jnp.asarray(src), jnp.asarray(dst)
            for li, (kp, vp) in enumerate(self.cache.pools):
                kq, vq = self.cache.qpools[li]
                ks, vs = self.cache.qscales[li]
                kq8, ksc = quantize_block(kp[bsrc])
                vq8, vsc = quantize_block(vp[bsrc])
                self.cache.qpools[li] = (kq.at[bdst].set(kq8),
                                         vq.at[bdst].set(vq8))
                self.cache.qscales[li] = (ks.at[bdst].set(ksc),
                                          vs.at[bdst].set(vsc))

    def _flush_promote(self) -> None:
        """Dequantize staged compressed-tier hits into their claimed fp
        blocks — after _flush_compress (a promotion may read a slot the
        same plan just filled) and BEFORE host loads, COW copies, and
        the step read: the same staging contract as tier revivals. Pad
        lanes read int8 scratch slot 0 and write fp scratch block 0."""
        jobs = self.cache.drain_promotes()
        for i in range(0, len(jobs), _TIER_LANES):
            batch = jobs[i:i + _TIER_LANES]
            src = np.zeros((_TIER_LANES,), np.int32)   # int8 slots
            dst = np.zeros((_TIER_LANES,), np.int32)   # fp blocks
            for j, (b, s) in enumerate(batch):
                dst[j], src[j] = b, s
            bsrc, bdst = jnp.asarray(src), jnp.asarray(dst)
            for li, (kp, vp) in enumerate(self.cache.pools):
                kq, vq = self.cache.qpools[li]
                ks, vs = self.cache.qscales[li]
                kfp = dequantize_block(kq[bsrc], ks[bsrc], kp.dtype)
                vfp = dequantize_block(vq[bsrc], vs[bsrc], vp.dtype)
                self.cache.pools[li] = (kp.at[bdst].set(kfp),
                                        vp.at[bdst].set(vfp))

    @property
    def kv_direct_int8(self) -> bool:
        """Whether this replica's compiled step reads int8-resident
        blocks in place (no promote round-trip). Advertised as the
        `direct_int8` capability field on /kvprefixes so the router can
        re-price this replica's device_int8 directory rung to near
        device-fp; older replicas never send the field."""
        return self.cache.compress_enabled and self.cache.direct_read_enabled

    def kv_prefix_directory(self, limit: int = 512) -> List[dict]:
        """This replica's fleet-directory advertisement: the warm
        prefixes it can serve without re-prefill, as
        {len, digest, tier} rows (device = prefix-index entries,
        device_int8 = in-device compressed entries, host = tier
        entries). Digests are crc32 over little-endian u32 token
        ids — the same encoding the router's prefix_shard hashes.
        Engine-loop thread only (reads the unlocked prefix index); the
        serve front-end snapshots it between steps for /kvprefixes."""
        out = [{"len": len(key), "digest": prefix_digest(key),
                "tier": "device"}
               for key in self.cache.prefix_keys(limit)]
        if self.cache.compress_enabled:
            out.extend({"len": len(key), "digest": prefix_digest(key),
                        "tier": "device_int8"}
                       for key in self.cache.compressed_keys(limit))
        if self.host_tier is not None:
            out.extend({"len": ln, "digest": dg, "tier": "host"}
                       for ln, dg in self.host_tier.advertised(limit))
        return out

    @staticmethod
    def _request_summary(req: Request) -> dict:
        return {
            "req_id": req.req_id,
            "state": req.state,
            "prompt_len": len(req.prompt),
            "generated": req.num_generated,
            "prefill_pos": req.prefill_pos,
            "cached_tokens": req.cached_tokens,
            "preemptions": req.preemptions,
            "deadline": None if req.deadline == float("inf")
            else req.deadline,
            "n_candidates": req.n_candidates,
        }

    def debug_state(self) -> dict:
        """Introspection snapshot for /debug and the flight recorder:
        the wait queue and running set as request summaries, block-pool
        occupancy, and the host-tier LRU summary. Engine-loop thread
        for a CONSISTENT view (the serve front-end refreshes it between
        steps); the flight recorder may also call it best-effort from a
        watchdog thread when the engine loop is wedged — reads only,
        never mutates, so a torn read is the worst case."""
        pool = {
            "num_blocks": self.cache.num_blocks,
            "block_size": self.cache.block_size,
            "free_blocks": self.cache.free_blocks,
            "used_blocks": self.cache.used_blocks,
            "shared_blocks": self.cache.shared_blocks,
            "occupancy": round(self.cache.occupancy(), 4),
        }
        out = {
            "steps": self.steps,
            "queue_depth": self.scheduler.queue_depth,
            "waiting": [self._request_summary(r)
                        for r in self.scheduler.waiting],
            "running": [self._request_summary(r)
                        for r in self.scheduler.running],
            "pool": pool,
            "cache": self.cache.stats(),
        }
        if self.host_tier is not None:
            out["host_tier"] = self.host_tier.stats()
        return out

    def _step_mixed(self, rows: List[StepRow]
                    ) -> "tuple[int, int, int, int]":
        """Pack the plan's rows — decode rows AND prefill chunks — into
        the flat ragged layout and run ONE compiled step. Row i's token
        window [start, start+length) lands in a tile_q-aligned segment
        of the [T] arrays; per-row metadata (block table, chunk-end
        context, start position) sits at index i, and the null row at
        index max_batch_size backs pad tiles (ctx 1, scratch table).
        For a plain decode row the window is [seq_len, seq_len+1) of
        req.tokens — exactly the last generated token at its next-token
        position, which is what the old decode step fed. A SPECULATIVE
        row widens that window to [seq_len, seq_len+1+k): the base
        token followed by k drafted tokens (scheduler StepRow.draft) —
        the same multi-token shape a prefill chunk uses, so the ragged
        kernel scores all k+1 positions in the one launch (each window
        position scatters its own k/v before attention reads it,
        exactly as chunk rows already do). last_idx is [B, spec_len]:
        speculative rows gather one hidden state per window position
        for verification; every other row repeats its single real
        index across the columns."""
        self._flush_compress()
        self._flush_promote()
        self._flush_tier_loads()
        self._flush_cow()
        t_flat, tq, nt = self.flat_tokens, self.tile_q, self.num_tiles
        b = self.max_batch_size
        mb = self.max_blocks_per_seq
        tokens = np.zeros((t_flat,), np.int32)
        positions = np.zeros((t_flat,), np.int32)
        # pad positions scatter into scratch block 0 (slot < bs)
        slots = np.zeros((t_flat,), np.int32)
        block_tables = np.zeros((b + 1, mb), np.int32)
        context_lens = np.ones((b + 1,), np.int32)   # null/pad rows: scratch
        q_starts = np.zeros((b + 1,), np.int32)
        tile_rows = np.full((nt,), b, np.int32)      # pad tiles -> null row
        tile_offs = np.zeros((nt,), np.int32)
        last_idx = np.zeros((b, self.spec_len), np.int32)
        cursor = 0
        for i, row in enumerate(rows):
            r = row.req
            toks = r.tokens
            if row.draft:
                # draft tokens live only in the plan, not in req.tokens
                window = [toks[row.start]] + row.draft
            else:
                window = toks[row.start:row.start + row.length]
            tokens[cursor:cursor + row.length] = window
            positions[cursor:cursor + row.length] = np.arange(
                row.start, row.start + row.length, dtype=np.int32)
            for p in range(row.length):
                slots[cursor + p] = self.cache.slot_of(r.req_id,
                                                       row.start + p)
            block_tables[i] = self.cache.padded_table(r.req_id, mb)
            context_lens[i] = row.start + row.length
            q_starts[i] = row.start
            if row.decode:
                # verification gathers per-position logits (plain
                # decode rows have length 1: every column clamps to
                # the one real index)
                for j in range(self.spec_len):
                    last_idx[i, j] = cursor + min(j, row.length - 1)
            else:
                last_idx[i, :] = cursor + row.length - 1
            ntiles = -(-row.length // tq)
            t0 = cursor // tq
            for k in range(ntiles):
                tile_rows[t0 + k] = i
                tile_offs[t0 + k] = k * tq
            cursor += ntiles * tq
        logits, self.cache.pools = self._step_fn(
            self.variables, jnp.asarray(tokens), jnp.asarray(positions),
            self.cache.pools, self.cache.qpools, self.cache.qscales,
            jnp.asarray(block_tables), jnp.asarray(context_lens),
            jnp.asarray(q_starts), jnp.asarray(tile_rows),
            jnp.asarray(tile_offs), jnp.asarray(slots),
            jnp.asarray(last_idx))
        logits = np.asarray(logits)
        chunks = [w for w in rows if not w.decode]
        decodes = [w for w in rows if w.decode]
        computed = sum(w.length for w in chunks)
        now = time.monotonic()
        drafted = accepted = 0
        for i, row in enumerate(rows):
            r = row.req
            if row.decode:
                # the step wrote r.generated[-1]'s k/v at the reserved
                # slot
                self.cache.advance(r.req_id, r.generated[-1])
                row_accepted = 0
                for j in range(len(row.draft) + 1):
                    # logits[i, j] scored window position start+j, i.e.
                    # it predicts the token at cache seq_len (which the
                    # advances below keep in lockstep with j)
                    tok, lp = _sample(logits[i, j], r,
                                      self.cache.seq_len(r.req_id))
                    r.logprob_sum += lp
                    self._emit_token(r, tok)
                    if r.finish_reason or j >= len(row.draft):
                        break
                    if row.draft[j] != tok:
                        # first rejection: everything past seq_len is
                        # dead weight — rollback is simply NOT
                        # advancing; the stale k/v beyond _lens gets
                        # re-reserved and overwritten by later appends
                        break
                    # draft j verified: its k/v (scattered this launch)
                    # IS the true token's k/v, so advancing onto it
                    # lets the next column's logits be consumed too
                    self.cache.advance(r.req_id, tok)
                    row_accepted += 1
                if row.draft:
                    drafted += len(row.draft)
                    accepted += row_accepted
                    self._m_spec_drafted.inc(len(row.draft))
                    self._m_spec_accepted.inc(row_accepted)
                    self._m_spec_rejected.inc(
                        len(row.draft) - row_accepted)
                    self._m_spec_ratio.observe(
                        row_accepted / len(row.draft))
            else:
                self.cache.commit_prefill(r.req_id, row.start + row.length)
                self.tracer.on_chunk(r.req_id, row.start, row.length)
                if row.start + row.length == len(r.prompt):  # final chunk
                    if r.n_candidates > 1 and not r.forks:
                        # fork BEFORE the primary consumes the logits:
                        # each sibling samples its first token from the
                        # same final-chunk row under its own seed
                        self._fork_candidates(r, logits[i, 0], now)
                    tok, lp = _sample(logits[i, 0], r, len(r.prompt))
                    r.logprob_sum += lp
                    if not r.first_token_time:
                        r.first_token_time = now
                    self.tracer.on_first_token(r.req_id)
                    self._emit_token(r, tok)
        if chunks:
            # per-event field: a request's prefix-hit tokens are
            # attributed to the step its FIRST chunk runs
            # (start == cached_tokens) and 0 on later chunks, so summing
            # `cached` over a drain equals hit_tokens; cumulative rates
            # ride `hit_rate`/stats()
            cached = sum(w.req.cached_tokens for w in chunks
                         if w.start == w.req.cached_tokens)
            self.prefill_tokens_computed += computed
            self.max_chunk_tokens = max(self.max_chunk_tokens, computed)
            self._m_tokens.labels(kind="prefill").inc(computed)
            if cached:
                self._m_tokens.labels(kind="cached").inc(cached)
            serve_event("serve_prefill", batch=len(chunks),
                        flat_t=t_flat, tokens=computed, cached=cached,
                        step=self.steps, cow=self.cache.cow_copies,
                        shared_blocks=self.cache.shared_blocks,
                        hit_rate=round(self.cache.hit_rate(), 4),
                        occupancy=round(self.cache.occupancy(), 4),
                        queue_depth=self.scheduler.queue_depth)
        if decodes:
            serve_event("serve_decode", batch=len(decodes),
                        step=self.steps, drafted=drafted,
                        accepted=accepted,
                        occupancy=round(self.cache.occupancy(), 4),
                        queue_depth=self.scheduler.queue_depth)
        return len(chunks), len(decodes), computed, drafted

    def _fork_candidates(self, primary: Request, logits_row: np.ndarray,
                         now: float) -> None:
        """Split a finished prefill into n parallel-sampling candidates.
        Each sibling's cache sequence shares EVERY prompt block with the
        primary — fork_sequence only bumps refcounts; COW peels a
        private copy the first time a candidate writes into a shared
        block — so the prompt is prefilled once and held once no matter
        how large n is. Siblings enter the running set decode-ready
        (prefill_pos == len(prompt)) and sample their FIRST token from
        the same final-chunk logits row under seed + i: because
        _sample is deterministic in (seed, position) and the ragged
        step's rows are batch-invariant, candidate i's whole stream is
        bit-identical to a solo run submitted with that seed."""
        for i in range(1, primary.n_candidates):
            cb = (primary.fork_callback(i)
                  if primary.fork_callback is not None else None)
            sib = Request(
                prompt=list(primary.prompt),
                max_new_tokens=primary.max_new_tokens,
                temperature=primary.temperature,
                top_k=primary.top_k,
                seed=primary.seed + i,
                eos_id=primary.eos_id,
                callback=cb,
                deadline=primary.deadline,
                cand_index=i,
                parent=primary)
            sib.enqueue_time = primary.enqueue_time
            sib.admit_time = primary.admit_time
            sib.prefill_pos = len(sib.prompt)      # decode-ready
            sib.cached_tokens = len(sib.prompt)    # whole prompt shared
            sib.state = RUNNING
            self.cache.fork_sequence(primary.req_id, sib.req_id)
            self.scheduler.running.append(sib)
            primary.forks.append(sib)
            self.tracer.on_enqueue(sib.req_id)
            self.tracer.on_admit(sib.req_id)
            tok, lp = _sample(logits_row, sib, len(sib.prompt))
            sib.logprob_sum += lp
            sib.first_token_time = now
            self.tracer.on_first_token(sib.req_id)
            self._emit_token(sib, tok)
        self._set_sched_gauges()
        serve_event("serve_fork", req_id=primary.req_id,
                    candidates=primary.n_candidates,
                    shared_blocks=self.cache.shared_blocks,
                    occupancy=round(self.cache.occupancy(), 4))

    def _emit_token(self, req: Request, tok: int) -> None:
        req.generated.append(tok)
        self._m_tokens.labels(kind="generated").inc()
        if req.callback is not None:
            req.callback(tok)
        hit_eos = req.eos_id is not None and tok == req.eos_id
        out_of_room = (len(req.tokens) >= self.max_seq_len - 1)
        if hit_eos or req.num_generated >= req.max_new_tokens or out_of_room:
            self._finish(req, "eos" if hit_eos else "length")

    def _finish(self, req: Request, reason: str) -> None:
        req.finish_time = time.monotonic()
        if self.demote_finished and self.host_tier is not None:
            # demote BEFORE the scheduler frees the blocks: the decode
            # replica pulls exactly the prefix this request committed
            self.cache.demote_sequence(req.req_id, reason="finish")
        self.scheduler.finish(req, reason)
        self.finished[req.req_id] = req
        ttft_ms = (req.first_token_time - req.enqueue_time) * 1e3
        decode_s = max(req.finish_time - req.first_token_time, 1e-9)
        n_gen = req.num_generated
        # per-request latency accounting: the histograms every SLO /
        # serve_bench verdict reads (TPOT only for requests that
        # actually decoded past the first token)
        self._m_ttft.observe(ttft_ms)
        self._m_e2e.observe((req.finish_time - req.enqueue_time) * 1e3)
        if n_gen > 1:
            self._m_tpot.observe(decode_s * 1e3 / (n_gen - 1))
        self._m_reqs.labels(reason=reason).inc()
        self._set_sched_gauges()
        self.tracer.on_finish(req.req_id, reason)
        serve_event("serve_done", req_id=req.req_id, reason=reason,
                    tokens=n_gen, ttft_ms=round(ttft_ms, 3),
                    decode_tok_s=round(max(n_gen - 1, 0) / decode_s, 2),
                    cached_tokens=req.cached_tokens,
                    preemptions=req.preemptions)

    def _on_preempt(self, req: Request) -> None:
        self._m_preempts.inc()
        self._set_sched_gauges()
        self.tracer.on_preempt(req.req_id)
        serve_event("serve_preempt", req_id=req.req_id,
                    kept_tokens=len(req.prompt),
                    occupancy=round(self.cache.occupancy(), 4))

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Cumulative serve counters: prefix-cache hit rate, prefill
        tokens actually computed, COW/shared block counts, peak block
        occupancy. The serve_bench verdicts key off these."""
        out = self.cache.stats()
        out.update({
            "prefill_tokens_computed": self.prefill_tokens_computed,
            "peak_occupancy": round(self.peak_occupancy, 4),
            "max_chunk_tokens": self.max_chunk_tokens,
            "steps": self.steps,
        })
        return out

    def reset_stats(self) -> None:
        """Zero the cumulative counters (after a warmup drain) without
        touching compiled steps or live state. Also zeroes this
        engine's metrics registry IN PLACE (families and child handles
        survive) and the request tracer — the post-warmup baseline
        serve_bench measures from."""
        self.cache.reset_stats()
        self.prefill_tokens_computed = 0
        self.peak_occupancy = 0.0
        self.max_chunk_tokens = 0
        self.steps = 0
        self.obs.reset()
        self.tracer.reset()
        # static-config series survive the zeroing: the tp degree and
        # the construction-time collective microprobe describe this
        # engine, not the traffic the reset is drawing a baseline for
        # (the warmup path restores ptpu_engine_compiles the same way)
        self._m_tp_size.set(float(self.tp_size))
        if self.host_tier is not None:
            self.host_tier.republish_boot_state()
        if self._serve_tp is not None:
            self._m_allreduce.labels(mode=self._serve_tp.mode).observe(
                self._allreduce_probe_ms)

    # -- convenience --------------------------------------------------------
    def generate(self, prompts: List[List[int]], max_new_tokens: int = 32,
                 **kwargs) -> List[List[int]]:
        """Batch-submit prompts, drain, return generations in order."""
        reqs = [self.add_request(p, max_new_tokens=max_new_tokens, **kwargs)
                for p in prompts]
        self.run()
        return [self._generated_of(r) for r in reqs]

    @staticmethod
    def _generated_of(req: Request) -> List[int]:
        """All tokens generated for a request, reassembling the ones a
        preemption folded into the prompt."""
        if req.preempt_carry:
            carried = req.prompt[len(req.prompt) - req.preempt_carry:]
            return list(carried) + list(req.generated)
        return list(req.generated)

"""Host-RAM KV tier: the second tier behind PagedKVCache.

Device HBM is the capacity wall of continuous batching (ROADMAP
"Tiered, fleet-wide KV"): the block pool is single-tier, so preemption
is recompute-only (quadratic in context) and cached-free prefix blocks
die the moment `_pop_free` recycles them. This module keeps that KV
alive one tier down:

- DEMOTION. When the pool is about to destroy cached content — a
  cached-free block handed out for fresh tokens, or a preempted
  sequence's committed blocks — the full block rows are device_get
  into host buffers, keyed by the SAME content token tuple the prefix
  index uses (the key IS the content, so the tier inherits the index's
  collision-free identity).
- REVIVAL. `PagedKVCache.alloc_sequence` walks a new prompt past its
  device-index match into this tier; every host hit claims a fresh
  device block and stages a (block, layers) load the engine flushes
  with functional `pool.at[block].set(...)` writes BEFORE the step
  that reads them — a DMA instead of a re-prefill. Tier traffic is
  entirely host-side: no new jit, the one-compile invariant holds.
- BUDGET. Entries live in an LRU ordered by last touch under a byte
  budget; demotions past the budget evict the coldest entries.
- INT8 MODE. `int8=True` stores blocks quantized with the symmetric
  abs-max scheme from paddle_tpu/quant/int8_compute.py (one scale per
  k/v array per layer per block), roughly doubling effective tier
  capacity; revival dequantizes. fp mode is bit-exact round-trip; the
  int8 tier is exact to within scale/127 per element
  (tests/test_kvtier.py gates the bound).

The tier is thread-safe: the engine loop mutates it while the serve
front-end's handler threads read `advertised()` for the fleet prefix
directory (serve/router.py) — replicas advertise (prefix length,
crc32 digest, tier) and the router prefers the replica holding the
longest warm prefix at the hottest tier.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry
from paddle_tpu.quant.int8_compute import (dequantize_host_int8,
                                           quantize_host_int8)

# per-layer block payload as the cache hands it over / gets it back:
# [(k_block, v_block), ...] — one (block_size, Hkv, hd) pair per layer
BlockLayers = List[Tuple[np.ndarray, np.ndarray]]


def prefix_digest(tokens: Sequence[int]) -> str:
    """Stable 8-hex-digit digest of a token prefix: crc32 over the ids
    as little-endian u32 — the same encoding `router.prefix_shard`
    hashes, so every process derives identical digests. Used only for
    fleet directory ADVERTISEMENT (a collision can misroute, never
    corrupt: the receiving replica re-matches on exact tokens)."""
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little")
                   for t in tokens)
    return format(zlib.crc32(raw), "08x")


class _Entry:
    """One demoted block: per-layer payloads + resident byte count.
    Payloads are immutable after construction, so readers may touch
    them outside the tier lock."""

    __slots__ = ("blobs", "nbytes")

    def __init__(self, blobs: list, nbytes: int):
        self.blobs = blobs
        self.nbytes = nbytes


class HostKVTier:
    """LRU byte-budgeted host store of full KV blocks, keyed by the
    prefix index's content token tuples. `int8=True` quantizes on
    demotion and dequantizes on revival."""

    def __init__(self, byte_budget: int, int8: bool = False,
                 registry: Optional[MetricsRegistry] = None):
        if byte_budget <= 0:
            raise ValueError(f"byte_budget {byte_budget} <= 0")
        self.byte_budget = int(byte_budget)
        self.int8 = bool(int8)
        # One lock covers the entry map and the byte counter; payload
        # arrays are immutable so get()/advertised() only need it for
        # the map touch, never for the (de)quantize work.
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = \
            OrderedDict()                    # guarded-by: self._lock
        self._bytes = 0                      # guarded-by: self._lock
        self._warm_start_blocks = 0          # guarded-by: self._lock
        reg = registry if registry is not None else default_registry()
        self._c_demoted = reg.counter(
            "ptpu_kv_tier_demoted_blocks_total",
            "KV blocks copied out to the host tier",
            labelnames=("reason",))     # reason=evict|preempt|finish
        self._c_revived = reg.counter(
            "ptpu_kv_tier_revived_blocks_total",
            "Host-tier blocks revived into the device pool")
        self._c_revived_toks = reg.counter(
            "ptpu_kv_tier_revived_tokens_total",
            "Prompt tokens served from the host tier instead of "
            "re-prefill")
        self._c_lru = reg.counter(
            "ptpu_kv_tier_lru_evictions_total",
            "Host-tier entries dropped by the LRU byte budget")
        self._g_bytes = reg.gauge(
            "ptpu_kv_tier_bytes", "Host-tier resident bytes")
        self._g_entries = reg.gauge(
            "ptpu_kv_tier_entries", "Host-tier resident block entries")
        self._c_spill_saved = reg.counter(
            "ptpu_kv_tier_spill_saved_blocks_total",
            "Host-tier blocks spilled to disk at drain/interval")
        self._c_spill_loaded = reg.counter(
            "ptpu_kv_tier_spill_loaded_blocks_total",
            "Host-tier blocks warm-started from a disk spill at boot")
        self._g_spill_bytes = reg.gauge(
            "ptpu_kv_tier_spill_bytes",
            "On-disk size of the latest spill")

    # -- capacity ---------------------------------------------------------
    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def contains(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries

    # -- demotion ---------------------------------------------------------
    def put(self, key: tuple, layers: BlockLayers,
            reason: str = "evict") -> bool:
        """Store one full block's per-layer KV under `key`. Quantizes
        in int8 mode, charges the byte budget, and LRU-evicts the
        coldest entries while over it. Returns False when the single
        block exceeds the whole budget (nothing stored)."""
        blobs = []
        nbytes = 0
        for k, v in layers:
            k = np.asarray(k)
            v = np.asarray(v)
            if self.int8:
                kq, ks = quantize_host_int8(k)
                vq, vs = quantize_host_int8(v)
                blobs.append((kq, ks, vq, vs, k.dtype))
                nbytes += kq.nbytes + vq.nbytes + 16
            else:
                blobs.append((k, v))
                nbytes += k.nbytes + v.nbytes
        if nbytes > self.byte_budget:
            return False
        lru_evicted = 0
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            self._entries[key] = _Entry(blobs, nbytes)
            self._bytes += nbytes
            while self._bytes > self.byte_budget:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                lru_evicted += 1
            bytes_now, count = self._bytes, len(self._entries)
        self._c_demoted.labels(reason=reason).inc()
        if lru_evicted:
            self._c_lru.inc(lru_evicted)
        self._g_bytes.set(float(bytes_now))
        self._g_entries.set(float(count))
        return True

    def put_device_int8(self, key: tuple, qlayers: list, dtype,
                        reason: str = "evict") -> bool:
        """Demote-to-host FAST PATH for a block already int8 on device
        (PagedKVCache's compressed tier spilling its coldest entry):
        per-layer (kq, ks, vq, vs) payloads arrive quantized, and the
        content round-trips in ONE quant step total, never two. An
        int8-mode tier stores them verbatim — revival dequantizes with
        the original device scales, byte-identical to revival straight
        from the int8 pool. An fp-mode tier stores the exact
        dequantization: dequantize is deterministic, so no second
        quantization ever happens either way."""
        dtype = np.dtype(dtype)
        blobs = []
        nbytes = 0
        for kq, ks, vq, vs in qlayers:
            kq = np.asarray(kq)
            vq = np.asarray(vq)
            if self.int8:
                blobs.append((kq, float(ks), vq, float(vs), dtype))
                nbytes += kq.nbytes + vq.nbytes + 16
            else:
                k = dequantize_host_int8(kq, float(ks), dtype)
                v = dequantize_host_int8(vq, float(vs), dtype)
                blobs.append((k, v))
                nbytes += k.nbytes + v.nbytes
        if not self._insert_raw(key, blobs, nbytes):
            return False
        self._c_demoted.labels(reason=reason).inc()
        return True

    # -- revival ----------------------------------------------------------
    def get(self, key: tuple) -> Optional[BlockLayers]:
        """Per-layer (k, v) float arrays for a stored block (LRU touch),
        or None. The entry stays resident — one host copy can revive
        onto any number of device blocks over its lifetime."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            blobs = entry.blobs
        if not self.int8:
            return list(blobs)
        return [(dequantize_host_int8(kq, ks, dtype),
                 dequantize_host_int8(vq, vs, dtype))
                for kq, ks, vq, vs, dtype in blobs]

    def note_revived(self, blocks: int, tokens: int) -> None:
        """The cache revived `blocks` host blocks covering `tokens`
        prompt tokens at admission (telemetry only)."""
        if blocks:
            self._c_revived.inc(blocks)
        if tokens:
            self._c_revived_toks.inc(tokens)

    # -- fleet directory --------------------------------------------------
    def advertised(self, limit: int = 512) -> List[Tuple[int, str]]:
        """(prefix length, digest) for the most recently touched
        entries — what a replica publishes on /kvprefixes for the
        router's fleet prefix directory. Thread-safe."""
        with self._lock:
            keys = list(self._entries.keys())
        if limit and len(keys) > limit:
            keys = keys[-limit:]
        return [(len(k), prefix_digest(k)) for k in keys]

    def entry_by_digest(self, digest: str
                        ) -> Optional[Tuple[tuple, list, int]]:
        """Raw (key, blobs, nbytes) for the resident entry whose
        content digest matches, or None — the `GET /kvblocks/<digest>`
        lookup (serve/kvxfer.py). Blobs come back still encoded (int8
        stays int8) and immutable, so the caller may serialize outside
        the lock; the entry is NOT LRU-touched — a fleet pull must not
        distort this replica's local heat ordering. Newest entries win
        a (vanishingly unlikely) digest collision."""
        with self._lock:
            for key in reversed(self._entries):
                if prefix_digest(key) == digest:
                    ent = self._entries[key]
                    return key, list(ent.blobs), ent.nbytes
        return None

    def insert_encoded(self, key: tuple, blobs: list, nbytes: int) -> bool:
        """Insert an entry that is ALREADY in this tier's blob encoding
        (the fleet KV-transfer pull path, serve/kvxfer.py): the wire
        carries the source tier's raw blobs, so fp entries stay
        bit-exact and int8 entries keep their original scales — revival
        on this replica dequantizes identically to the source."""
        return self._insert_raw(key, blobs, nbytes)

    # -- warm restarts: disk spill ----------------------------------------
    # Layout inside the spill dir (tier-spill.json commits LAST, so a
    # manifest that exists implies a complete npz — the same
    # write-tmp-then-rename commit protocol as io/checkpoint.py):
    #   tier-spill.npz    every blob array, named e{entry}_{slot}
    #   tier-spill.json   {"version", "int8", "crc32", "entries": [...]}

    _SPILL_NPZ = "tier-spill.npz"
    _SPILL_JSON = "tier-spill.json"

    def spill(self, dirpath: str) -> int:
        """Write every resident entry (LRU order preserved) to
        `dirpath`, atomically replacing any previous spill. Returns the
        number of blocks written. Payloads are immutable, so only the
        snapshot of the entry map needs the lock — serialization runs
        outside it."""
        with self._lock:
            snapshot = list(self._entries.items())
        os.makedirs(dirpath, exist_ok=True)
        arrays: dict = {}
        manifest_entries = []
        for i, (key, entry) in enumerate(snapshot):
            slots = []
            dtypes = []
            for j, blob in enumerate(entry.blobs):
                if self.int8:
                    kq, ks, vq, vs, dtype = blob
                    parts = (kq, ks, vq, vs)
                    dtypes.append(np.dtype(dtype).name)
                else:
                    parts = blob
                for p, arr in enumerate(parts):
                    slot = f"e{i}_l{j}_p{p}"
                    arrays[slot] = np.asarray(arr)
                    slots.append(slot)
            manifest_entries.append(
                {"key": [int(t) for t in key], "layers": len(entry.blobs),
                 "nbytes": entry.nbytes, "slots": slots, "dtypes": dtypes})
        # tmp name must keep the .npz suffix (np.savez appends it)
        npz_tmp = os.path.join(dirpath, "tier-spill.tmp.npz")
        np.savez(npz_tmp, **arrays)
        with open(npz_tmp, "rb") as f:
            crc = zlib.crc32(f.read())
        os.replace(npz_tmp, os.path.join(dirpath, self._SPILL_NPZ))
        manifest = {"version": 1, "int8": self.int8, "crc32": crc,
                    "entries": manifest_entries}
        json_tmp = os.path.join(dirpath, self._SPILL_JSON + ".tmp")
        with open(json_tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(json_tmp, os.path.join(dirpath, self._SPILL_JSON))
        self._c_spill_saved.inc(len(snapshot))
        self._g_spill_bytes.set(float(
            os.path.getsize(os.path.join(dirpath, self._SPILL_NPZ))))
        return len(snapshot)

    def load_spill(self, dirpath: str) -> int:
        """Warm-start from a spill written by `spill()`: re-inserts
        every entry (oldest first, so relative LRU order survives the
        restart) under the normal byte budget. Tolerant by design — a
        missing, torn, or mode-mismatched spill warm-starts NOTHING and
        returns 0; a cold boot is always safe. Returns blocks loaded."""
        manifest_path = os.path.join(dirpath, self._SPILL_JSON)
        npz_path = os.path.join(dirpath, self._SPILL_NPZ)
        if not (os.path.exists(manifest_path) and os.path.exists(npz_path)):
            return 0
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            if manifest.get("version") != 1 \
                    or bool(manifest.get("int8")) != self.int8:
                return 0
            with open(npz_path, "rb") as f:
                if zlib.crc32(f.read()) != manifest.get("crc32"):
                    return 0
            arrays = np.load(npz_path)
            loaded = 0
            for ent in manifest["entries"]:
                key = tuple(int(t) for t in ent["key"])
                blobs = []
                slots = iter(ent["slots"])
                for j in range(ent["layers"]):
                    if self.int8:
                        kq, ks, vq, vs = (arrays[next(slots)]
                                          for _ in range(4))
                        # scales round-trip as 0-d float64 arrays;
                        # restore the python-float type put() stored so
                        # dequantize promotes identically (bit-exact
                        # revival vs the pre-restart tier)
                        blobs.append((kq, float(ks), vq, float(vs),
                                      np.dtype(ent["dtypes"][j])))
                    else:
                        blobs.append((arrays[next(slots)],
                                      arrays[next(slots)]))
                if self._insert_raw(key, blobs, int(ent["nbytes"])):
                    loaded += 1
        except (OSError, KeyError, ValueError, json.JSONDecodeError,
                zlib.error, StopIteration):
            return 0
        if loaded:
            with self._lock:
                self._warm_start_blocks += loaded
            self._c_spill_loaded.inc(loaded)
        return loaded

    def republish_boot_state(self) -> None:
        """Re-publish the series that describe this tier's BOOT, not
        its traffic: a post-warmup registry reset (engine.reset_stats)
        zeroes every family in place, but the warm-start really did
        happen — restore the loaded counter and occupancy gauges the
        same way the engine restores ptpu_engine_compiles."""
        with self._lock:
            bytes_now, count = self._bytes, len(self._entries)
            warm = self._warm_start_blocks
        if warm:
            self._c_spill_loaded.inc(warm)
        self._g_bytes.set(float(bytes_now))
        self._g_entries.set(float(count))

    def _insert_raw(self, key: tuple, blobs: list, nbytes: int) -> bool:
        """Insert an already-encoded entry (spill revival path): same
        budget/LRU accounting as put(), no re-quantization."""
        if nbytes > self.byte_budget:
            return False
        lru_evicted = 0
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            self._entries[key] = _Entry(blobs, nbytes)
            self._bytes += nbytes
            while self._bytes > self.byte_budget:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                lru_evicted += 1
            bytes_now, count = self._bytes, len(self._entries)
        if lru_evicted:
            self._c_lru.inc(lru_evicted)
        self._g_bytes.set(float(bytes_now))
        self._g_entries.set(float(count))
        return True

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"tier_entries": len(self._entries),
                    "tier_bytes": self._bytes,
                    "tier_int8": self.int8}

"""Continuous batching scheduler (iteration-level scheduling, Orca-style)
with chunked prefill.

Classic static batching admits a batch, decodes until EVERY member
finishes, then admits the next — short requests wait on the longest
one, and freed KV memory idles. Continuous batching reschedules every
STEP: finished sequences leave the running set immediately, waiting
requests are admitted the moment blocks free up, and each step the
scheduler hands the engine ONE MIXED plan — every decode-ready row
plus budget-bounded prefill chunks, packed into the same launch.

Policy (simple and deterministic, ENGINE.md §scheduler):

- Admission is FIFO and block-bound only: a request admits when a
  batch slot is open and its prompt's blocks fit (prefix-cache hits
  shrink the bill). Admission allocates the WHOLE prompt's blocks and
  records how many leading tokens the prefix cache already holds —
  those are never prefilled.
- MIXED STEPS: every step carries one row per running request — a
  decode row (its next token) for decode-ready sequences, a prefill
  chunk of at most `max_prefill_tokens` total tokens for sequences
  still prefilling (Sarathi-style piggybacking). A long prompt can
  never starve running decodes (they advance EVERY step) and is never
  starved by them (every step also moves its prefill forward), so
  both inter-token latency and TTFT stay bounded without the old
  chunk/decode alternation. A request whose final chunk ran becomes
  decode-ready (the engine samples its first token from that chunk's
  logits). A decode row is just the 1-token window
  [seq_len, seq_len+1) of req.tokens — the engine packs both row
  kinds into one flat launch (kernels/paged_attention.py ragged).
- Preemption by recompute: when a decode append or a COW copy needs a
  block and the pool is empty, the LAST-admitted running request is
  evicted — its blocks are dropped (refcounts) and it rejoins the
  FRONT of the waiting queue with prompt := prompt + generated, so its
  re-prefill reproduces the exact KV state (cheaper than copy-out for
  short sequences, and the deterministic choice keeps tests
  reproducible). FIFO order of the others is preserved.

The scheduler owns no device state; it manipulates the PagedKVCache's
host-side bookkeeping and Request objects. The engine turns its plans
into jitted prefill/decode calls.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from paddle_tpu.engine.paged_cache import CacheExhausted, PagedKVCache

# request lifecycle: WAITING -> RUNNING -> FINISHED (PREEMPTED -> WAITING)
WAITING, RUNNING, FINISHED = "waiting", "running", "finished"

_req_ids = itertools.count()


@dataclass
class Request:
    """One inference request; `prompt` grows on preemption (recompute)."""
    prompt: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0          # 0 => greedy
    top_k: int = 0                    # 0 => full vocab
    seed: int = 0
    eos_id: Optional[int] = None
    callback: Optional[Callable[[int], None]] = None  # per-token stream
    # absolute monotonic completion deadline (inf = none). The
    # scheduler's preemption choice reads it: the victim is the running
    # request with the MOST slack, so tight-deadline requests keep
    # their KV state under pool pressure.
    deadline: float = float("inf")
    # parallel sampling (best-of-n): the engine forks n_candidates - 1
    # siblings off this request's finished prefill, all sharing its
    # prompt blocks (PagedKVCache.fork_sequence). Siblings are ordinary
    # requests with cand_index > 0 and `parent` set; the primary lists
    # them in `forks`. fork_callback(i) builds sibling i's per-token
    # stream callback (None = decode silently, the best_of > n case).
    n_candidates: int = 1
    cand_index: int = 0
    parent: Optional["Request"] = None
    forks: List["Request"] = field(default_factory=list)
    fork_callback: Optional[Callable[[int],
                                     Optional[Callable[[int], None]]]] = None
    # cumulative log-probability of the sampled tokens under each
    # step's sampling distribution — the best-of-n ranking signal
    logprob_sum: float = 0.0
    req_id: int = field(default_factory=lambda: next(_req_ids))
    generated: List[int] = field(default_factory=list)
    state: str = WAITING
    preemptions: int = 0
    preempt_carry: int = 0            # tokens folded into prompt on preempt
    prefill_pos: int = 0              # prompt tokens prefilled (or cached)
    cached_tokens: int = 0            # prefix-cache hit at last admission
    enqueue_time: float = 0.0
    admit_time: float = 0.0           # first admission (queue-wait metric)
    first_token_time: float = 0.0
    finish_time: float = 0.0
    finish_reason: str = ""

    @property
    def tokens(self) -> List[int]:
        """Prompt as the cache must hold it (original + regenerated)."""
        return self.prompt + self.generated

    @property
    def num_generated(self) -> int:
        """Tokens generated across preemptions (prompt absorbs them)."""
        return len(self.generated) + self.preempt_carry

    @property
    def prefilling(self) -> bool:
        # against the PROMPT, not tokens: generated tokens enter the
        # cache via decode's append/advance, never via a chunk
        return self.prefill_pos < len(self.prompt)


@dataclass
class StepRow:
    """One row of a mixed step: run `req`'s token window
    [start, start + length). decode=True is the next-token window of a
    decode-ready sequence (its slots already reserved); decode=False
    is a prefill chunk of the prompt. A decode row with a non-empty
    `draft` is a SPECULATIVE row: its window is [start, start+1+k) —
    the base token plus k drafted tokens — and the engine verifies all
    k positions from the one launch, emitting the accepted prefix."""
    req: Request
    start: int
    length: int
    decode: bool = False
    draft: List[int] = field(default_factory=list)


# back-compat alias: a prefill chunk is a StepRow with decode=False
PrefillChunk = StepRow

Plan = List[StepRow]


class Scheduler:
    """Decides, per engine step, what work runs: one mixed plan of
    decode rows and prefill chunks. Bounds: `max_batch_size` concurrent
    running sequences (the engine packs exactly this many rows into its
    compiled step), `max_prefill_tokens` prompt tokens per step's
    chunks (decode rows ride free), `max_seq_len` ceiling on
    prompt+generation."""

    def __init__(self, cache: PagedKVCache, max_batch_size: int = 8,
                 max_prefill_tokens: int = 512, max_seq_len: int = 2048,
                 drafter=None):
        self.cache = cache
        self.max_batch_size = max_batch_size
        self.max_prefill_tokens = max_prefill_tokens
        self.max_seq_len = max_seq_len
        # speculative decoding (engine/draft.py): when set, decode-ready
        # rows carry up to drafter.k drafted tokens for batched
        # verification; None = plain 1-token decode rows
        self.drafter = drafter
        self.waiting: deque[Request] = deque()
        self.running: List[Request] = []
        # engine hooks: fired after a preemption moves a req back to
        # waiting / after admission moves one to running (telemetry:
        # queue-wait histograms and request-lifecycle spans)
        self.on_preempt: Optional[Callable[[Request], None]] = None
        self.on_admit: Optional[Callable[[Request], None]] = None

    # -- intake -----------------------------------------------------------
    def add(self, req: Request) -> None:
        if len(req.prompt) > self.max_seq_len:
            raise ValueError(
                f"prompt len {len(req.prompt)} > max_seq_len {self.max_seq_len}")
        req.state = WAITING
        self.waiting.append(req)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- planning ---------------------------------------------------------
    def next_batch(self) -> Optional[Plan]:
        """Plan one MIXED step: a list of StepRows (decode rows plus
        prefill chunks, one row per running request, in admission
        order) or None when idle. Admission allocates cache blocks
        (prefix hits included) and moves requests to RUNNING; chunk
        planning advances `prefill_pos` optimistically (the engine
        always executes the plan it is handed); every decode row has
        its next-token block reserved before it enters the plan,
        preempting from the tail if the pool runs dry. The chunk token
        budget goes to the head request first, so earlier prompts
        reach their first token sooner."""
        self._try_admit()
        if not self.running:
            self._check_liveness()
            return None
        rows: List[StepRow] = []
        budget = self.max_prefill_tokens
        for req in list(self.running):
            if req not in self.running:     # preempted by an earlier row
                continue
            if req.prefilling:
                if budget <= 0:
                    continue
                take = min(len(req.prompt) - req.prefill_pos, budget)
                start = req.prefill_pos
                # COW (a chunk writing into a shared block) may need a
                # free block; a dry pool preempts from the tail
                self._ensure_writable_or_preempt(req, start, start + take)
                req.prefill_pos += take
                budget -= take
                rows.append(StepRow(req, start, take, decode=False))
            else:
                draft = self._propose_draft(req)
                if draft:
                    try:
                        # all-or-nothing: base token + k draft slots in
                        # one transaction; a short pool drops the draft
                        # (below) rather than preempting for it —
                        # speculation is an optimization, never worth
                        # evicting a neighbor's KV state
                        self.cache.reserve_slots(req.req_id,
                                                 1 + len(draft))
                        rows.append(StepRow(
                            req, self.cache.seq_len(req.req_id),
                            1 + len(draft), decode=True, draft=draft))
                        continue
                    except CacheExhausted:
                        pass
                if self._reserve_decode_block(req):
                    rows.append(StepRow(
                        req, self.cache.seq_len(req.req_id), 1,
                        decode=True))
        # a later row's block starvation may have evicted an
        # ALREADY-planned request (_pick_victim considers every running
        # row): its table is freed and prefill_pos reset, so its row
        # must not reach the engine
        rows = [w for w in rows if w.req in self.running]
        if rows:
            return rows
        if self.running:
            return self.next_batch()    # everything preempted; replan
        self._check_liveness()
        return None

    def _propose_draft(self, req: Request) -> List[int]:
        """Draft tokens for one decode-ready row, capped so the whole
        speculative window — base token + k drafts, each potentially
        EMITTING a token — can never overrun the request's token budget
        or the sequence-length ceiling."""
        if self.drafter is None:
            return []
        room = min(self.drafter.k,
                   req.max_new_tokens - req.num_generated - 1,
                   self.max_seq_len - len(req.tokens) - 1)
        if room <= 0:
            return []
        return self.drafter.propose(req.tokens, room)

    def _slots_of(self, req: Request) -> int:
        """Batch slots a request claims: itself, plus — while it still
        prefills — one per sibling the engine will fork at its final
        chunk. Admission counts the whole group up front so the forks'
        decode rows are guaranteed batch room the moment they exist."""
        if not req.prefilling:
            return 1
        return 1 + max(0, req.n_candidates - 1 - len(req.forks))

    def _try_admit(self) -> List[Request]:
        admitted: List[Request] = []
        while self.waiting:
            req = self.waiting[0]
            slots = (sum(self._slots_of(r) for r in self.running)
                     + sum(self._slots_of(r) for r in admitted))
            if (slots + self._slots_of(req) > self.max_batch_size
                    or not self.cache.can_allocate(req.tokens)):
                break       # FIFO: don't skip ahead of the head request
            self.waiting.popleft()
            # re-admissions re-hit their own committed blocks; don't let
            # that inflate the prefix-cache hit rate
            cached = self.cache.alloc_sequence(
                req.req_id, req.tokens, count_stats=req.preemptions == 0)
            req.prefill_pos = cached
            req.cached_tokens = cached
            req.state = RUNNING
            admitted.append(req)
        self.running.extend(admitted)
        if self.on_admit is not None:
            for req in admitted:
                self.on_admit(req)
        return admitted

    def _ensure_writable_or_preempt(self, req: Request, start: int,
                                    end: int) -> None:
        """COW the chunk's target blocks, evicting tail requests (never
        `req` itself) while the pool is dry."""
        while True:
            try:
                self.cache.ensure_writable(req.req_id, start, end)
                return
            except CacheExhausted:
                victim = self._pick_victim(req)
                if victim is None:
                    raise
                self.preempt(victim)

    def _reserve_decode_block(self, req: Request) -> bool:
        """Ensure a decode-ready sequence can hold one more token,
        evicting from the tail (last admitted) until allocation holds.
        Returns False when `req` itself was preempted along the way."""
        while req in self.running:
            try:
                self.cache.append_token(req.req_id)
                return True
            except CacheExhausted:
                victim = self._pick_victim(req)
                if victim is None:
                    raise CacheExhausted(
                        "single sequence exceeds total KV pool; "
                        "increase num_blocks or lower max_seq_len")
                self.preempt(victim)
        return False

    def _preempt_cost(self, req: Request) -> float:
        """Modeled cost of evicting `req` and bringing it back. Without
        any lower tier every committed token re-prefills, and attention
        over the growing context makes that superlinear: ~n^2. With a
        host tier, committed FULL blocks swap out and revive by DMA
        (linear in bytes ~ n) and only the uncommitted tail re-prefills
        (~tail^2) — which is why long-context victims flip from worst
        choice to best under a tier. The in-device int8 rung is
        CHEAPER still: demotion and promotion are on-device lane
        scatters (no host DMA on either side) — but only as many blocks
        as the int8 pool has FREE slots get that rate; a demotion
        beyond that spills to the host rung (with a tier) or drops
        content entirely (without one, making it recompute-only), so
        the cheap credit is capped by free-slot capacity rather than
        handed to every committed block of an arbitrarily long
        victim. With direct reads (promote_hits != 1) the int8 rate
        drops further: revival no longer pays the promote round-trip
        (fp claim + dequantize scatter) — re-admission just bias-encodes
        the resident slots into the new block table."""
        n = len(req.tokens)
        if self.cache.host_tier is None \
                and not self.cache.compress_enabled:
            return float(n * n)
        full = (n // self.cache.block_size) * self.cache.block_size
        tail = n - full
        if self.cache.compress_enabled:
            cheap = min(full,
                        self.cache.compress_free_slots
                        * self.cache.block_size)
            rest = full - cheap
            rate = 0.1 if self.cache.direct_read_enabled else 0.25
            if self.cache.host_tier is not None:
                return float(cheap * rate + rest + tail * tail)
            return float(cheap * rate + rest * rest + tail * tail)
        return float(full + tail * tail)

    def _pick_victim(self, keep: Request) -> Optional[Request]:
        """The running request (other than `keep`) with the MOST
        deadline slack — a recompute preemption costs its victim a full
        re-prefill, so it should land on the request that can best
        absorb it. Without deadlines every slack is +inf and the choice
        degrades to the original deterministic rule: last admitted.
        With a host tier or the in-device compressed tier attached,
        equal-slack candidates are split by the swap-vs-recompute cost
        model instead (cheapest round-trip loses its blocks); with
        neither the legacy rule is bit-exact. None when nothing else is
        left to evict."""
        if self.cache.host_tier is None \
                and not self.cache.compress_enabled:
            best: Optional[Request] = None
            for r in self.running:      # later index wins ties (stable max)
                if r is not keep and (best is None
                                      or r.deadline >= best.deadline):
                    best = r
            return best
        best = None
        best_cost = 0.0
        for r in self.running:          # later index wins ties (stable max)
            if r is keep:
                continue
            cost = self._preempt_cost(r)
            if (best is None or r.deadline > best.deadline
                    or (r.deadline == best.deadline and cost <= best_cost)):
                best, best_cost = r, cost
        return best

    def preempt(self, req: Request) -> None:
        """Evict by recompute: drop block refs, fold generated tokens
        into the prompt, and requeue at the FRONT so it re-prefills
        first. With a host tier the committed blocks demote first —
        re-admission then revives them by DMA and only the tail
        recomputes."""
        self.cache.demote_sequence(req.req_id)
        self.cache.free_sequence(req.req_id)
        self.running.remove(req)
        req.preempt_carry += len(req.generated)
        req.prompt = req.prompt + req.generated
        req.generated = []
        req.preemptions += 1
        req.prefill_pos = 0
        req.state = WAITING
        self.waiting.appendleft(req)
        if self.on_preempt is not None:
            self.on_preempt(req)

    def _check_liveness(self) -> None:
        """With an idle engine and an empty pool, a head request that
        still can't admit NEVER will — fail loud instead of silently
        stranding it in the queue. (Chunked prefill removed the
        prefill-budget ceiling: any prompt that fits the pool admits.)"""
        if not self.waiting or self.running:
            return
        req = self.waiting[0]
        n = len(req.tokens)
        if self.cache.blocks_for(n) > self.cache.num_blocks - 1:
            raise CacheExhausted(
                f"request {req.req_id} ({n} tokens incl. "
                f"{req.preempt_carry} preempt-folded) can never be "
                f"scheduled; raise num_blocks ({self.cache.num_blocks})")

    # -- completion -------------------------------------------------------
    def finish(self, req: Request, reason: str) -> None:
        self.cache.free_sequence(req.req_id)
        self.running.remove(req)
        req.state = FINISHED
        req.finish_reason = reason

    def cancel(self, req: Request) -> bool:
        """Remove a request wherever it sits — the wait queue (no KV
        held) or the running set (frees its blocks; shared prefix
        blocks just drop one refcount and queued COW copies to freed
        blocks are cancelled by free_sequence). Returns False when the
        request already finished. Engine-thread only, BETWEEN steps: a
        cancelled row must never reach an in-flight plan (the serve
        front-end marshals client disconnects through the engine loop,
        serve/frontend.py)."""
        if req in self.running:
            self.cache.free_sequence(req.req_id)
            self.running.remove(req)
        elif req in self.waiting:
            self.waiting.remove(req)
        else:
            return False
        req.state = FINISHED
        req.finish_reason = "cancelled"
        return True

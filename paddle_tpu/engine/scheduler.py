"""Continuous batching scheduler (iteration-level scheduling, Orca-style).

Classic static batching admits a batch, decodes until EVERY member
finishes, then admits the next — short requests wait on the longest
one, and freed KV memory idles. Continuous batching reschedules every
STEP: finished sequences leave the running set immediately, waiting
requests are admitted the moment blocks free up, and each step the
scheduler hands the engine either one prefill batch or one decode
batch over the current running set.

Policy (simple and deterministic, ENGINE.md §scheduler):

- Prefill-priority: if any waiting request fits (KV blocks available,
  a running slot open, prompt under the per-step token budget), run a
  prefill step admitting as many as fit, FIFO. New requests reach
  their first token fast (TTFT), at the cost of slightly delaying
  in-flight decodes for one step.
- Otherwise run one decode step over all running sequences (one token
  each).
- Preemption by recompute: when decode needs a block and the pool is
  empty, the LAST-admitted running request is evicted — its blocks are
  freed and it rejoins the FRONT of the waiting queue with
  prompt := prompt + generated, so its re-prefill reproduces the exact
  KV state (cheaper than copy-out for short sequences, and the
  deterministic choice keeps tests reproducible). FIFO order of the
  others is preserved.

The scheduler owns no device state; it manipulates the PagedKVCache's
host-side bookkeeping and Request objects. The engine turns its plans
into jitted prefill/decode calls.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from paddle_tpu.engine.paged_cache import CacheExhausted, PagedKVCache

# request lifecycle: WAITING -> RUNNING -> FINISHED (PREEMPTED -> WAITING)
WAITING, RUNNING, FINISHED = "waiting", "running", "finished"

_req_ids = itertools.count()


@dataclass
class Request:
    """One inference request; `prompt` grows on preemption (recompute)."""
    prompt: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0          # 0 => greedy
    top_k: int = 0                    # 0 => full vocab
    seed: int = 0
    eos_id: Optional[int] = None
    callback: Optional[Callable[[int], None]] = None  # per-token stream
    req_id: int = field(default_factory=lambda: next(_req_ids))
    generated: List[int] = field(default_factory=list)
    state: str = WAITING
    preemptions: int = 0
    preempt_carry: int = 0            # tokens folded into prompt on preempt
    enqueue_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    finish_reason: str = ""

    @property
    def tokens(self) -> List[int]:
        """Prompt as the cache must hold it (original + regenerated)."""
        return self.prompt + self.generated

    @property
    def num_generated(self) -> int:
        """Tokens generated across preemptions (prompt absorbs them)."""
        return len(self.generated) + self.preempt_carry


class Scheduler:
    """Decides, per engine step, what work runs: a prefill batch or a
    decode batch. Bounds: `max_batch_size` concurrent running
    sequences (the engine compiles its decode step for exactly this
    batch), `max_prefill_tokens` padded prompt tokens per prefill step,
    `max_seq_len` ceiling on prompt+generation."""

    def __init__(self, cache: PagedKVCache, max_batch_size: int = 8,
                 max_prefill_tokens: int = 512, max_seq_len: int = 2048):
        self.cache = cache
        self.max_batch_size = max_batch_size
        self.max_prefill_tokens = max_prefill_tokens
        self.max_seq_len = max_seq_len
        self.waiting: deque[Request] = deque()
        self.running: List[Request] = []
        # engine hook, fired after a preemption moves a req back to waiting
        self.on_preempt: Optional[Callable[[Request], None]] = None

    # -- intake -----------------------------------------------------------
    def add(self, req: Request) -> None:
        if len(req.prompt) > self.max_seq_len:
            raise ValueError(
                f"prompt len {len(req.prompt)} > max_seq_len {self.max_seq_len}")
        req.state = WAITING
        self.waiting.append(req)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # -- planning ---------------------------------------------------------
    def next_batch(self) -> Optional[Tuple[str, List[Request]]]:
        """Plan one step: ("prefill", admitted) | ("decode", running) |
        None when idle. Prefill admission allocates cache blocks and
        moves requests to RUNNING; decode planning guarantees every
        running sequence has its next-token block reserved, preempting
        if the pool runs dry."""
        admitted = self._try_admit()
        if admitted:
            return ("prefill", admitted)
        if self.running:
            self._reserve_decode_blocks()
            if self.running:
                return ("decode", list(self.running))
            # everything got preempted; retry admission with freed blocks
            admitted = self._try_admit()
            if admitted:
                return ("prefill", admitted)
        if self.waiting and not self.running:
            # liveness check: with an idle engine and an empty pool, a
            # head request that still can't admit NEVER will — fail loud
            # instead of silently stranding it in the queue
            req = self.waiting[0]
            n = len(req.tokens)
            if (n > self.max_prefill_tokens
                    or self.cache.blocks_for(n) > self.cache.num_blocks - 1):
                raise CacheExhausted(
                    f"request {req.req_id} ({n} tokens incl. "
                    f"{req.preempt_carry} preempt-folded) can never be "
                    f"scheduled; raise max_prefill_tokens "
                    f"({self.max_prefill_tokens}) or num_blocks "
                    f"({self.cache.num_blocks})")
        return None

    def _try_admit(self) -> List[Request]:
        admitted: List[Request] = []
        budget = self.max_prefill_tokens
        while self.waiting:
            req = self.waiting[0]
            n = len(req.tokens)
            if (len(self.running) + len(admitted) >= self.max_batch_size
                    or n > budget
                    or not self.cache.can_allocate(n)):
                break       # FIFO: don't skip ahead of the head request
            self.waiting.popleft()
            self.cache.alloc_sequence(req.req_id, n)
            req.state = RUNNING
            admitted.append(req)
            budget -= n
        self.running.extend(admitted)
        return admitted

    def _reserve_decode_blocks(self) -> None:
        """Ensure every running sequence can hold one more token,
        evicting from the tail (last admitted) until allocation holds."""
        i = 0
        while i < len(self.running):
            req = self.running[i]
            try:
                self.cache.append_token(req.req_id)
                i += 1
            except CacheExhausted:
                if len(self.running) == 1:
                    raise CacheExhausted(
                        "single sequence exceeds total KV pool; "
                        "increase num_blocks or lower max_seq_len")
                victim = self.running[-1]
                if victim is req:
                    victim = self.running[-2]
                self.preempt(victim)
                # re-check same index (list may have shifted under us)
                i = self.running.index(req) if req in self.running else i

    def preempt(self, req: Request) -> None:
        """Evict by recompute: free blocks, fold generated tokens into the
        prompt, and requeue at the FRONT so it re-prefills first."""
        self.cache.free_sequence(req.req_id)
        self.running.remove(req)
        req.preempt_carry += len(req.generated)
        req.prompt = req.prompt + req.generated
        req.generated = []
        req.preemptions += 1
        req.state = WAITING
        self.waiting.appendleft(req)
        if self.on_preempt is not None:
            self.on_preempt(req)

    # -- completion -------------------------------------------------------
    def finish(self, req: Request, reason: str) -> None:
        self.cache.free_sequence(req.req_id)
        self.running.remove(req)
        req.state = FINISHED
        req.finish_reason = reason

"""PagedKVCache: refcounted block-pool KV storage with prefix sharing.

The HBM side of continuous batching (ENGINE.md): instead of one dense
[B, Tmax, Hkv, hd] cache per batch slot — which reserves worst-case
HBM for every request and welds batch membership to allocation — KV
state lives in ONE pool of fixed-size token blocks per layer
([num_blocks, block_size, Hkv, hd] for k and for v). A sequence owns a
BLOCK TABLE (ordered list of pool block ids); growing a sequence
appends a block from the free list, finishing/evicting one returns its
blocks in O(blocks). Fragmentation is bounded at block_size-1 wasted
slots per sequence, and admission capacity is a pure free-list check.

Prefix sharing (vLLM-style): blocks carry REFCOUNTS, and every FULL
block whose KV content is actually in the pool is registered in a
prefix index keyed by the exact token tuple of the sequence prefix it
ends (collision-free by construction — the key IS the content, not a
hash of it). `alloc_sequence` walks a new prompt block by block
through the index and reuses matching blocks instead of allocating:
a hit means those tokens' KV already exists, so the engine skips their
prefill compute AND their HBM. Because only committed-full blocks are
shareable, a shared block is write-immutable in the common case; the
one legal write into a shared block (a full-prompt hit is capped at
n-1 so the last token always recomputes for logits, landing mid-block)
triggers COPY-ON-WRITE: the writer gets a fresh private block and the
engine replays the old block's contents into it on device
(`drain_copies` -> the engine's compiled gather/scatter).

Freed blocks stay CACHED-FREE: when the last reference drops, the
block returns to the free list but keeps its prefix-index entry, so a
later request with the same prefix (the shared-system-prompt pattern)
revives it from the free list instead of recomputing — the KV is
still sitting in the pool untouched. The entry is evicted lazily, only
when `_pop_free` hands the block out for fresh content; frees append
to the right and pops take from the left, so the longest-freed cached
content is recycled first (FIFO ~ LRU here).

In-device compressed tier (ENGINE.md "In-device KV compression"): with
`compress_blocks > 0` the cache also owns a parallel int8 block pool
plus per-block k/v scales (`qpools`/`qscales`, slot 0 scratch like
block 0). Cold committed prefix blocks QUANTIZE INTO IT at ~half the
bytes — proactively while still fp-resident (compress_cold: the fp
copy and index entry stay, so fp hits remain byte-exact), and as the
first rung of the demotion ladder when the pool recycles a cached-free
block or a sequence preempts: device-fp -> device-int8 -> host tier ->
gone. A prefix hit against a compressed entry claims a fresh fp block
and stages a dequantize PROMOTION the engine flushes before the step
reads it — like a host-tier revival, except the payload never leaves
the device. Everything here is host-side bookkeeping; the actual
quantize/dequantize run as the engine's fixed-lane eager scatters
(primed at construction, jit cache stays at exactly 1), and a spilled
compressed entry ships its int8 payload + scales straight into the
host tier without a second quantization.

Host/device split: this class is the HOST-side allocator + bookkeeping
(free list, refcounts, per-sequence tables/lengths/tokens, prefix
index). The device-side pools are jnp arrays held in `self.pools` and
are updated FUNCTIONALLY — the jitted prefill-scatter / decode step /
COW block copy return new pool arrays and the engine assigns them
back. Nothing here traces into XLA; block tables cross into jit as
plain int32 operands.

Block 0 is reserved as a scratch block: padded batch rows (the engine
pads decode batches to a fixed size for one-compilation serving) write
their garbage k/v there, so a dummy row can never corrupt a live
sequence.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry

if TYPE_CHECKING:
    from paddle_tpu.engine.kvtier import HostKVTier


class CacheExhausted(Exception):
    """No free blocks; the scheduler must evict (preempt) a sequence."""


class PagedKVCache:
    """Refcounted block-pool KV cache shared by all layers of one model.

    All layers allocate in lockstep (a token occupies the same slot in
    every layer's pool), so ONE free list / block table set serves the
    whole stack; `pools` holds per-layer (k_pool, v_pool) arrays.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.float32,
                 enable_prefix_cache: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 host_tier: Optional["HostKVTier"] = None,
                 compress_blocks: int = 0,
                 promote_hits: int = 0,
                 tp_size: int = 1, mesh=None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is scratch)")
        if compress_blocks < 0:
            raise ValueError(f"compress_blocks {compress_blocks} < 0")
        if promote_hits < 0:
            raise ValueError(f"promote_hits {promote_hits} < 0")
        if tp_size < 1:
            raise ValueError(f"tp_size {tp_size} < 1")
        if num_kv_heads % tp_size != 0:
            # fail at construction, not as a reshape crash mid-serve:
            # the pool shards over kv-heads, so every chip must own a
            # whole number of them (GQA groups stay device-local)
            raise ValueError(
                f"num_kv_heads={num_kv_heads} not divisible by "
                f"tp_size={tp_size}: the KV pool shards over kv-heads "
                "(pool_shape), so tp must divide them evenly")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.tp_size = tp_size
        self.enable_prefix_cache = enable_prefix_cache
        # pools are allocated at the GLOBAL shape; under tp the mesh
        # shards the kv-head dim so each chip HOLDS pool_shape() bytes
        shape = (num_blocks, block_size, num_kv_heads, head_dim)
        self.pools: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]
        if mesh is not None and tp_size > 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            ns = NamedSharding(mesh, P(None, None, "tp", None))
            self.pools = [(jax.device_put(kp, ns), jax.device_put(vp, ns))
                          for kp, vp in self.pools]
        # optional in-device compressed tier: a parallel int8 block pool
        # (+ per-block k/v scales) cold prefix content quantizes into at
        # ~half the bytes. Slot 0 is scratch (the fixed-lane flushes pad
        # with it), mirroring fp block 0. Like `pools`, the arrays are
        # updated FUNCTIONALLY by the engine's eager lane scatters.
        self.compress_blocks = int(compress_blocks)
        self._compress_on = self.compress_blocks > 0 and enable_prefix_cache
        self.qpools: List[Tuple[jnp.ndarray, jnp.ndarray]] = []
        self.qscales: List[Tuple[jnp.ndarray, jnp.ndarray]] = []
        if self._compress_on:
            qshape = (self.compress_blocks + 1, block_size,
                      num_kv_heads, head_dim)
            self.qpools = [(jnp.zeros(qshape, jnp.int8),
                            jnp.zeros(qshape, jnp.int8))
                           for _ in range(num_layers)]
            self.qscales = [(jnp.ones((self.compress_blocks + 1,),
                                      jnp.float32),
                             jnp.ones((self.compress_blocks + 1,),
                                      jnp.float32))
                            for _ in range(num_layers)]
            if mesh is not None and tp_size > 1:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P
                qns = NamedSharding(mesh, P(None, None, "tp", None))
                self.qpools = [(jax.device_put(kq, qns),
                                jax.device_put(vq, qns))
                               for kq, vq in self.qpools]
        # compressed-tier bookkeeping (host-side): slot free list,
        # content-keyed LRU index (OrderedDict end = hottest), reverse
        # map, staged fixed-lane traffic, and the last-hit clock the
        # deterministic coldness policy orders by (the ENGINE publishes
        # step_now each step).
        self._cfree = deque(range(1, self.compress_blocks + 1))
        self._cindex: "OrderedDict[tuple, int]" = OrderedDict()
        self._cslot_key: Dict[int, tuple] = {}
        self._pending_compress: List[Tuple[int, int]] = []  # (fp blk, slot)
        self._pending_promotes: List[Tuple[int, int]] = []  # (fp blk, slot)
        self._promote_slots: Set[int] = set()
        # direct-read plumbing: a compressed hit is served IN PLACE —
        # the block table carries the bias-encoded slot (-(slot+1)) and
        # the ragged step dequantizes it inside the kernel — instead of
        # claiming an fp block and staging a promote. promote_hits is
        # the opt-in warm-up threshold: 0 never promotes, 1 restores the
        # always-promote PR-19 behavior, N>1 promotes a key once it has
        # been hit N times (hot prefixes graduate back to fp reads).
        self.promote_hits = int(promote_hits)
        self._cslot_refs: Dict[int, int] = {}     # slot -> live direct readers
        self._chits: Dict[tuple, int] = {}        # key -> compressed-hit count
        self._last_hit: Dict[int, int] = {}           # block -> step
        self.step_now = 0
        self.compressed_total = 0         # blocks quantized in-device
        self.promoted_total = 0           # compressed blocks re-inflated
        self.compress_spills = 0          # cslot evictions (-> host/gone)
        self.compress_hit_tokens = 0      # prompt tokens served int8
        self.direct_reads = 0             # int8 blocks read in place
        self.direct_read_tokens = 0       # prompt tokens they covered
        # block 0 reserved for padded/dummy rows — never handed out
        self._free = deque(range(1, num_blocks))
        self._tables: Dict[int, List[int]] = {}
        self._lens: Dict[int, int] = {}
        # token ids backing each reserved position (the content identity
        # the prefix index is keyed on)
        self._tokens: Dict[int, List[int]] = {}
        # prefix length per sequence whose KV is actually IN the pool —
        # alloc reserves blocks for the whole prompt up front, but their
        # content arrives chunk by chunk; only committed-full blocks are
        # shareable (a hit must never read a block whose scatter is
        # still queued behind it in the schedule)
        self._committed: Dict[int, int] = {}
        self._refs: Dict[int, int] = {}               # block -> refcount
        # full-prefix token tuple -> block holding that prefix's last block
        self._index: Dict[tuple, int] = {}
        self._key_of: Dict[int, tuple] = {}           # block -> index key
        self._pending_copies: List[Tuple[int, int]] = []   # (src, dst)
        # optional host-RAM second tier (engine/kvtier.py): blocks the
        # pool is about to destroy are copied out, and alloc_sequence
        # walks it past the device index. Revivals stage (block, layers)
        # loads here; the engine flushes them into the device pools
        # (drain_host_loads) BEFORE any step reads or COW-copies them.
        self.host_tier = host_tier
        self._pending_host_loads: List[Tuple[int, list]] = []
        self.tier_revivals = 0            # host-tier blocks revived
        self.tier_hit_tokens = 0          # prompt tokens covered by them
        # cumulative stats (serve_event / bench verdicts)
        self.hit_tokens = 0
        self.prompt_tokens = 0
        self.cow_copies = 0
        self.cached_free_evictions = 0    # stale prefix entries recycled
        self.cached_free_revivals = 0     # freed blocks re-hit from the index
        # event-driven counters into the metrics registry
        # (OBSERVABILITY.md); gauges (occupancy/hit_rate) are sampled
        # per step by the engine — nothing here runs per token
        reg = registry if registry is not None else default_registry()
        self._c_cow = reg.counter(
            "ptpu_kv_cow_copies_total", "Copy-on-write block copies")
        self._c_evict = reg.counter(
            "ptpu_kv_cached_free_evictions_total",
            "Cached-free prefix entries evicted on block reuse")
        self._c_revive = reg.counter(
            "ptpu_kv_cached_free_revivals_total",
            "Freed blocks revived from the prefix index")
        self._c_prompt_toks = reg.counter(
            "ptpu_kv_prompt_tokens_total", "Prompt tokens admitted")
        self._c_hit_toks = reg.counter(
            "ptpu_kv_hit_tokens_total",
            "Prompt tokens served from the prefix cache")
        self._c_compress = reg.counter(
            "ptpu_kv_compress_total",
            "Cold prefix blocks quantized into the device int8 pool")
        self._c_promote = reg.counter(
            "ptpu_kv_promote_total",
            "Compressed blocks dequantized back into fp on a prefix hit")
        self._c_direct_reads = reg.counter(
            "ptpu_kv_direct_int8_reads_total",
            "Int8-resident blocks read in place by the ragged step")
        self._c_direct_toks = reg.counter(
            "ptpu_kv_direct_int8_tokens_total",
            "Prompt tokens served by direct int8 reads")

    # -- capacity ---------------------------------------------------------
    def pool_shape(self, tp_size: Optional[int] = None) -> Tuple[int, ...]:
        """PER-CHIP shape of one k (or v) pool under `tp_size`-way
        tensor parallelism (defaults to this cache's own tp_size): the
        kv-head dim divides by tp, everything else replicates. tp=1 is
        the global shape. Sizing math (engine HBM planning,
        tools/paged_roofline.py --tp-size) goes through here so the
        divisibility contract lives in ONE place."""
        tp = self.tp_size if tp_size is None else tp_size
        if tp < 1 or self.num_kv_heads % tp != 0:
            raise ValueError(
                f"num_kv_heads={self.num_kv_heads} not divisible by "
                f"tp_size={tp}")
        return (self.num_blocks, self.block_size,
                self.num_kv_heads // tp, self.head_dim)

    def per_chip_pool_bytes(self) -> int:
        """Measured HBM bytes ONE chip holds across every layer's k+v
        pool — read off the arrays' addressable shards, not computed,
        so the serve_bench tp gate checks what XLA actually allocated.
        Falls back to the full array size for unsharded pools."""
        total = 0
        for kp, vp in self.pools:
            for arr in (kp, vp):
                shards = getattr(arr, "addressable_shards", None)
                if shards:
                    total += max(s.data.nbytes for s in shards)
                else:
                    total += arr.nbytes
        return total

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """DISTINCT allocated blocks — sharing shows up as lower usage."""
        return (self.num_blocks - 1) - len(self._free)

    @property
    def shared_blocks(self) -> int:
        return sum(1 for r in self._refs.values() if r > 1)

    @property
    def total_refs(self) -> int:
        return sum(self._refs.values())

    def ref_count(self, block: int) -> int:
        return self._refs.get(block, 0)

    def occupancy(self) -> float:
        """Fraction of allocatable blocks in use (serve_event metric)."""
        return self.used_blocks / max(1, self.num_blocks - 1)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def _pop_free(self) -> int:
        """Take a block for FRESH content, lazily evicting any stale
        cached-free index entry it still carries (freed blocks keep
        their prefix KV reusable until the pool actually needs them —
        free_sequence appends to the RIGHT and this pops from the LEFT,
        so the longest-freed cached content is evicted first). With a
        host tier attached the content is demoted before the entry
        dies — eviction becomes a tier transition, not a loss."""
        block = self._free.popleft()
        key = self._key_of.pop(block, None)
        if key is not None and self._index.get(key) == block:
            self._demote_block(block, key, "evict")
            del self._index[key]
            self.cached_free_evictions += 1
            self._c_evict.inc()
        self._last_hit.pop(block, None)
        return block

    def _demote_block(self, block: int, key: tuple, reason: str) -> bool:
        """Ship one committed block's KV one rung down the demotion
        ladder — device-fp -> device-int8 -> host tier -> gone — under
        its content key. The int8 rung stages a fixed-lane quantize the
        engine flushes before anything overwrites the src block (the
        payload never leaves the device); the host rung is a device_get
        into the tier. reason="finish" skips the int8 rung: finish
        demotion feeds the fleet KV-transfer plane (serve/kvxfer.py
        GET /kvblocks), which serves from the HOST tier. A no-op when a
        lower rung already holds the key — that copy is the truth (the
        key IS the content, so it can never be stale), and re-encoding
        a revived-but-unflushed block would read back garbage."""
        if self._compress_on and reason != "finish":
            if key in self._cindex:
                return False          # already resident one rung down
            slot = self._take_cslot()
            if slot is not None:
                self._stage_compress(block, key, slot)
                return True
        if self.host_tier is None or self.host_tier.contains(key):
            return False
        layers = [(np.asarray(kp[block]), np.asarray(vp[block]))
                  for kp, vp in self.pools]
        return self.host_tier.put(key, layers, reason=reason)

    # -- in-device compressed tier ----------------------------------------
    def _stage_compress(self, block: int, key: tuple, slot: int) -> None:
        """Queue one fp block's quantize into int8 slot `slot`. The
        payload is READ at flush time, which is safe against every
        same-plan writer: promotions, host loads, and COW copies all
        flush after compressions, and prefill/decode scatters land in
        the step after that."""
        self._pending_compress.append((block, slot))
        self._cindex[key] = slot           # inserted hottest (end)
        self._cslot_key[slot] = key
        self.compressed_total += 1
        self._c_compress.inc()

    def _take_cslot(self) -> Optional[int]:
        """A free int8 slot — or the coldest evictable compressed
        entry's slot, after spilling that entry one rung further down.
        Slots with in-flight lane traffic are not evictable: a
        pending-compress dst holds no payload yet (spilling it would
        read scratch garbage), a pending-promote src is about to be
        read by the flush, and a slot with live direct readers
        (_cslot_refs) is part of a running sequence's block table.
        Returns None when nothing can move; the caller falls through
        to the host rung."""
        if self._cfree:
            return self._cfree.popleft()
        busy = {s for _, s in self._pending_compress}
        busy |= self._promote_slots
        busy |= set(self._cslot_refs)
        for key, slot in self._cindex.items():     # coldest first
            if slot in busy:
                continue
            self._spill_cslot(key, slot)
            del self._cindex[key]
            del self._cslot_key[slot]
            self._chits.pop(key, None)   # warm-up clock dies with the entry
            return slot
        return None

    def _spill_cslot(self, key: tuple, slot: int) -> None:
        """Demote-to-host FAST PATH for an evicted compressed entry:
        the int8 payload + scales ship straight into the host tier —
        one quant step total, never a dequant->requant round trip. An
        int8-mode tier stores the device blobs verbatim (revival
        dequantizes with the original scales); an fp-mode tier stores
        the exact dequantization, which adds no second quant step."""
        self.compress_spills += 1
        if self.host_tier is None or self.host_tier.contains(key):
            return
        self.host_tier.put_device_int8(key, self._slot_qlayers(slot),
                                       self.dtype, reason="evict")

    def _slot_qlayers(self, slot: int) -> list:
        """One int8 slot's per-layer (kq, kscale, vq, vscale) payload —
        the device_int8 wire/tier encoding (kvtier.put_device_int8)."""
        qlayers = []
        for li, (kq, vq) in enumerate(self.qpools):
            ks, vs = self.qscales[li]
            qlayers.append((np.asarray(kq[slot]), float(ks[slot]),
                            np.asarray(vq[slot]), float(vs[slot])))
        return qlayers

    def compress_cold(self, idle_steps: int = 4,
                      max_blocks: Optional[int] = None) -> int:
        """Proactive cold sweep (engine-driven, once per step):
        quantize the coldest committed prefix blocks — cached-free AND
        refcount-shared — into FREE int8 slots before pool pressure
        would evict them. Coldness is deterministic LRU by last-hit
        step; a block must have sat untouched >= `idle_steps`. The fp
        copy and its index entry STAY, so fp hits remain byte-exact and
        compressing a block that is still referenced is safe (committed
        full blocks are content-immutable: the key IS the content).
        The proactive path only fills free slots — it never spills a
        warmer compressed entry to make room; forced demotions do that.
        Returns blocks staged."""
        if not self._compress_on or not self._cfree:
            return 0
        # blocks whose device contents are not real yet — a staged
        # host-load dst (DMA flushes AFTER compressions) or a staged
        # promote dst — must never feed the quantize lanes this step
        inflight = {b for b, _ in self._pending_host_loads}
        inflight |= {b for b, _ in self._pending_promotes}
        cands = sorted(
            (self._last_hit.get(b, 0), b)
            for b, key in self._key_of.items()
            if key not in self._cindex and b not in inflight
            and self.step_now - self._last_hit.get(b, 0) >= idle_steps)
        staged = 0
        for _, b in cands:
            if not self._cfree or (max_blocks is not None
                                   and staged >= max_blocks):
                break
            self._stage_compress(b, self._key_of[b], self._cfree.popleft())
            staged += 1
        return staged

    def demote_sequence(self, seq_id: int, reason: str = "preempt") -> int:
        """Copy a live sequence's committed full blocks out to the host
        tier — the preemption path: the scheduler calls this right
        before free_sequence so re-admission revives the context by DMA
        instead of re-prefilling it (quadratic recompute becomes a
        linear copy). A prefill-phase engine also calls it at request
        FINISH (reason="finish") so a decode replica can pull the
        finished prefix over the fleet KV-transfer plane
        (serve/kvxfer.py). Returns blocks demoted. With the in-device
        compressed tier enabled this works without a host tier too —
        preempted blocks land one rung down in int8 (the cheapest
        revival) instead of being recompute-only."""
        if (self.host_tier is None and not self._compress_on) \
                or not self.enable_prefix_cache:
            return 0
        table = self._tables.get(seq_id)
        if table is None:
            return 0
        self._register_full_blocks(seq_id)
        toks = self._tokens[seq_id]
        bs = self.block_size
        count = 0
        for bi in range(self._committed.get(seq_id, 0) // bs):
            b = table[bi]
            if b < 0:
                # bias-encoded direct-read entry: the content already
                # lives in the int8 tier, so preempt-demotion is a
                # no-op. Finish-demotion feeds the fleet transfer plane
                # from the HOST tier — ship the int8 payload down the
                # spill fast path (one quant step total, no fp detour).
                slot = -b - 1
                key = (self._cslot_key.get(slot)
                       or tuple(toks[:(bi + 1) * bs]))
                if reason == "finish" and self.host_tier is not None \
                        and not self.host_tier.contains(key):
                    if self.host_tier.put_device_int8(
                            key, self._slot_qlayers(slot), self.dtype,
                            reason=reason):
                        count += 1
                continue
            key = self._key_of.get(b) or tuple(toks[:(bi + 1) * bs])
            if self._demote_block(b, key, reason):
                count += 1
        return count

    def _match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest run of committed full blocks matching `tokens`' head
        (read-only: no refs taken)."""
        if not self.enable_prefix_cache:
            return []
        matched: List[int] = []
        bs = self.block_size
        for end in range(bs, len(tokens) + 1, bs):
            block = self._index.get(tuple(tokens[:end]))
            if block is None:
                break
            matched.append(block)
        return matched

    def can_allocate(self, tokens) -> bool:
        """Admission check. `tokens` may be a token list (prefix-aware:
        matched blocks cost nothing beyond their own revival) or a bare
        count (conservative)."""
        if isinstance(tokens, int):
            return self.blocks_for(tokens) <= len(self._free)
        matched = self._match_prefix(tokens)
        need = self.blocks_for(len(tokens)) - len(matched)
        # cached-free matches leave the free list too (revival)
        revive = sum(1 for b in matched if b not in self._refs)
        return need + revive <= len(self._free)

    # -- sequence lifecycle ----------------------------------------------
    def alloc_sequence(self, seq_id: int, tokens: Sequence[int],
                       count_stats: bool = True) -> int:
        """Reserve blocks for a sequence's prompt, reusing committed
        prefix blocks from the index. Returns the number of CACHED
        tokens (KV already in the pool — the engine prefills only the
        suffix). A full-prompt hit is capped at n-1 so the last token
        always recomputes (its logits seed sampling); that write lands
        inside a shared block and COWs it. Raises CacheExhausted
        (allocating nothing) when the free list is short — the
        scheduler turns that into deferred admission or preemption.
        `count_stats=False` leaves hit_tokens/prompt_tokens untouched:
        a preemption re-admission re-hits its own just-committed blocks
        and would otherwise inflate hit_rate."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        n = len(tokens)
        bs = self.block_size
        matched = self._match_prefix(tokens)
        # walk PAST the device-fp match into the compressed tier. Each
        # hit is served IN PLACE by default: the table entry carries the
        # bias-encoded slot (-(slot+1)) and the ragged step dequantizes
        # the block inside the kernel — no fp claim, no promote lanes.
        # A hit claims a fresh fp block + staged dequantize promotion
        # only when the warm-up threshold says so (promote_hits; see
        # __init__) or when the hit is the prompt's FINAL block: the
        # full-prompt cap recomputes token n-1, and its write must land
        # in a writable fp block, never an int8 slot.
        chits: List[Tuple[tuple, int, bool]] = []   # (key, slot, promote?)
        if self._compress_on:
            for end in range((len(matched) + 1) * bs, n + 1, bs):
                key = tuple(tokens[:end])
                slot = self._cindex.get(key)
                if slot is None:
                    break
                hits = self._chits.get(key, 0) + 1
                chits.append((key, slot,
                              end >= n or 0 < self.promote_hits <= hits))
        # ... and past THAT into the host tier: every hit is fetched
        # now (the payload is pinned here — a later demotion's LRU
        # eviction between admission and flush can't revoke it)
        host_loads: List[Tuple[tuple, list]] = []
        if self.host_tier is not None and self.enable_prefix_cache:
            for end in range((len(matched) + len(chits) + 1) * bs,
                             n + 1, bs):
                layers = self.host_tier.get(tuple(tokens[:end]))
                if layers is None:
                    break
                host_loads.append((tuple(tokens[:end]), layers))
        n_direct = sum(1 for _, _, p in chits if not p)
        need = self.blocks_for(n) - len(matched) - n_direct
        revive = [b for b in matched if b not in self._refs]
        if need + len(revive) > len(self._free):
            raise CacheExhausted(
                f"need {need + len(revive)} blocks, {len(self._free)} free")
        for b in matched:
            if b in self._refs:
                self._refs[b] += 1
            else:                       # cached-free hit: revive the block
                self._free.remove(b)
                self._refs[b] = 1
                self.cached_free_revivals += 1
                self._c_revive.inc()
            self._last_hit[b] = self.step_now
        # Pin every compressed hit's slot FIRST: the _pop_free calls
        # below can themselves demote dying cached-free entries into
        # the int8 pool, and a full pool would otherwise evict (spill)
        # the very slots this table is about to read or promote from.
        mid_blocks: List[int] = []      # compressed hits, in table order
        n_promoted = 0
        if chits:
            self._promote_slots.update(s for _, s, p in chits if p)
            for _, s, p in chits:
                if not p:
                    self._cslot_refs[s] = self._cslot_refs.get(s, 0) + 1
            for key, slot, p in chits:
                self._chits[key] = self._chits.get(key, 0) + 1
                self._cindex.move_to_end(key)        # LRU touch: hottest
                if not p:
                    mid_blocks.append(-(slot + 1))
                    self.direct_reads += 1
                    self._c_direct_reads.inc()
                    continue
                b = self._pop_free()
                self._refs[b] = 1
                mid_blocks.append(b)
                n_promoted += 1
                self._pending_promotes.append((b, slot))
                self._last_hit[b] = self.step_now
                if key not in self._index and b not in self._key_of:
                    self._index[key] = b
                    self._key_of[b] = key
                self.promoted_total += 1
                self._c_promote.inc()
        # host-tier hits claim fresh device blocks and stage their DMA;
        # the key registers first-wins so later prompts can share the
        # block as soon as the engine flushes the load
        host_blocks: List[int] = []
        for key, layers in host_loads:
            b = self._pop_free()
            self._refs[b] = 1
            host_blocks.append(b)
            self._pending_host_loads.append((b, layers))
            self._last_hit[b] = self.step_now
            if key not in self._index and b not in self._key_of:
                self._index[key] = b
                self._key_of[b] = key
        fresh = [self._pop_free()
                 for _ in range(need - n_promoted - len(host_blocks))]
        for b in fresh:
            self._refs[b] = 1
            self._last_hit[b] = self.step_now
        self._tables[seq_id] = matched + mid_blocks + host_blocks + fresh
        self._lens[seq_id] = n
        self._tokens[seq_id] = list(tokens)
        cached = min((len(matched) + len(chits) + len(host_blocks))
                     * bs, n - 1)
        self._committed[seq_id] = cached
        if chits:
            self.compress_hit_tokens += max(
                0, min((len(matched) + len(chits)) * bs, cached)
                - len(matched) * bs)
        if n_direct:
            self.direct_read_tokens += n_direct * bs
            self._c_direct_toks.inc(n_direct * bs)
        if host_blocks:
            tier_toks = max(0, cached - (len(matched) + len(chits))
                            * bs)
            self.tier_revivals += len(host_blocks)
            self.tier_hit_tokens += tier_toks
            self.host_tier.note_revived(len(host_blocks), tier_toks)
        if count_stats:
            self.hit_tokens += cached
            self.prompt_tokens += n
            self._c_hit_toks.inc(cached)
            self._c_prompt_toks.inc(n)
        return cached

    def ensure_writable(self, seq_id: int, start: int, end: int) -> None:
        """Copy-on-write pass before the engine scatters positions
        [start, end): every touched block with refcount > 1 is swapped
        for a fresh private block and an on-device (src, dst) block
        copy is queued (drain_copies) so already-valid positions in the
        block survive. Raises CacheExhausted when a COW needs a block
        and the free list is empty."""
        table = self._tables[seq_id]
        bs = self.block_size
        for bi in range(start // bs, (max(end, start + 1) - 1) // bs + 1):
            old = table[bi]
            if old < 0:
                # unreachable by construction: writes land at positions
                # >= cached, and alloc_sequence force-promotes the one
                # compressed hit a capped full-prompt write can touch
                # (the final block). Fail loudly rather than corrupt
                # the shared int8 slot.
                raise RuntimeError(
                    f"copy-on-write reached int8-resident entry {old} "
                    f"(seq {seq_id}, block index {bi})")
            if self._refs[old] <= 1:
                continue
            if not self._free:
                raise CacheExhausted("no free block for copy-on-write")
            new = self._pop_free()
            self._refs[old] -= 1
            self._refs[new] = 1
            table[bi] = new
            self._pending_copies.append((old, new))
            self.cow_copies += 1
            self._c_cow.inc()

    def drain_copies(self) -> List[Tuple[int, int]]:
        """Queued COW block copies; the engine MUST replay them on the
        device pools (src block -> dst block, every layer) before the
        next prefill/decode call reads or writes the dst blocks."""
        out, self._pending_copies = self._pending_copies, []
        return out

    def drain_host_loads(self) -> List[Tuple[int, list]]:
        """Staged host-tier revivals: (block, per-layer [(k, v)] host
        arrays). The engine MUST write them into the device pools
        BEFORE draining COW copies — a just-revived block can be the
        src of a same-plan copy-on-write."""
        out, self._pending_host_loads = self._pending_host_loads, []
        return out

    def drain_compress(self) -> List[Tuple[int, int]]:
        """Staged (fp block, int8 slot) quantizations. The engine MUST
        flush these FIRST — before promotions, host loads, and COW
        copies — so the quantize lanes read every src block's content
        ahead of any same-plan writer reusing it."""
        out, self._pending_compress = self._pending_compress, []
        return out

    def drain_promotes(self) -> List[Tuple[int, int]]:
        """Staged (fp block, int8 slot) dequantize promotions, flushed
        AFTER compressions (a promo may read a slot the same plan just
        filled) and BEFORE host loads / COW copies / the step read."""
        out, self._pending_promotes = self._pending_promotes, []
        self._promote_slots = set()
        return out

    def commit_prefill(self, seq_id: int, upto: int) -> None:
        """Mark positions [0, upto) as present in the pool (a prefill
        chunk just scattered them) and register every newly-full block
        in the prefix index so later prompts can share it."""
        self._committed[seq_id] = max(self._committed.get(seq_id, 0), upto)
        self._register_full_blocks(seq_id)

    def committed_len(self, seq_id: int) -> int:
        return self._committed.get(seq_id, 0)

    def _register_full_blocks(self, seq_id: int) -> None:
        if not self.enable_prefix_cache:
            return
        bs = self.block_size
        table = self._tables[seq_id]
        toks = self._tokens[seq_id]
        for bi in range(self._committed[seq_id] // bs):
            block = table[bi]
            if block < 0:
                continue    # int8-resident: indexed by _cindex, not here
            if block in self._key_of:
                continue                    # already indexed (maybe shared)
            key = tuple(toks[:(bi + 1) * bs])
            if key in self._index:
                continue                    # duplicate content: first wins
            self._index[key] = block
            self._key_of[block] = key

    def append_token(self, seq_id: int) -> int:
        """Reserve the slot for this sequence's next token (allocating a
        fresh block at a block boundary, COWing a shared tail block);
        returns the FLAT pool slot (block_id * block_size + offset) the
        engine passes to the decode step. Does NOT advance the length —
        call advance() after the step actually writes."""
        return self.reserve_slots(seq_id, 1)[0]

    def reserve_slots(self, seq_id: int, count: int) -> List[int]:
        """Reserve the next `count` token slots in one ALL-OR-NOTHING
        transaction (the speculative-decode path: the base token plus k
        draft tokens land in one multi-token StepRow, so either the
        whole window gets slots or the scheduler falls back to a plain
        1-token decode). The bill is pre-checked — COW copies for
        shared blocks the window touches plus fresh blocks past the
        table's end — and CacheExhausted raises BEFORE any refcount or
        table mutation, so a failed reservation leaves nothing to roll
        back. Returns the flat pool slots in window order. Like
        append_token, the length does not advance: the engine calls
        advance() only for positions verification actually accepted,
        and un-advanced slots are simply re-reserved (and overwritten)
        by the next step — that IS the speculative rollback."""
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        bs = self.block_size
        end = pos + count
        in_table_end = min(end, len(table) * bs)
        cow_need = 0
        if in_table_end > pos:
            cow_need = sum(
                1 for bi in range(pos // bs, (in_table_end - 1) // bs + 1)
                if self._refs[table[bi]] > 1)
        new_need = max(0, self.blocks_for(end) - len(table))
        if cow_need + new_need > len(self._free):
            raise CacheExhausted(
                f"need {cow_need + new_need} blocks ({cow_need} COW + "
                f"{new_need} fresh), {len(self._free)} free")
        if in_table_end > pos:
            self.ensure_writable(seq_id, pos, in_table_end)
        for _ in range(new_need):
            block = self._pop_free()
            self._refs[block] = 1
            self._last_hit[block] = self.step_now
            table.append(block)
        return [table[(pos + j) // bs] * bs + (pos + j) % bs
                for j in range(count)]

    def fork_sequence(self, src_id: int, dst_id: int) -> None:
        """Clone `src_id`'s sequence state into `dst_id` sharing EVERY
        block (refcount bump — zero new blocks, zero device copies):
        the parallel-sampling / best-of-n primitive. A finished prefill
        forks into n candidates that all read the same prompt KV; the
        first time a fork WRITES (its own generated tokens, starting
        with the shared partially-filled tail block) the ordinary
        ensure_writable copy-on-write path peels it a private copy.
        free_sequence needs no special casing: a fork's exclusive
        blocks (refcount 1) return to the free list, shared prompt
        blocks just drop one reference."""
        if dst_id in self._tables:
            raise ValueError(f"sequence {dst_id} already allocated")
        table = self._tables[src_id]
        for b in table:
            if b < 0:       # shared direct-read slot: bump its pin too
                self._cslot_refs[-b - 1] += 1
            else:
                self._refs[b] += 1
        self._tables[dst_id] = list(table)
        self._lens[dst_id] = self._lens[src_id]
        self._tokens[dst_id] = list(self._tokens[src_id])
        self._committed[dst_id] = self._committed[src_id]

    def advance(self, seq_id: int, token: int) -> None:
        """The decode step wrote `token`'s k/v at the reserved slot:
        extend the sequence and index the tail block if it just
        filled (generated continuations are shareable too)."""
        self._tokens[seq_id].append(token)
        self._lens[seq_id] += 1
        self._committed[seq_id] = self._lens[seq_id]
        if self._lens[seq_id] % self.block_size == 0:
            self._register_full_blocks(seq_id)

    def free_sequence(self, seq_id: int) -> int:
        """Drop this sequence's references; blocks whose refcount hits
        zero return to the free list but KEEP their prefix-index entry
        (cached-free): a later prompt with the same prefix revives them
        instead of recomputing, and `_pop_free` lazily evicts the entry
        only when the pool reuses the block for fresh content. Queued
        COW copies targeting a freed block are cancelled — the pool may
        hand the block straight back out, and a stale copy flushing
        later would clobber the new owner's KV. Returns how many blocks
        went back to the free list (shared ones live on)."""
        blocks = self._tables.pop(seq_id, [])
        self._lens.pop(seq_id, None)
        self._tokens.pop(seq_id, None)
        self._committed.pop(seq_id, None)
        freed = 0
        freed_set = set()
        for b in blocks:
            if b < 0:
                # direct-read entry: unpin the int8 slot. The payload
                # stays resident in _cindex (it never left), so there
                # is no cached-free bookkeeping — the slot just becomes
                # spillable again once its last reader drops.
                slot = -b - 1
                left = self._cslot_refs.get(slot, 0) - 1
                if left > 0:
                    self._cslot_refs[slot] = left
                else:
                    self._cslot_refs.pop(slot, None)
                continue
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
                # the block was in live use until this very step — its
                # cached-free coldness clock starts NOW
                self._last_hit[b] = self.step_now
                freed += 1
                freed_set.add(b)
        if freed_set and self._pending_copies:
            self._pending_copies = [
                (s, d) for s, d in self._pending_copies
                if d not in freed_set]
        if freed_set and self._pending_host_loads:
            # cancel-mid-revival: the request died before its staged
            # host loads flushed. The freed blocks were index-registered
            # for content that never arrived — deregister them (the
            # host tier still holds the data; a re-request revives it
            # onto new blocks).
            stale = [b for b, _ in self._pending_host_loads
                     if b in freed_set]
            if stale:
                self._pending_host_loads = [
                    (b, la) for b, la in self._pending_host_loads
                    if b not in freed_set]
                for b in stale:
                    key = self._key_of.pop(b, None)
                    if key is not None and self._index.get(key) == b:
                        del self._index[key]
        if freed_set and self._pending_promotes:
            # cancel-mid-promotion (mirror of the host-load cancel):
            # a freed dst block may be re-issued immediately, and a
            # stale dequantize flushing later would clobber the new
            # owner's KV. The compressed entry still holds the payload;
            # a re-request promotes it onto new blocks.
            stale_p = [b for b, _ in self._pending_promotes
                       if b in freed_set]
            if stale_p:
                self._pending_promotes = [
                    (b, s) for b, s in self._pending_promotes
                    if b not in freed_set]
                self._promote_slots = {s for _, s in self._pending_promotes}
                for b in stale_p:
                    key = self._key_of.pop(b, None)
                    if key is not None and self._index.get(key) == b:
                        del self._index[key]
        return freed

    # -- views for the jitted step ---------------------------------------
    def seq_len(self, seq_id: int) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def slot_of(self, seq_id: int, pos: int) -> int:
        """Flat pool slot of an ALREADY-RESERVED position."""
        table = self._tables[seq_id]
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size

    def padded_table(self, seq_id: int, max_blocks: int) -> List[int]:
        """Block table right-padded with scratch block 0 to the fixed
        width the compiled decode step expects."""
        table = self._tables[seq_id]
        if len(table) > max_blocks:
            raise ValueError(f"sequence {seq_id} spans {len(table)} blocks "
                             f"> max {max_blocks}")
        return table + [0] * (max_blocks - len(table))

    def prefix_keys(self, limit: int = 512) -> List[tuple]:
        """Most recently indexed prefix keys (device tier) — the
        engine's half of the fleet prefix directory advertisement.
        Engine-loop thread only (reads the unlocked index)."""
        keys = list(self._index.keys())
        return keys[-limit:] if limit and len(keys) > limit else keys

    def compressed_keys(self, limit: int = 512) -> List[tuple]:
        """Most recently touched compressed-tier keys (hottest last) —
        advertised as the `device_int8` rung of the fleet prefix
        directory, between device-fp and host. Engine-loop thread
        only."""
        keys = list(self._cindex.keys())
        return keys[-limit:] if limit and len(keys) > limit else keys

    @property
    def compress_enabled(self) -> bool:
        """Whether the in-device int8 tier is active (budget > 0 and
        prefix caching on) — the scheduler's victim costing and the
        engine's directory advertisement branch on this."""
        return self._compress_on

    @property
    def direct_read_enabled(self) -> bool:
        """Whether compressed hits are served in place by the mixed
        ragged step (promote_hits != 1; 1 restores always-promote).
        The scheduler's victim costing and the frontend's /kvprefixes
        capability field branch on this."""
        return self._compress_on and self.promote_hits != 1

    @property
    def compressed_resident(self) -> int:
        return len(self._cindex)

    @property
    def compress_free_slots(self) -> int:
        """Unused int8 slots — the scheduler's victim costing caps the
        cheap-rung credit by this (a forced demotion beyond it spills
        warmer entries or, with no host tier, drops content)."""
        return len(self._cfree)

    def effective_pool_bytes(self) -> int:
        """fp-equivalent bytes of UNIQUE KV the device currently holds:
        the fp pool plus compressed entries whose content lives ONLY in
        the int8 tier. Proactively compressed blocks keep their fp copy
        resident (compress_cold), so counting every _cindex entry would
        double-count content present in both tiers; an entry counts
        only once its fp index entry is gone (the block was evicted or
        was never fp-resident). Reaches (num_blocks-1 + compress_blocks)
        x block-bytes when the int8 pool is full of fp-evicted content
        — the ~2x-effective-pool headline, sampled into
        ptpu_kv_pool_effective_bytes."""
        blk = (2 * self.block_size * self.num_kv_heads * self.head_dim
               * np.dtype(self.dtype).itemsize * len(self.pools))
        uniq = sum(1 for k in self._cindex if k not in self._index)
        return (self.num_blocks - 1 + uniq) * blk

    # -- observability ----------------------------------------------------
    def hit_rate(self) -> float:
        """Fraction of all prompt tokens served from the prefix cache."""
        return self.hit_tokens / max(1, self.prompt_tokens)

    def stats(self) -> Dict[str, float]:
        out = {
            "hit_tokens": self.hit_tokens,
            "prompt_tokens": self.prompt_tokens,
            "hit_rate": round(self.hit_rate(), 4),
            "cow_copies": self.cow_copies,
            "cached_free_evictions": self.cached_free_evictions,
            "cached_free_revivals": self.cached_free_revivals,
            "shared_blocks": self.shared_blocks,
            "used_blocks": self.used_blocks,
            "occupancy": round(self.occupancy(), 4),
        }
        if self._compress_on:
            out["compressed_blocks"] = len(self._cindex)
            out["compress_total"] = self.compressed_total
            out["promote_total"] = self.promoted_total
            out["compress_spills"] = self.compress_spills
            out["compress_hit_tokens"] = self.compress_hit_tokens
            out["direct_int8_reads"] = self.direct_reads
            out["direct_int8_tokens"] = self.direct_read_tokens
        if self.host_tier is not None:
            out["tier_revivals"] = self.tier_revivals
            out["tier_hit_tokens"] = self.tier_hit_tokens
            out.update(self.host_tier.stats())
        return out

    def reset_stats(self) -> None:
        self.hit_tokens = self.prompt_tokens = self.cow_copies = 0
        self.cached_free_evictions = self.cached_free_revivals = 0
        self.tier_revivals = self.tier_hit_tokens = 0
        self.compressed_total = self.promoted_total = 0
        self.compress_spills = self.compress_hit_tokens = 0
        self.direct_reads = self.direct_read_tokens = 0

    def assert_quiesced(self) -> None:
        """Leak check: with no live sequences every refcount must be
        gone and the free list full. Index entries may remain, but only
        for cached-free blocks (their content stays reusable by
        design); an indexed block NOT on the free list is a leak."""
        if self._tables:
            raise RuntimeError(f"live sequences: {list(self._tables)}")
        if self._refs:
            raise RuntimeError(f"leaked refcounts: {self._refs}")
        if self._pending_host_loads:
            raise RuntimeError(
                f"{len(self._pending_host_loads)} host-tier loads never "
                "flushed")
        if self._pending_compress:
            raise RuntimeError(
                f"{len(self._pending_compress)} compress lanes never "
                "flushed")
        if self._pending_promotes:
            raise RuntimeError(
                f"{len(self._pending_promotes)} promote lanes never "
                "flushed")
        if self._cslot_refs:
            raise RuntimeError(
                f"leaked direct-read slot pins: {self._cslot_refs}")
        if self._compress_on and \
                len(self._cfree) + len(self._cindex) != self.compress_blocks:
            raise RuntimeError(
                f"compressed-slot leak: {len(self._cfree)} free + "
                f"{len(self._cindex)} resident != {self.compress_blocks}")
        if len(self._free) != self.num_blocks - 1:
            raise RuntimeError(
                f"free list {len(self._free)} != {self.num_blocks - 1}")
        free = set(self._free)
        leaked = [b for b in self._key_of if b not in free]
        if leaked:
            raise RuntimeError(
                f"indexed blocks not on the free list: {leaked}")

"""PagedKVCache: block-pool KV storage for online inference.

The HBM side of continuous batching (ENGINE.md): instead of one dense
[B, Tmax, Hkv, hd] cache per batch slot — which reserves worst-case
HBM for every request and welds batch membership to allocation — KV
state lives in ONE pool of fixed-size token blocks per layer
([num_blocks, block_size, Hkv, hd] for k and for v). A sequence owns a
BLOCK TABLE (ordered list of pool block ids); growing a sequence
appends a block from the free list, finishing/evicting one returns its
blocks in O(blocks). Fragmentation is bounded at block_size-1 wasted
slots per sequence, and admission capacity is a pure free-list check.

Host/device split: this class is the HOST-side allocator + bookkeeping
(free list, per-sequence tables, lengths). The device-side pools are
jnp arrays held in `self.pools` and are updated FUNCTIONALLY — the
jitted prefill-scatter / decode step return new pool arrays and the
engine assigns them back. Nothing here traces into XLA; block tables
cross into jit as plain int32 operands.

Block 0 is reserved as a scratch block: padded batch rows (the engine
pads decode batches to a fixed size for one-compilation serving) write
their garbage k/v there, so a dummy row can never corrupt a live
sequence.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Tuple

import jax.numpy as jnp


class CacheExhausted(Exception):
    """No free blocks; the scheduler must evict (preempt) a sequence."""


class PagedKVCache:
    """Block-pool KV cache shared by all layers of one model.

    All layers allocate in lockstep (a token occupies the same slot in
    every layer's pool), so ONE free list / block table set serves the
    whole stack; `pools` holds per-layer (k_pool, v_pool) arrays.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.float32):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is scratch)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        shape = (num_blocks, block_size, num_kv_heads, head_dim)
        self.pools: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]
        # block 0 reserved for padded/dummy rows — never handed out
        self._free = deque(range(1, num_blocks))
        self._tables: Dict[int, List[int]] = {}
        self._lens: Dict[int, int] = {}

    # -- capacity ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def occupancy(self) -> float:
        """Fraction of allocatable blocks in use (serve_event metric)."""
        return self.used_blocks / max(1, self.num_blocks - 1)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def can_allocate(self, num_tokens: int) -> bool:
        return self.blocks_for(num_tokens) <= len(self._free)

    # -- sequence lifecycle ----------------------------------------------
    def alloc_sequence(self, seq_id: int, num_tokens: int) -> None:
        """Reserve blocks for a sequence's first num_tokens (prefill).
        Raises CacheExhausted (allocating nothing) when the free list is
        short — the scheduler turns that into deferred admission or
        preemption."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        need = self.blocks_for(num_tokens)
        if need > len(self._free):
            raise CacheExhausted(
                f"need {need} blocks, {len(self._free)} free")
        self._tables[seq_id] = [self._free.popleft() for _ in range(need)]
        self._lens[seq_id] = num_tokens

    def append_token(self, seq_id: int) -> int:
        """Reserve the slot for this sequence's next token (allocating a
        fresh block at a block boundary); returns the FLAT pool slot
        (block_id * block_size + offset) the engine passes to the decode
        step. Does NOT advance the length — call advance() after the
        step actually writes."""
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        if pos == len(table) * self.block_size:     # block boundary
            if not self._free:
                raise CacheExhausted("no free block for decode append")
            table.append(self._free.popleft())
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size

    def advance(self, seq_id: int) -> None:
        self._lens[seq_id] += 1

    def free_sequence(self, seq_id: int) -> int:
        """Return a finished/evicted sequence's blocks; returns how many."""
        blocks = self._tables.pop(seq_id, [])
        self._lens.pop(seq_id, None)
        self._free.extend(blocks)
        return len(blocks)

    # -- views for the jitted step ---------------------------------------
    def seq_len(self, seq_id: int) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def slot_of(self, seq_id: int, pos: int) -> int:
        """Flat pool slot of an ALREADY-RESERVED position."""
        table = self._tables[seq_id]
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size

    def padded_table(self, seq_id: int, max_blocks: int) -> List[int]:
        """Block table right-padded with scratch block 0 to the fixed
        width the compiled decode step expects."""
        table = self._tables[seq_id]
        if len(table) > max_blocks:
            raise ValueError(f"sequence {seq_id} spans {len(table)} blocks "
                             f"> max {max_blocks}")
        return table + [0] * (max_blocks - len(table))

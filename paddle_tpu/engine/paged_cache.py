"""PagedKVCache: refcounted block-pool KV storage with prefix sharing.

The HBM side of continuous batching (ENGINE.md): instead of one dense
[B, Tmax, Hkv, hd] cache per batch slot — which reserves worst-case
HBM for every request and welds batch membership to allocation — KV
state lives in ONE pool of fixed-size token blocks per layer
([num_blocks, block_size, Hkv, hd] for k and for v). A sequence owns a
BLOCK TABLE (ordered list of pool block ids); growing a sequence
appends a block from the free list, finishing/evicting one returns its
blocks in O(blocks). Fragmentation is bounded at block_size-1 wasted
slots per sequence, and admission capacity is a pure free-list check.

Prefix sharing (vLLM-style): blocks carry REFCOUNTS, and every FULL
block whose KV content is actually in the pool is registered in a
prefix index keyed by the exact token tuple of the sequence prefix it
ends (collision-free by construction — the key IS the content, not a
hash of it). `alloc_sequence` walks a new prompt block by block
through the index and reuses matching blocks instead of allocating:
a hit means those tokens' KV already exists, so the engine skips their
prefill compute AND their HBM. Because only committed-full blocks are
shareable, a shared block is write-immutable in the common case; the
one legal write into a shared block (a full-prompt hit is capped at
n-1 so the last token always recomputes for logits, landing mid-block)
triggers COPY-ON-WRITE: the writer gets a fresh private block and the
engine replays the old block's contents into it on device
(`drain_copies` -> the engine's compiled gather/scatter).

Freed blocks stay CACHED-FREE: when the last reference drops, the
block returns to the free list but keeps its prefix-index entry, so a
later request with the same prefix (the shared-system-prompt pattern)
revives it from the free list instead of recomputing — the KV is
still sitting in the pool untouched. The entry is evicted lazily, only
when `_pop_free` hands the block out for fresh content; frees append
to the right and pops take from the left, so the longest-freed cached
content is recycled first (FIFO ~ LRU here).

Host/device split: this class is the HOST-side allocator + bookkeeping
(free list, refcounts, per-sequence tables/lengths/tokens, prefix
index). The device-side pools are jnp arrays held in `self.pools` and
are updated FUNCTIONALLY — the jitted prefill-scatter / decode step /
COW block copy return new pool arrays and the engine assigns them
back. Nothing here traces into XLA; block tables cross into jit as
plain int32 operands.

Block 0 is reserved as a scratch block: padded batch rows (the engine
pads decode batches to a fixed size for one-compilation serving) write
their garbage k/v there, so a dummy row can never corrupt a live
sequence.
"""

from __future__ import annotations

from collections import deque
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry

if TYPE_CHECKING:
    from paddle_tpu.engine.kvtier import HostKVTier


class CacheExhausted(Exception):
    """No free blocks; the scheduler must evict (preempt) a sequence."""


class PagedKVCache:
    """Refcounted block-pool KV cache shared by all layers of one model.

    All layers allocate in lockstep (a token occupies the same slot in
    every layer's pool), so ONE free list / block table set serves the
    whole stack; `pools` holds per-layer (k_pool, v_pool) arrays.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.float32,
                 enable_prefix_cache: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 host_tier: Optional["HostKVTier"] = None,
                 tp_size: int = 1, mesh=None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is scratch)")
        if tp_size < 1:
            raise ValueError(f"tp_size {tp_size} < 1")
        if num_kv_heads % tp_size != 0:
            # fail at construction, not as a reshape crash mid-serve:
            # the pool shards over kv-heads, so every chip must own a
            # whole number of them (GQA groups stay device-local)
            raise ValueError(
                f"num_kv_heads={num_kv_heads} not divisible by "
                f"tp_size={tp_size}: the KV pool shards over kv-heads "
                "(pool_shape), so tp must divide them evenly")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.tp_size = tp_size
        self.enable_prefix_cache = enable_prefix_cache
        # pools are allocated at the GLOBAL shape; under tp the mesh
        # shards the kv-head dim so each chip HOLDS pool_shape() bytes
        shape = (num_blocks, block_size, num_kv_heads, head_dim)
        self.pools: List[Tuple[jnp.ndarray, jnp.ndarray]] = [
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]
        if mesh is not None and tp_size > 1:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            ns = NamedSharding(mesh, P(None, None, "tp", None))
            self.pools = [(jax.device_put(kp, ns), jax.device_put(vp, ns))
                          for kp, vp in self.pools]
        # block 0 reserved for padded/dummy rows — never handed out
        self._free = deque(range(1, num_blocks))
        self._tables: Dict[int, List[int]] = {}
        self._lens: Dict[int, int] = {}
        # token ids backing each reserved position (the content identity
        # the prefix index is keyed on)
        self._tokens: Dict[int, List[int]] = {}
        # prefix length per sequence whose KV is actually IN the pool —
        # alloc reserves blocks for the whole prompt up front, but their
        # content arrives chunk by chunk; only committed-full blocks are
        # shareable (a hit must never read a block whose scatter is
        # still queued behind it in the schedule)
        self._committed: Dict[int, int] = {}
        self._refs: Dict[int, int] = {}               # block -> refcount
        # full-prefix token tuple -> block holding that prefix's last block
        self._index: Dict[tuple, int] = {}
        self._key_of: Dict[int, tuple] = {}           # block -> index key
        self._pending_copies: List[Tuple[int, int]] = []   # (src, dst)
        # optional host-RAM second tier (engine/kvtier.py): blocks the
        # pool is about to destroy are copied out, and alloc_sequence
        # walks it past the device index. Revivals stage (block, layers)
        # loads here; the engine flushes them into the device pools
        # (drain_host_loads) BEFORE any step reads or COW-copies them.
        self.host_tier = host_tier
        self._pending_host_loads: List[Tuple[int, list]] = []
        self.tier_revivals = 0            # host-tier blocks revived
        self.tier_hit_tokens = 0          # prompt tokens covered by them
        # cumulative stats (serve_event / bench verdicts)
        self.hit_tokens = 0
        self.prompt_tokens = 0
        self.cow_copies = 0
        self.cached_free_evictions = 0    # stale prefix entries recycled
        self.cached_free_revivals = 0     # freed blocks re-hit from the index
        # event-driven counters into the metrics registry
        # (OBSERVABILITY.md); gauges (occupancy/hit_rate) are sampled
        # per step by the engine — nothing here runs per token
        reg = registry if registry is not None else default_registry()
        self._c_cow = reg.counter(
            "ptpu_kv_cow_copies_total", "Copy-on-write block copies")
        self._c_evict = reg.counter(
            "ptpu_kv_cached_free_evictions_total",
            "Cached-free prefix entries evicted on block reuse")
        self._c_revive = reg.counter(
            "ptpu_kv_cached_free_revivals_total",
            "Freed blocks revived from the prefix index")
        self._c_prompt_toks = reg.counter(
            "ptpu_kv_prompt_tokens_total", "Prompt tokens admitted")
        self._c_hit_toks = reg.counter(
            "ptpu_kv_hit_tokens_total",
            "Prompt tokens served from the prefix cache")

    # -- capacity ---------------------------------------------------------
    def pool_shape(self, tp_size: Optional[int] = None) -> Tuple[int, ...]:
        """PER-CHIP shape of one k (or v) pool under `tp_size`-way
        tensor parallelism (defaults to this cache's own tp_size): the
        kv-head dim divides by tp, everything else replicates. tp=1 is
        the global shape. Sizing math (engine HBM planning,
        tools/paged_roofline.py --tp-size) goes through here so the
        divisibility contract lives in ONE place."""
        tp = self.tp_size if tp_size is None else tp_size
        if tp < 1 or self.num_kv_heads % tp != 0:
            raise ValueError(
                f"num_kv_heads={self.num_kv_heads} not divisible by "
                f"tp_size={tp}")
        return (self.num_blocks, self.block_size,
                self.num_kv_heads // tp, self.head_dim)

    def per_chip_pool_bytes(self) -> int:
        """Measured HBM bytes ONE chip holds across every layer's k+v
        pool — read off the arrays' addressable shards, not computed,
        so the serve_bench tp gate checks what XLA actually allocated.
        Falls back to the full array size for unsharded pools."""
        total = 0
        for kp, vp in self.pools:
            for arr in (kp, vp):
                shards = getattr(arr, "addressable_shards", None)
                if shards:
                    total += max(s.data.nbytes for s in shards)
                else:
                    total += arr.nbytes
        return total

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """DISTINCT allocated blocks — sharing shows up as lower usage."""
        return (self.num_blocks - 1) - len(self._free)

    @property
    def shared_blocks(self) -> int:
        return sum(1 for r in self._refs.values() if r > 1)

    @property
    def total_refs(self) -> int:
        return sum(self._refs.values())

    def ref_count(self, block: int) -> int:
        return self._refs.get(block, 0)

    def occupancy(self) -> float:
        """Fraction of allocatable blocks in use (serve_event metric)."""
        return self.used_blocks / max(1, self.num_blocks - 1)

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def _pop_free(self) -> int:
        """Take a block for FRESH content, lazily evicting any stale
        cached-free index entry it still carries (freed blocks keep
        their prefix KV reusable until the pool actually needs them —
        free_sequence appends to the RIGHT and this pops from the LEFT,
        so the longest-freed cached content is evicted first). With a
        host tier attached the content is demoted before the entry
        dies — eviction becomes a tier transition, not a loss."""
        block = self._free.popleft()
        key = self._key_of.pop(block, None)
        if key is not None and self._index.get(key) == block:
            self._demote_block(block, key, "evict")
            del self._index[key]
            self.cached_free_evictions += 1
            self._c_evict.inc()
        return block

    def _demote_block(self, block: int, key: tuple, reason: str) -> bool:
        """device_get one committed block's KV (every layer) into the
        host tier under its content key. No-op without a tier or when
        the tier already holds the key (a revived-but-unflushed block
        would otherwise read back garbage — the tier copy is the truth
        until the staged load lands)."""
        if self.host_tier is None or self.host_tier.contains(key):
            return False
        layers = [(np.asarray(kp[block]), np.asarray(vp[block]))
                  for kp, vp in self.pools]
        return self.host_tier.put(key, layers, reason=reason)

    def demote_sequence(self, seq_id: int, reason: str = "preempt") -> int:
        """Copy a live sequence's committed full blocks out to the host
        tier — the preemption path: the scheduler calls this right
        before free_sequence so re-admission revives the context by DMA
        instead of re-prefilling it (quadratic recompute becomes a
        linear copy). A prefill-phase engine also calls it at request
        FINISH (reason="finish") so a decode replica can pull the
        finished prefix over the fleet KV-transfer plane
        (serve/kvxfer.py). Returns blocks demoted."""
        if self.host_tier is None or not self.enable_prefix_cache:
            return 0
        table = self._tables.get(seq_id)
        if table is None:
            return 0
        self._register_full_blocks(seq_id)
        toks = self._tokens[seq_id]
        bs = self.block_size
        count = 0
        for bi in range(self._committed.get(seq_id, 0) // bs):
            key = self._key_of.get(table[bi]) or tuple(toks[:(bi + 1) * bs])
            if self._demote_block(table[bi], key, reason):
                count += 1
        return count

    def _match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest run of committed full blocks matching `tokens`' head
        (read-only: no refs taken)."""
        if not self.enable_prefix_cache:
            return []
        matched: List[int] = []
        bs = self.block_size
        for end in range(bs, len(tokens) + 1, bs):
            block = self._index.get(tuple(tokens[:end]))
            if block is None:
                break
            matched.append(block)
        return matched

    def can_allocate(self, tokens) -> bool:
        """Admission check. `tokens` may be a token list (prefix-aware:
        matched blocks cost nothing beyond their own revival) or a bare
        count (conservative)."""
        if isinstance(tokens, int):
            return self.blocks_for(tokens) <= len(self._free)
        matched = self._match_prefix(tokens)
        need = self.blocks_for(len(tokens)) - len(matched)
        # cached-free matches leave the free list too (revival)
        revive = sum(1 for b in matched if b not in self._refs)
        return need + revive <= len(self._free)

    # -- sequence lifecycle ----------------------------------------------
    def alloc_sequence(self, seq_id: int, tokens: Sequence[int],
                       count_stats: bool = True) -> int:
        """Reserve blocks for a sequence's prompt, reusing committed
        prefix blocks from the index. Returns the number of CACHED
        tokens (KV already in the pool — the engine prefills only the
        suffix). A full-prompt hit is capped at n-1 so the last token
        always recomputes (its logits seed sampling); that write lands
        inside a shared block and COWs it. Raises CacheExhausted
        (allocating nothing) when the free list is short — the
        scheduler turns that into deferred admission or preemption.
        `count_stats=False` leaves hit_tokens/prompt_tokens untouched:
        a preemption re-admission re-hits its own just-committed blocks
        and would otherwise inflate hit_rate."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        n = len(tokens)
        bs = self.block_size
        matched = self._match_prefix(tokens)
        # walk PAST the device match into the host tier: every hit is
        # fetched now (the payload is pinned here — a later demotion's
        # LRU eviction between admission and flush can't revoke it)
        host_loads: List[Tuple[tuple, list]] = []
        if self.host_tier is not None and self.enable_prefix_cache:
            for end in range((len(matched) + 1) * bs, n + 1, bs):
                layers = self.host_tier.get(tuple(tokens[:end]))
                if layers is None:
                    break
                host_loads.append((tuple(tokens[:end]), layers))
        need = self.blocks_for(n) - len(matched)
        revive = [b for b in matched if b not in self._refs]
        if need + len(revive) > len(self._free):
            raise CacheExhausted(
                f"need {need + len(revive)} blocks, {len(self._free)} free")
        for b in matched:
            if b in self._refs:
                self._refs[b] += 1
            else:                       # cached-free hit: revive the block
                self._free.remove(b)
                self._refs[b] = 1
                self.cached_free_revivals += 1
                self._c_revive.inc()
        # host-tier hits claim fresh device blocks and stage their DMA;
        # the key registers first-wins so later prompts can share the
        # block as soon as the engine flushes the load
        host_blocks: List[int] = []
        for key, layers in host_loads:
            b = self._pop_free()
            self._refs[b] = 1
            host_blocks.append(b)
            self._pending_host_loads.append((b, layers))
            if key not in self._index and b not in self._key_of:
                self._index[key] = b
                self._key_of[b] = key
        fresh = [self._pop_free() for _ in range(need - len(host_blocks))]
        for b in fresh:
            self._refs[b] = 1
        self._tables[seq_id] = matched + host_blocks + fresh
        self._lens[seq_id] = n
        self._tokens[seq_id] = list(tokens)
        cached = min((len(matched) + len(host_blocks)) * bs, n - 1)
        self._committed[seq_id] = cached
        if host_blocks:
            tier_toks = max(0, cached - len(matched) * bs)
            self.tier_revivals += len(host_blocks)
            self.tier_hit_tokens += tier_toks
            self.host_tier.note_revived(len(host_blocks), tier_toks)
        if count_stats:
            self.hit_tokens += cached
            self.prompt_tokens += n
            self._c_hit_toks.inc(cached)
            self._c_prompt_toks.inc(n)
        return cached

    def ensure_writable(self, seq_id: int, start: int, end: int) -> None:
        """Copy-on-write pass before the engine scatters positions
        [start, end): every touched block with refcount > 1 is swapped
        for a fresh private block and an on-device (src, dst) block
        copy is queued (drain_copies) so already-valid positions in the
        block survive. Raises CacheExhausted when a COW needs a block
        and the free list is empty."""
        table = self._tables[seq_id]
        bs = self.block_size
        for bi in range(start // bs, (max(end, start + 1) - 1) // bs + 1):
            old = table[bi]
            if self._refs[old] <= 1:
                continue
            if not self._free:
                raise CacheExhausted("no free block for copy-on-write")
            new = self._pop_free()
            self._refs[old] -= 1
            self._refs[new] = 1
            table[bi] = new
            self._pending_copies.append((old, new))
            self.cow_copies += 1
            self._c_cow.inc()

    def drain_copies(self) -> List[Tuple[int, int]]:
        """Queued COW block copies; the engine MUST replay them on the
        device pools (src block -> dst block, every layer) before the
        next prefill/decode call reads or writes the dst blocks."""
        out, self._pending_copies = self._pending_copies, []
        return out

    def drain_host_loads(self) -> List[Tuple[int, list]]:
        """Staged host-tier revivals: (block, per-layer [(k, v)] host
        arrays). The engine MUST write them into the device pools
        BEFORE draining COW copies — a just-revived block can be the
        src of a same-plan copy-on-write."""
        out, self._pending_host_loads = self._pending_host_loads, []
        return out

    def commit_prefill(self, seq_id: int, upto: int) -> None:
        """Mark positions [0, upto) as present in the pool (a prefill
        chunk just scattered them) and register every newly-full block
        in the prefix index so later prompts can share it."""
        self._committed[seq_id] = max(self._committed.get(seq_id, 0), upto)
        self._register_full_blocks(seq_id)

    def committed_len(self, seq_id: int) -> int:
        return self._committed.get(seq_id, 0)

    def _register_full_blocks(self, seq_id: int) -> None:
        if not self.enable_prefix_cache:
            return
        bs = self.block_size
        table = self._tables[seq_id]
        toks = self._tokens[seq_id]
        for bi in range(self._committed[seq_id] // bs):
            block = table[bi]
            if block in self._key_of:
                continue                    # already indexed (maybe shared)
            key = tuple(toks[:(bi + 1) * bs])
            if key in self._index:
                continue                    # duplicate content: first wins
            self._index[key] = block
            self._key_of[block] = key

    def append_token(self, seq_id: int) -> int:
        """Reserve the slot for this sequence's next token (allocating a
        fresh block at a block boundary, COWing a shared tail block);
        returns the FLAT pool slot (block_id * block_size + offset) the
        engine passes to the decode step. Does NOT advance the length —
        call advance() after the step actually writes."""
        return self.reserve_slots(seq_id, 1)[0]

    def reserve_slots(self, seq_id: int, count: int) -> List[int]:
        """Reserve the next `count` token slots in one ALL-OR-NOTHING
        transaction (the speculative-decode path: the base token plus k
        draft tokens land in one multi-token StepRow, so either the
        whole window gets slots or the scheduler falls back to a plain
        1-token decode). The bill is pre-checked — COW copies for
        shared blocks the window touches plus fresh blocks past the
        table's end — and CacheExhausted raises BEFORE any refcount or
        table mutation, so a failed reservation leaves nothing to roll
        back. Returns the flat pool slots in window order. Like
        append_token, the length does not advance: the engine calls
        advance() only for positions verification actually accepted,
        and un-advanced slots are simply re-reserved (and overwritten)
        by the next step — that IS the speculative rollback."""
        pos = self._lens[seq_id]
        table = self._tables[seq_id]
        bs = self.block_size
        end = pos + count
        in_table_end = min(end, len(table) * bs)
        cow_need = 0
        if in_table_end > pos:
            cow_need = sum(
                1 for bi in range(pos // bs, (in_table_end - 1) // bs + 1)
                if self._refs[table[bi]] > 1)
        new_need = max(0, self.blocks_for(end) - len(table))
        if cow_need + new_need > len(self._free):
            raise CacheExhausted(
                f"need {cow_need + new_need} blocks ({cow_need} COW + "
                f"{new_need} fresh), {len(self._free)} free")
        if in_table_end > pos:
            self.ensure_writable(seq_id, pos, in_table_end)
        for _ in range(new_need):
            block = self._pop_free()
            self._refs[block] = 1
            table.append(block)
        return [table[(pos + j) // bs] * bs + (pos + j) % bs
                for j in range(count)]

    def fork_sequence(self, src_id: int, dst_id: int) -> None:
        """Clone `src_id`'s sequence state into `dst_id` sharing EVERY
        block (refcount bump — zero new blocks, zero device copies):
        the parallel-sampling / best-of-n primitive. A finished prefill
        forks into n candidates that all read the same prompt KV; the
        first time a fork WRITES (its own generated tokens, starting
        with the shared partially-filled tail block) the ordinary
        ensure_writable copy-on-write path peels it a private copy.
        free_sequence needs no special casing: a fork's exclusive
        blocks (refcount 1) return to the free list, shared prompt
        blocks just drop one reference."""
        if dst_id in self._tables:
            raise ValueError(f"sequence {dst_id} already allocated")
        table = self._tables[src_id]
        for b in table:
            self._refs[b] += 1
        self._tables[dst_id] = list(table)
        self._lens[dst_id] = self._lens[src_id]
        self._tokens[dst_id] = list(self._tokens[src_id])
        self._committed[dst_id] = self._committed[src_id]

    def advance(self, seq_id: int, token: int) -> None:
        """The decode step wrote `token`'s k/v at the reserved slot:
        extend the sequence and index the tail block if it just
        filled (generated continuations are shareable too)."""
        self._tokens[seq_id].append(token)
        self._lens[seq_id] += 1
        self._committed[seq_id] = self._lens[seq_id]
        if self._lens[seq_id] % self.block_size == 0:
            self._register_full_blocks(seq_id)

    def free_sequence(self, seq_id: int) -> int:
        """Drop this sequence's references; blocks whose refcount hits
        zero return to the free list but KEEP their prefix-index entry
        (cached-free): a later prompt with the same prefix revives them
        instead of recomputing, and `_pop_free` lazily evicts the entry
        only when the pool reuses the block for fresh content. Queued
        COW copies targeting a freed block are cancelled — the pool may
        hand the block straight back out, and a stale copy flushing
        later would clobber the new owner's KV. Returns how many blocks
        went back to the free list (shared ones live on)."""
        blocks = self._tables.pop(seq_id, [])
        self._lens.pop(seq_id, None)
        self._tokens.pop(seq_id, None)
        self._committed.pop(seq_id, None)
        freed = 0
        freed_set = set()
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)
                freed += 1
                freed_set.add(b)
        if freed_set and self._pending_copies:
            self._pending_copies = [
                (s, d) for s, d in self._pending_copies
                if d not in freed_set]
        if freed_set and self._pending_host_loads:
            # cancel-mid-revival: the request died before its staged
            # host loads flushed. The freed blocks were index-registered
            # for content that never arrived — deregister them (the
            # host tier still holds the data; a re-request revives it
            # onto new blocks).
            stale = [b for b, _ in self._pending_host_loads
                     if b in freed_set]
            if stale:
                self._pending_host_loads = [
                    (b, la) for b, la in self._pending_host_loads
                    if b not in freed_set]
                for b in stale:
                    key = self._key_of.pop(b, None)
                    if key is not None and self._index.get(key) == b:
                        del self._index[key]
        return freed

    # -- views for the jitted step ---------------------------------------
    def seq_len(self, seq_id: int) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def slot_of(self, seq_id: int, pos: int) -> int:
        """Flat pool slot of an ALREADY-RESERVED position."""
        table = self._tables[seq_id]
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size

    def padded_table(self, seq_id: int, max_blocks: int) -> List[int]:
        """Block table right-padded with scratch block 0 to the fixed
        width the compiled decode step expects."""
        table = self._tables[seq_id]
        if len(table) > max_blocks:
            raise ValueError(f"sequence {seq_id} spans {len(table)} blocks "
                             f"> max {max_blocks}")
        return table + [0] * (max_blocks - len(table))

    def prefix_keys(self, limit: int = 512) -> List[tuple]:
        """Most recently indexed prefix keys (device tier) — the
        engine's half of the fleet prefix directory advertisement.
        Engine-loop thread only (reads the unlocked index)."""
        keys = list(self._index.keys())
        return keys[-limit:] if limit and len(keys) > limit else keys

    # -- observability ----------------------------------------------------
    def hit_rate(self) -> float:
        """Fraction of all prompt tokens served from the prefix cache."""
        return self.hit_tokens / max(1, self.prompt_tokens)

    def stats(self) -> Dict[str, float]:
        out = {
            "hit_tokens": self.hit_tokens,
            "prompt_tokens": self.prompt_tokens,
            "hit_rate": round(self.hit_rate(), 4),
            "cow_copies": self.cow_copies,
            "cached_free_evictions": self.cached_free_evictions,
            "cached_free_revivals": self.cached_free_revivals,
            "shared_blocks": self.shared_blocks,
            "used_blocks": self.used_blocks,
            "occupancy": round(self.occupancy(), 4),
        }
        if self.host_tier is not None:
            out["tier_revivals"] = self.tier_revivals
            out["tier_hit_tokens"] = self.tier_hit_tokens
            out.update(self.host_tier.stats())
        return out

    def reset_stats(self) -> None:
        self.hit_tokens = self.prompt_tokens = self.cow_copies = 0
        self.cached_free_evictions = self.cached_free_revivals = 0
        self.tier_revivals = self.tier_hit_tokens = 0

    def assert_quiesced(self) -> None:
        """Leak check: with no live sequences every refcount must be
        gone and the free list full. Index entries may remain, but only
        for cached-free blocks (their content stays reusable by
        design); an indexed block NOT on the free list is a leak."""
        if self._tables:
            raise RuntimeError(f"live sequences: {list(self._tables)}")
        if self._refs:
            raise RuntimeError(f"leaked refcounts: {self._refs}")
        if self._pending_host_loads:
            raise RuntimeError(
                f"{len(self._pending_host_loads)} host-tier loads never "
                "flushed")
        if len(self._free) != self.num_blocks - 1:
            raise RuntimeError(
                f"free list {len(self._free)} != {self.num_blocks - 1}")
        free = set(self._free)
        leaked = [b for b in self._key_of if b not in free]
        if leaked:
            raise RuntimeError(
                f"indexed blocks not on the free list: {leaked}")

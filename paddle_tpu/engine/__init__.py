"""Online inference engine: paged KV cache + continuous batching.

The serving-runtime counterpart of io/inference.py's static predictor
(ENGINE.md): `ServeEngine` runs a CausalLM under iteration-level
scheduling — requests join and leave the batch every step, KV state
lives in a block-pool `PagedKVCache`, and decode attention gathers
through block tables (kernels/paged_attention.py).
"""

from paddle_tpu.engine.draft import NgramDrafter
from paddle_tpu.engine.engine import ServeEngine, serve_metadata
from paddle_tpu.engine.kvtier import HostKVTier, prefix_digest
from paddle_tpu.engine.paged_cache import CacheExhausted, PagedKVCache
from paddle_tpu.engine.scheduler import (PrefillChunk, Request, Scheduler,
                                         StepRow)

__all__ = ["ServeEngine", "serve_metadata", "PagedKVCache",
           "CacheExhausted", "Scheduler", "Request", "StepRow",
           "PrefillChunk", "NgramDrafter", "HostKVTier", "prefix_digest"]

"""Executors: the runtime that turns (module, optimizer, loss) into compiled
TPU step functions.

Capability-equivalent of the reference execution stack:
- `Executor` ≈ python/paddle/fluid/executor.py:262 + framework/executor.cc:185
  (run a program with feed/fetch, program cache keyed on the fn).
- `Trainer`/`TrainState` ≈ the Executor + append_backward (backward.py:394) +
  optimizer.minimize flow: here `jax.value_and_grad` over a pure loss is the
  autodiff, and the whole fwd+bwd+update is ONE jitted function — the XLA
  compiler plays the role of the reference's op scheduler, fusion passes
  (ir/*_fuse_pass.cc) and garbage collector (framework/garbage_collector.h).
- Buffer donation (`donate_argnums`) is the analog of the reference's inplace/
  memory_optimize passes (details/memory_optimize_pass.cc): the old parameter
  buffers are reused for the new ones.
- NaN/Inf guard ≈ FLAGS_check_nan_inf (framework/operator.cc CheckNanInf).

TPU-first notes: the step function is traced once per (shape, dtype)
signature; static shapes are required. Python-level control flow in a step is
a bug, not a feature — recompile storms surface via the program cache stats.
"""

from __future__ import annotations

import dataclasses
import functools
import time
import weakref
from collections import OrderedDict
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

import jax
import jax.numpy as jnp

from paddle_tpu.core.module import Module, Variables, PARAMS, STATE
from paddle_tpu.optim.optimizer import Optimizer
from paddle_tpu.profiler.profiler import RecordEvent
from paddle_tpu.utils.flags import FLAGS

Pytree = Any


class ExecutorError(Exception):
    pass


def check_nan_inf(tree: Pytree, what: str = "outputs") -> None:
    """Debug guard: raise if any leaf contains NaN/Inf.

    Reference: FLAGS_check_nan_inf, framework/operator.cc CheckNanInf path.
    Runs host-side (blocks on device values) — debug mode only.
    """
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            if not bool(jnp.isfinite(arr).all()):
                name = "/".join(str(getattr(p, "key", p)) for p in path)
                raise FloatingPointError(
                    f"NaN/Inf detected in {what} at {name!r}")


# --------------------------------------------------------------------------
# TrainState: the unit of training progress (params + mutable state + opt).
# --------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TrainState:
    """All mutable training quantities as one pytree.

    ≈ the reference's Scope contents for a training program: parameters,
    BN running stats (non-trainable state), optimizer accumulators
    (optimizer.py _create_accumulators) and the global step.

    NOTE on `_step_hint`: trainers stamp returned states with a host-side
    `_step_hint` int attribute that rides OUTSIDE the pytree — any
    `jax.tree.map` over a TrainState builds a new instance and silently
    drops it. That is safe (host_step_of falls back to one device_get and
    trainers re-stamp on the next step) but costs one sync; the hint is a
    logging optimisation only and nothing in the compiled step depends on
    it.
    """
    params: Pytree
    state: Pytree          # non-trainable module state (BN stats, ...)
    opt_state: Pytree
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.state, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def variables(self) -> Variables:
        return {PARAMS: self.params, STATE: self.state}


def host_step_of(ts: TrainState) -> int:
    """Host-side value of ts.step without a device sync when possible.

    Trainers stamp returned TrainStates with a `_step_hint` attribute
    (plain Python int riding outside the pytree) when the incoming state
    carried one; a state that went through a pytree transform or a
    checkpoint restore loses the hint and costs ONE device_get here.
    The hot path never depends on this: the default rng stream is derived
    from the device-resident ts.step inside the compiled step, so
    host_step_of is only for host-side logging (fit, bench loops).
    """
    hint = getattr(ts, "_step_hint", None)
    if hint is None:
        hint = int(jax.device_get(ts.step))
    return hint


def _stamp_step(ts: TrainState, step: int) -> TrainState:
    ts._step_hint = step
    return ts


# --------------------------------------------------------------------------
# Trainer: builds and caches the compiled train/eval step.
# --------------------------------------------------------------------------

class Trainer:
    """Single-device training engine.

    loss_fn(module, variables, batch, rngs, training) -> (loss, aux) where
    aux is a dict of extra fetches (metrics). The full step compiles to one
    XLA executable with donated state buffers.

    For mesh execution use paddle_tpu.parallel.MeshTrainer, which shares this
    state layout so checkpoints interchange.
    """

    def __init__(self, module: Module, optimizer: Optimizer,
                 loss_fn: Callable[..., Tuple[jax.Array, Dict[str, Any]]],
                 seed: int = 0):
        self.module = module
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.seed = seed
        self._train_step = None
        self._eval_step = None
        self.compile_count = 0

    # -- state ------------------------------------------------------------
    def init_state(self, *example_inputs, rng: Optional[jax.Array] = None
                   ) -> TrainState:
        if rng is None:
            rng = jax.random.key(self.seed)
        variables = self.module.init(rng, *example_inputs)
        params = variables.get(PARAMS, {})
        return _stamp_step(TrainState(
            params=params,
            state=variables.get(STATE, {}),
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        ), 0)

    # -- step builders ----------------------------------------------------
    def _build_train_step(self):
        module, optimizer, loss_fn = self.module, self.optimizer, self.loss_fn
        seed = self.seed

        def step_fn(ts: TrainState, batch, rng) -> Tuple[TrainState, Dict]:
            if rng is None:
                # Default rng stream derived from the device-resident step
                # inside the compiled fn: no host sync, and the stream stays
                # tied to the state itself (rollback/restore reproducible).
                rng = jax.random.fold_in(jax.random.key(seed ^ 0x5EED),
                                         ts.step)

            def loss_of(params):
                variables = {PARAMS: params, STATE: ts.state}
                (loss, aux), new_state = loss_fn(
                    module, variables, batch, rng, True)
                return loss, (aux, new_state)

            (loss, (aux, new_state)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(ts.params)
            new_params, new_opt = optimizer.apply(
                ts.params, grads, ts.opt_state)
            new_ts = TrainState(new_params, new_state, new_opt, ts.step + 1)
            fetches = {"loss": loss, **aux}
            return new_ts, fetches

        self.compile_count += 1
        return jax.jit(step_fn, donate_argnums=(0,))

    def _build_eval_step(self):
        module, loss_fn = self.module, self.loss_fn

        def step_fn(ts: TrainState, batch) -> Dict:
            variables = {PARAMS: ts.params, STATE: ts.state}
            (loss, aux), _ = loss_fn(module, variables, batch, None, False)
            return {"loss": loss, **aux}

        return jax.jit(step_fn)

    # -- public API -------------------------------------------------------
    def train_step(self, ts: TrainState, batch, rng=None
                   ) -> Tuple[TrainState, Dict]:
        if self._train_step is None:
            self._train_step = self._build_train_step()
        with RecordEvent("Trainer.train_step"):
            new_ts, fetches = self._train_step(ts, batch, rng)
        hint = getattr(ts, "_step_hint", None)
        if hint is not None:
            _stamp_step(new_ts, hint + 1)
        if FLAGS.get("check_nan_inf"):
            check_nan_inf(fetches, "train fetches")
            check_nan_inf(new_ts.params, "params")
        return new_ts, fetches

    def eval_step(self, ts: TrainState, batch) -> Dict:
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        return self._eval_step(ts, batch)

    def fit(self, ts: TrainState, data: Iterable, epochs: int = 1,
            log_every: int = 100,
            callback: Optional[Callable[[int, Dict], None]] = None
            ) -> TrainState:
        """Simple epoch loop (≈ tests/book training loops)."""
        step_t0, bench = time.perf_counter(), FLAGS.get("benchmark")
        # one sync at most (restored states); the loop then counts locally
        s = host_step_of(ts)
        _stamp_step(ts, s)
        for epoch in range(epochs):
            for batch in data:
                ts, fetches = self.train_step(ts, batch)
                s += 1
                if callback is not None:
                    callback(s, fetches)
                if bench and log_every and s % log_every == 0:
                    dt = (time.perf_counter() - step_t0) / log_every
                    print(f"step {s} loss {float(fetches['loss']):.4f} "
                          f"{dt * 1e3:.2f} ms/step")
                    step_t0 = time.perf_counter()
        return ts


def supervised_loss(criterion: Callable[[jax.Array, jax.Array], jax.Array],
                    metrics: Optional[Dict[str, Callable]] = None):
    """Standard loss_fn factory: module(x) vs labels under `criterion`.

    Batch convention: (inputs, labels) tuple or {"image":..., "label":...}.
    """
    metrics = metrics or {}

    def loss_fn(module, variables, batch, rng, training):
        if isinstance(batch, dict):
            x, y = batch["image"], batch["label"]
        else:
            x, y = batch
        out, mut = module.apply(variables, x, training=training, rngs=rng,
                                mutable=True)
        loss = jnp.mean(criterion(out, y))
        aux = {name: fn(out, y) for name, fn in metrics.items()}
        return (loss, aux), mut.get(STATE, variables.get(STATE, {}))

    return loss_fn


def train_from_files(trainer: "Trainer", ts: TrainState,
                     files: Sequence[str], slots,
                     batch_fn: Optional[Callable] = None, *,
                     batch_size: int = 128, nthreads: int = 2,
                     epochs: int = 1, prefetch: int = 2,
                     max_sparse_len: Optional[int] = None,
                     drop_last: bool = True,
                     callback: Optional[Callable[[int, Dict], None]] = None
                     ) -> TrainState:
    """Train straight from slot-format text files.

    The AsyncExecutor.RunFromFile capability (reference
    framework/async_executor.cc:236: training threads consume a DataFeed
    without returning to Python between examples) in TPU form: the native
    MultiSlotDataFeed parses files on C++ threads, sparse slots convert to
    static-shape padded+mask form, and `data.feeder.device_prefetch` keeps
    `prefetch` H2D transfers in flight so parsing and copies overlap the
    device step.

    `batch_fn(batch_dict) -> model batch` adapts a columnar batch (dense
    slots: arrays; sparse slots: (padded, mask) after conversion) to the
    trainer's batch convention; default passes the dict through. With
    `drop_last` the ragged tail batch is dropped so every step reuses one
    compiled shape (a tail batch would recompile and, at scale, that is
    almost always the wrong trade).
    """
    from paddle_tpu.data.datafeed import (MultiSlotDataFeed, _batch_rows,
                                          to_padded)
    from paddle_tpu.data.feeder import device_prefetch

    feed = MultiSlotDataFeed(files, slots, batch_size=batch_size,
                             nthreads=nthreads)
    sparse = [s.name for s in feed.slots if not s.dense]
    if sparse and max_sparse_len is None:
        raise ValueError(
            f"sparse slots {sparse} need max_sparse_len for the "
            "static-shape padded form")

    def batches():
        for b in feed:
            if drop_last and _batch_rows(b) != batch_size:
                continue
            out = {}
            for name, v in b.items():
                out[name] = (to_padded(v[0], v[1], max_sparse_len)
                             if isinstance(v, tuple) else v)
            yield batch_fn(out) if batch_fn is not None else out

    s = host_step_of(ts)
    _stamp_step(ts, s)
    for _ in range(epochs):
        for batch in device_prefetch(batches(), size=prefetch):
            ts, fetches = trainer.train_step(ts, batch)
            s += 1
            if callback is not None:
                callback(s, fetches)
    return ts


# --------------------------------------------------------------------------
# Executor: generic compiled-program runner with feed/fetch (reference API).
# --------------------------------------------------------------------------

class Executor:
    """Run arbitrary pure programs with a compile cache.

    ≈ fluid.Executor (executor.py:262): `run(program, feed, fetch_list)`.
    A "program" here is any pure Python callable over arrays; it is jitted
    once per abstract input signature and cached (the reference caches
    prepared ExecutorPrepareContexts the same way, executor.py program cache).
    """

    def __init__(self, place: Optional[Any] = None):
        self.place = place or jax.devices()[0]
        # Keyed on (program, signature): the program object itself (not
        # id()) so an id can never be recycled and served a stale
        # executable; bound methods hash by (__self__, __func__), so the
        # per-call method object still hits its entry. LRU-bounded by
        # FLAGS_executor_cache_capacity (read per run, so tests and
        # long-lived servers can retune it live); a long-lived process
        # running many distinct programs evicts oldest-used instead of
        # growing without bound. close() releases everything.
        self._cache: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self.cache_misses = 0
        self.cache_hits = 0
        self.cache_evictions = 0
        _live_executors.add(self)

    @staticmethod
    def _signature(feed: Dict[str, Any]) -> Tuple:
        sig = []
        for k in sorted(feed):
            arr = jnp.asarray(feed[k])
            sig.append((k, arr.shape, str(arr.dtype)))
        return tuple(sig)

    def run(self, program: Callable, feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence[str]] = None):
        """program(**feed) -> dict of outputs; returns [outputs[k] for k in
        fetch_list] as numpy-convertible arrays (or the full dict)."""
        feed = feed or {}
        key = (program, self._signature(feed))
        fn = self._cache.get(key)
        if fn is None:
            fn = self._cache[key] = jax.jit(program)
            self.cache_misses += 1
        else:
            self._cache.move_to_end(key)
            self.cache_hits += 1
        # enforce on hits too: lowering the flag live must shrink an
        # all-hit working set, not wait for the next miss
        cap = FLAGS.get("executor_cache_capacity")
        while cap > 0 and len(self._cache) > cap:
            self._cache.popitem(last=False)
            self.cache_evictions += 1
        with RecordEvent("Executor.run"):
            out = fn(**{k: jnp.asarray(v) for k, v in feed.items()})
        if FLAGS.get("check_nan_inf"):
            check_nan_inf(out, "program outputs")
        if fetch_list is None:
            return out
        if not isinstance(out, dict):
            raise ExecutorError("fetch_list given but program returned "
                                f"{type(out).__name__}, expected dict")
        missing = [k for k in fetch_list if k not in out]
        if missing:
            raise ExecutorError(f"fetch targets not produced: {missing}")
        return [out[k] for k in fetch_list]

    def cache_stats(self) -> Dict[str, int]:
        return {"entries": len(self._cache), "hits": self.cache_hits,
                "misses": self.cache_misses,
                "evictions": self.cache_evictions}

    def close(self) -> None:
        self._cache.clear()


# Live executors, for utils.debug.memory_stats' executor_caches section
# (weak: an Executor's lifetime is its owner's business, not the stats').
_live_executors: "weakref.WeakSet[Executor]" = weakref.WeakSet()


def executor_cache_stats() -> List[Dict[str, int]]:
    """Aggregate compile-cache stats over all live Executors."""
    return [e.cache_stats() for e in _live_executors]


class NaiveExecutor:
    """Inference-only runner: one compiled fn, zero feed/fetch overhead.

    ≈ framework/naive_executor.h:31 (and the ZeroCopyRun idea,
    analysis_predictor.h:61): inputs go straight to the compiled callable,
    buffers stay on device.
    """

    def __init__(self, fn: Callable, example_args: Sequence[Any]):
        self._compiled = jax.jit(fn).lower(*example_args).compile()

    def run(self, *args):
        return self._compiled(*args)

"""Core NN layers (dense stack).

Capability-equivalent of the reference layers DSL (python/paddle/fluid/layers/
nn.py — fc, conv2d, conv3d, pool2d, batch_norm, layer_norm, group_norm,
dropout, embedding, one-hot, etc.) and their C++ kernels (operators/*,
conv_cudnn_op.cu.cc, batch_norm_op.cu).

TPU-first choices:
- NHWC image layout (the TPU-native layout; the reference defaults NCHW for
  cuDNN). `data_format` arg accepts both; NHWC is the fast path.
- bfloat16-friendly: params kept fp32 by default, compute dtype selectable;
  matmuls/convs hit the MXU via lax.dot_general/conv_general_dilated.
- No im2col/col2im machinery (operators/math/im2col.cc) — XLA lowers convs
  to MXU directly.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.module import Context, Module
from paddle_tpu.nn import initializers as I


def _pair(v) -> Tuple[int, int]:
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def normalize_padding(pad):
    """2-D conv padding in its lax form: "SAME"/"VALID" pass through; an
    int or (h, w) int pair becomes explicit per-dim (lo, hi) pairs. One
    home for the idiom (Conv2D and the quant/int8 conv twins all accept
    the same forms)."""
    if isinstance(pad, int):
        return [(pad, pad), (pad, pad)]
    if isinstance(pad, (tuple, list)) and isinstance(pad[0], int):
        return [(pad[0], pad[0]), (pad[1], pad[1])]
    return pad


class Linear(Module):
    """Fully-connected layer (reference fluid.layers.fc, nn.py; mul+add ops).

    Input dim inferred at init-trace time (lazy, like the reference's fc
    which infers from input shape).
    """

    def __init__(self, features: int, use_bias: bool = True,
                 kernel_init=None, bias_init=None, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        super().__init__()
        self.features = features
        self.use_bias = use_bias
        self.kernel_init = kernel_init or I.glorot_uniform
        self.bias_init = bias_init or I.zeros
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        in_features = x.shape[-1]
        w = cx.param("weight", (in_features, self.features),
                     self.kernel_init, self.param_dtype)
        x, w = self._qtransform(cx, x, w)
        y = jnp.matmul(x.astype(self.dtype), w.astype(self.dtype))
        if self.use_bias:
            b = cx.param("bias", (self.features,), self.bias_init,
                         self.param_dtype)
            y = y + b.astype(self.dtype)
        return y

    def _qtransform(self, cx: Context, x, w):
        """Hook for input/weight transforms (quant.layers overrides this
        with the fake-quant pair); identity in the float layer."""
        return x, w


class Conv2D(Module):
    """2-D convolution, NHWC, kernel (kh, kw, in/groups, out).

    Reference: fluid.layers.conv2d + operators/conv_op.cc, conv_cudnn_op.
    """

    def __init__(self, features: int, kernel_size, stride=1, padding="SAME",
                 dilation=1, groups: int = 1, use_bias: bool = True,
                 kernel_init=None, bias_init=None, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        super().__init__()
        self.features = features
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.dilation = _pair(dilation)
        self.padding = padding
        self.groups = groups
        self.use_bias = use_bias
        self.kernel_init = kernel_init or I.kaiming_normal
        self.bias_init = bias_init or I.zeros
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        cin = x.shape[-1]
        kh, kw = self.kernel_size
        w = cx.param("weight", (kh, kw, cin // self.groups, self.features),
                     self.kernel_init, self.param_dtype)
        x, w = self._qtransform(cx, x, w)
        pad = normalize_padding(self.padding)
        y = lax.conv_general_dilated(
            x.astype(self.dtype), w.astype(self.dtype),
            window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation, feature_group_count=self.groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            b = cx.param("bias", (self.features,), self.bias_init,
                         self.param_dtype)
            y = y + b.astype(self.dtype)
        return y

    def _qtransform(self, cx: Context, x, w):
        """Hook for input/weight transforms (see Linear._qtransform)."""
        return x, w


class Conv2DTranspose(Module):
    """Transposed conv (reference conv2d_transpose, operators/conv_transpose_op)."""

    def __init__(self, features: int, kernel_size, stride=1, padding="SAME",
                 use_bias: bool = True, kernel_init=None, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        super().__init__()
        self.features = features
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = padding
        self.use_bias = use_bias
        self.kernel_init = kernel_init or I.glorot_uniform
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        cin = x.shape[-1]
        kh, kw = self.kernel_size
        w = cx.param("weight", (kh, kw, cin, self.features),
                     self.kernel_init, self.param_dtype)
        y = lax.conv_transpose(
            x.astype(self.dtype), w.astype(self.dtype),
            strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            b = cx.param("bias", (self.features,), I.zeros, self.param_dtype)
            y = y + b.astype(self.dtype)
        return y


def _triple(v) -> Tuple[int, int, int]:
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v, v)


class Conv3D(Module):
    """3-D convolution, NDHWC, kernel (kd, kh, kw, in/groups, out).

    Reference: fluid.layers.conv3d (operators/conv_op.cc registers conv3d;
    kernels conv_op.h). TPU-first: NDHWC layout so XLA tiles the contraction
    onto the MXU exactly as for 2-D convs.
    """

    def __init__(self, features: int, kernel_size, stride=1, padding="SAME",
                 dilation=1, groups: int = 1, use_bias: bool = True,
                 kernel_init=None, bias_init=None, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        super().__init__()
        self.features = features
        self.kernel_size = _triple(kernel_size)
        self.stride = _triple(stride)
        self.dilation = _triple(dilation)
        self.padding = padding
        self.groups = groups
        self.use_bias = use_bias
        self.kernel_init = kernel_init or I.kaiming_normal
        self.bias_init = bias_init or I.zeros
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        cin = x.shape[-1]
        kd, kh, kw = self.kernel_size
        w = cx.param("weight", (kd, kh, kw, cin // self.groups, self.features),
                     self.kernel_init, self.param_dtype)
        pad = self.padding
        if isinstance(pad, int):
            pad = [(pad, pad)] * 3
        elif isinstance(pad, (tuple, list)) and isinstance(pad[0], int):
            pad = [(p, p) for p in pad]
        y = lax.conv_general_dilated(
            x.astype(self.dtype), w.astype(self.dtype),
            window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation, feature_group_count=self.groups,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.use_bias:
            b = cx.param("bias", (self.features,), self.bias_init,
                         self.param_dtype)
            y = y + b.astype(self.dtype)
        return y


class Conv3DTranspose(Module):
    """Transposed 3-D conv (reference conv3d_transpose,
    operators/conv_transpose_op.cc). NDHWC."""

    def __init__(self, features: int, kernel_size, stride=1, padding="SAME",
                 use_bias: bool = True, kernel_init=None, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        super().__init__()
        self.features = features
        self.kernel_size = _triple(kernel_size)
        self.stride = _triple(stride)
        self.padding = padding
        self.use_bias = use_bias
        self.kernel_init = kernel_init or I.glorot_uniform
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        cin = x.shape[-1]
        kd, kh, kw = self.kernel_size
        w = cx.param("weight", (kd, kh, kw, cin, self.features),
                     self.kernel_init, self.param_dtype)
        y = lax.conv_transpose(
            x.astype(self.dtype), w.astype(self.dtype),
            strides=self.stride, padding=self.padding,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.use_bias:
            b = cx.param("bias", (self.features,), I.zeros, self.param_dtype)
            y = y + b.astype(self.dtype)
        return y


def max_pool3d(x, window, stride=None, padding="VALID"):
    """Reference pool3d(pool_type='max') (operators/pool_op.cc). NDHWC."""
    wd, wh, ww = _triple(window)
    sd, sh, sw = _triple(stride if stride is not None else window)
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, wd, wh, ww, 1),
                             (1, sd, sh, sw, 1), padding)


def avg_pool3d(x, window, stride=None, padding="VALID"):
    """Reference pool3d(pool_type='avg'). NDHWC."""
    wd, wh, ww = _triple(window)
    sd, sh, sw = _triple(stride if stride is not None else window)
    summed = lax.reduce_window(x, 0.0, lax.add, (1, wd, wh, ww, 1),
                               (1, sd, sh, sw, 1), padding)
    return summed / (wd * wh * ww)


def lrn(x, n: int = 5, k: float = 1.0, alpha: float = 1e-4,
        beta: float = 0.75):
    """Local response normalisation across channels (reference lrn op,
    operators/lrn_op.cc). NHWC: window of `n` adjacent channels."""
    sq = jnp.square(x.astype(jnp.float32))
    half = n // 2
    # channel-axis sliding-window sum via padded reduce_window
    win = (1,) * (x.ndim - 1) + (n,)
    strides = (1,) * x.ndim
    pads = [(0, 0)] * (x.ndim - 1) + [(half, n - 1 - half)]
    denom = k + alpha * lax.reduce_window(sq, 0.0, lax.add, win, strides,
                                          pads)
    return (x.astype(jnp.float32) / jnp.power(denom, beta)).astype(x.dtype)


class DataNorm(Module):
    """Streaming feature normalisation without batch statistics coupling
    (reference data_norm op, operators/data_norm_op.cc: normalises by
    accumulated size/sum/squared-sum — used by CTR models where batch norm's
    batch coupling hurts).

    State: (count, sum, sumsq) accumulated per feature; output is
    (x - mean) / std with means/stds from the running totals.
    """

    def __init__(self, epsilon: float = 1e-4, param_dtype=jnp.float32):
        super().__init__()
        self.epsilon = epsilon
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        feat = x.shape[-1]
        count = cx.state("count", (), I.ones, self.param_dtype)
        total = cx.state("sum", (feat,), I.zeros, self.param_dtype)
        sumsq = cx.state("sumsq", (feat,), I.ones, self.param_dtype)
        mean = total / count
        var = jnp.maximum(sumsq / count - jnp.square(mean), 0.0)
        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.epsilon)
        if cx.training:
            xf = x.astype(jnp.float32).reshape(-1, feat)
            cx.set_state("count", count + xf.shape[0])
            cx.set_state("sum", total + jnp.sum(xf, axis=0))
            cx.set_state("sumsq", sumsq + jnp.sum(jnp.square(xf), axis=0))
        return y.astype(x.dtype)


def max_pool2d(x, window, stride=None, padding="VALID"):
    """Reference fluid.layers.pool2d(pool_type='max'); NHWC."""
    wh, ww = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, wh, ww, 1),
                             (1, sh, sw, 1), padding)


def avg_pool2d(x, window, stride=None, padding="VALID",
               count_include_pad: bool = True):
    wh, ww = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    summed = lax.reduce_window(x, 0.0, lax.add, (1, wh, ww, 1),
                               (1, sh, sw, 1), padding)
    if count_include_pad or padding == "VALID":
        return summed / (wh * ww)
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    counts = lax.reduce_window(ones, 0.0, lax.add, (1, wh, ww, 1),
                               (1, sh, sw, 1), padding)
    return summed / counts


def global_avg_pool2d(x):
    """pool2d(global_pooling=True) analog: NHWC → N,C."""
    return jnp.mean(x, axis=(1, 2))


class BatchNorm(Module):
    """Batch normalisation with running stats (reference batch_norm op,
    operators/batch_norm_op.cc; layers/nn.py batch_norm).

    Functional state: running mean/var live in the `state` collection and are
    returned via `apply(..., mutable=True)` during training. `axis` is the
    feature axis (NHWC → -1).
    """

    def __init__(self, momentum: float = 0.9, epsilon: float = 1e-5,
                 scale: bool = True, center: bool = True, axis: int = -1,
                 dtype=None, param_dtype=jnp.float32,
                 axis_name: Optional[str] = None,
                 fuse_relu: bool = False):
        super().__init__()
        self.momentum = momentum
        self.epsilon = epsilon
        self.scale = scale
        self.center = center
        self.axis = axis
        self.dtype = dtype
        self.param_dtype = param_dtype
        # If set, batch stats are psum-averaged over this mesh axis
        # (sync-BN — the multi-device analog of the reference's per-device BN).
        self.axis_name = axis_name
        # fuse_relu folds the activation INTO the layer and uses the
        # memory-efficient custom backward (nn/fused_bn.py): backward
        # reconstructs normalized activations from the output, so the
        # pre-BN tensor is never saved — the main HBM saver for conv+BN
        # towers (PERF_NOTES.md roofline).
        self.fuse_relu = fuse_relu

    def _update_ema(self, cx: Context, mean_rv, var_rv, mean, var) -> None:
        m = self.momentum
        cx.set_state("mean", (m * mean_rv + (1 - m) * mean)
                     .astype(self.param_dtype))
        cx.set_state("var", (m * var_rv + (1 - m) * var)
                     .astype(self.param_dtype))

    def forward(self, cx: Context, x, use_running_stats: Optional[bool] = None):
        feat = x.shape[self.axis]
        reduce_axes = tuple(i for i in range(x.ndim)
                            if i != (self.axis % x.ndim))
        shape = tuple(feat if i == (self.axis % x.ndim) else 1
                      for i in range(x.ndim))

        mean_rv = cx.state("mean", (feat,), I.zeros, self.param_dtype)
        var_rv = cx.state("var", (feat,), I.ones, self.param_dtype)

        use_running = (not cx.training) if use_running_stats is None \
            else use_running_stats
        if (self.fuse_relu and not use_running and self.scale
                and self.center and self.axis in (-1, x.ndim - 1)
                and self.axis_name is None):
            from paddle_tpu.nn.fused_bn import bn_relu_train
            g = cx.param("scale", (feat,), I.ones, self.param_dtype)
            b = cx.param("bias", (feat,), I.zeros, self.param_dtype)
            y, mean, var = bn_relu_train(x, g.astype(jnp.float32),
                                         b.astype(jnp.float32),
                                         float(self.epsilon))
            self._update_ema(cx, mean_rv, var_rv, mean, var)
            return y.astype(self.dtype or x.dtype)
        if use_running:
            mean, var = mean_rv, var_rv
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean2 = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            self._update_ema(cx, mean_rv, var_rv, mean, var)

        inv = lax.rsqrt(var.astype(jnp.float32) + self.epsilon)
        y = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
        if self.scale:
            g = cx.param("scale", (feat,), I.ones, self.param_dtype)
            y = y * g.reshape(shape)
        if self.center:
            b = cx.param("bias", (feat,), I.zeros, self.param_dtype)
            y = y + b.reshape(shape)
        if self.fuse_relu:
            # the layer owns its activation in fused mode; this branch is
            # the eval / non-fusable fallback with identical semantics
            y = jax.nn.relu(y)
        # dtype=None: match the input dtype (stats stay fp32 above). A bf16
        # activation stream stays bf16 end to end — upcasting here doubles
        # HBM traffic on every norm, the main MFU sink found in round 2.
        return y.astype(self.dtype or x.dtype)


class LayerNorm(Module):
    """Reference fluid.layers.layer_norm (operators/layer_norm_op)."""

    def __init__(self, epsilon: float = 1e-5, scale: bool = True,
                 center: bool = True, dtype=None,
                 param_dtype=jnp.float32):
        super().__init__()
        self.epsilon = epsilon
        self.scale = scale
        self.center = center
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        feat = x.shape[-1]
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.epsilon)
        if self.scale:
            y = y * cx.param("scale", (feat,), I.ones, self.param_dtype)
        if self.center:
            y = y + cx.param("bias", (feat,), I.zeros, self.param_dtype)
        return y.astype(self.dtype or x.dtype)


class GroupNorm(Module):
    """Reference fluid.layers.group_norm (operators/group_norm_op). NHWC."""

    def __init__(self, groups: int = 32, epsilon: float = 1e-5,
                 dtype=None, param_dtype=jnp.float32):
        super().__init__()
        self.groups = groups
        self.epsilon = epsilon
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, x):
        feat = x.shape[-1]
        g = self.groups
        orig = x.shape
        xf = x.astype(jnp.float32).reshape(orig[:-1] + (g, feat // g))
        axes = tuple(range(1, xf.ndim - 2)) + (xf.ndim - 1,)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
        y = ((xf - mean) * lax.rsqrt(var + self.epsilon)).reshape(orig)
        y = y * cx.param("scale", (feat,), I.ones, self.param_dtype)
        y = y + cx.param("bias", (feat,), I.zeros, self.param_dtype)
        return y.astype(self.dtype or x.dtype)


class Dropout(Module):
    """Reference fluid.layers.dropout (operators/dropout_op).

    Uses upscale-in-train convention (outputs scaled by 1/keep_prob during
    training, identity at inference).
    """

    def __init__(self, rate: float = 0.5):
        super().__init__()
        self.rate = rate

    def forward(self, cx: Context, x, deterministic: Optional[bool] = None):
        det = (not cx.training) if deterministic is None else deterministic
        if det or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(cx.rng(), keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x))


class Embedding(Module):
    """Token embedding lookup (reference lookup_table op,
    operators/lookup_table_op.cc; fluid.layers.embedding).

    `padding_idx` rows return zeros (reference padding_idx attr). The
    distributed/sharded variant lives in paddle_tpu.parallel.embedding.
    """

    def __init__(self, num_embeddings: int, features: int,
                 padding_idx: Optional[int] = None, embedding_init=None,
                 dtype=jnp.float32, param_dtype=jnp.float32):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.features = features
        self.padding_idx = padding_idx
        self.embedding_init = embedding_init or I.normal(0.0, 0.02)
        self.dtype = dtype
        self.param_dtype = param_dtype

    def forward(self, cx: Context, ids):
        table = cx.param("weight", (self.num_embeddings, self.features),
                         self.embedding_init, self.param_dtype)
        out = jnp.take(table, ids, axis=0).astype(self.dtype)
        if self.padding_idx is not None:
            mask = (ids != self.padding_idx)[..., None]
            out = jnp.where(mask, out, jnp.zeros_like(out))
        return out

    def attend(self, cx: Context, x):
        """Tied-softmax projection: x @ table.T (for LM output heads).

        Self-scopes like Module.__call__ so the lookup resolves to THIS
        module's "weight" — called bare with the parent's cx it would
        otherwise silently create an independent parent-level param and
        break the tie (the bug this fixed in BertEncoder's MLM head)."""
        cx = cx.scope(self._name or type(self).__name__)
        table = cx.param("weight", (self.num_embeddings, self.features),
                         self.embedding_init, self.param_dtype)
        return jnp.matmul(x.astype(self.dtype),
                          table.T.astype(self.dtype))

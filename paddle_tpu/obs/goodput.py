"""Training goodput ledger and MFU accounting.

Serving got its SLO math in PR 7; this is the training-side twin. Two
independent pieces share the module because both turn raw step
mechanics into the two numbers a training fleet is judged by:

- **GoodputLedger** — goodput = productive-step-time / tracked wall
  time. It consumes the `resilience` event stream through the
  `utils.log.add_event_tap` hook (PR 12), so the supervisor/trainer
  emit sites stay untouched: every rollback, bad-step skip, preempt,
  retry or chaos injection the run prints is *also* counted here, and
  the per-cause lost-time counters reconcile exactly with the event
  stream by construction. Time is attributed per attempt window: an
  `attempt()` that saw no fault event is productive; one that saw
  faults is charged to the worst cause observed (severity order
  below). Explicit `pause(cause)` windows cover the time a run spends
  outside attempts — checkpoint saves, rollback restores.

- **MFUMeter** — model FLOPs utilization from an analytic per-step
  FLOP count (`causal_lm_step_flops`, same convention as
  benchmark/models.py: 6 FLOPs per parameter per token for the dense
  path, ``6*B*T^2*D`` per layer for causal attention) against the
  per-platform peak table in benchmark/harness.py. On hosts where the
  peak is unknown (CPU) and no `PTPU_PEAK_FLOPS` override is set the
  meter registers nothing — the gauge is cleanly absent rather than
  lying.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterable, Optional

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry
from paddle_tpu.utils.log import add_event_tap, remove_event_tap

# worst-first: an attempt that both retried and rolled back is charged
# to the rollback (the retry time is subsumed by the larger failure)
SEVERITY = ("rollback", "preempt", "hang", "bad_step_skip",
            "ckpt_reject", "retry", "chaos_inject")


class GoodputLedger:
    """Attributes training wall time to productive work or a fault
    cause, fed by the resilience event stream (zero emit-site
    changes)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 stream: str = "resilience"):
        reg = registry if registry is not None else default_registry()
        self._stream = stream
        self._c_productive = reg.counter(
            "ptpu_goodput_productive_seconds_total",
            "Attempt time with no fault event observed")
        self._c_lost = reg.counter(
            "ptpu_goodput_lost_seconds_total",
            "Attempt/pause time charged to a fault or pause cause",
            labelnames=("cause",))
        self._c_events = reg.counter(
            "ptpu_goodput_events_total",
            "Resilience events seen by the goodput tap",
            labelnames=("cause",))
        self._g_goodput = reg.gauge(
            "ptpu_train_goodput",
            "productive seconds / (productive + lost) seconds")
        self._lock = threading.Lock()
        self._window: Optional[set] = None  # guarded-by: self._lock
        self._installed = False
        self._t_start: Optional[float] = None

    # -- event tap --------------------------------------------------------
    def install(self) -> "GoodputLedger":
        if not self._installed:
            add_event_tap(self._tap)
            self._installed = True
            self._t_start = time.perf_counter()
        return self

    def uninstall(self) -> None:
        if self._installed:
            remove_event_tap(self._tap)
            self._installed = False

    @property
    def installed(self) -> bool:
        return self._installed

    def _tap(self, stream: str, rec: Dict) -> None:
        if stream != self._stream:
            return
        evt = str(rec.get("evt", ""))
        if not evt:
            return
        self._c_events.labels(cause=evt).inc()
        with self._lock:
            if self._window is not None:
                self._window.add(evt)

    # -- time attribution -------------------------------------------------
    @contextlib.contextmanager
    def attempt(self):
        """One step attempt: productive unless a fault event lands
        inside the window."""
        with self._lock:
            self._window = set()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                causes = self._window or set()
                self._window = None
            cause = next((c for c in SEVERITY if c in causes), None)
            if cause is None and causes:
                cause = sorted(causes)[0]   # unknown event kinds still lose
            if cause is None:
                self._c_productive.inc(dt)
            else:
                self._c_lost.labels(cause=cause).inc(dt)
            self._update_gauge()

    @contextlib.contextmanager
    def pause(self, cause: str):
        """Non-attempt lost time with an explicit cause (checkpoint
        save, rollback restore)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._c_lost.labels(cause=cause).inc(time.perf_counter() - t0)
            self._update_gauge()

    def _update_gauge(self) -> None:
        p = self._c_productive.value
        lost = self._c_lost.total()
        self._g_goodput.set(p / (p + lost) if (p + lost) > 0 else 1.0)

    # -- accessors (tests, goodput_report) --------------------------------
    def goodput(self) -> float:
        return self._g_goodput.value

    def lost_seconds(self) -> Dict[str, float]:
        return {key[0]: child.value
                for key, child in self._c_lost.children().items()}

    def event_counts(self) -> Dict[str, float]:
        return {key[0]: child.value
                for key, child in self._c_events.children().items()}

    def productive_seconds(self) -> float:
        return self._c_productive.value

    def wall_seconds(self) -> float:
        if self._t_start is None:
            return 0.0
        return time.perf_counter() - self._t_start


# -- FLOPs accounting --------------------------------------------------------

def param_count(params) -> int:
    """Total trainable scalar count of a param pytree."""
    import jax
    return int(sum(getattr(leaf, "size", 0)
                   for leaf in jax.tree.leaves(params)))


def causal_lm_step_flops(*, batch_size: int, seq_len: int, d_model: int,
                         n_layers: int, n_params: int,
                         include_attention: bool = True) -> float:
    """Analytic train-step FLOPs for a causal transformer LM.

    Dense path: 6 FLOPs per parameter per token (fwd 2 + bwd 4).
    Attention: ``6 * B * T^2 * D`` per layer — same convention as
    benchmark/models.py's bench_causal_lm, so MFU numbers from the
    training telemetry and from BENCH_r* rows are comparable.
    """
    tokens = batch_size * seq_len
    flops = 6.0 * tokens * float(n_params)
    if include_attention:
        flops += 6.0 * batch_size * float(seq_len) ** 2 * d_model * n_layers
    return flops


def resolve_peak_flops(dtype_bits: int = 16) -> Optional[float]:
    """Peak FLOP/s for MFU: `PTPU_PEAK_FLOPS` env override first, then
    the per-platform table keyed by device_kind, else None (CPU)."""
    env = os.environ.get("PTPU_PEAK_FLOPS", "").strip()
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    from paddle_tpu.benchmark.harness import device_peak_flops
    return device_peak_flops(dtype_bits)


class MFUMeter:
    """Publishes `ptpu_train_mfu` from per-step wall time. Registers
    nothing when the platform peak is unknown (gauge cleanly absent on
    CPU) — callers can pass `peak_flops` explicitly to force it."""

    def __init__(self, flops_per_step: float,
                 peak_flops: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 alpha: float = 0.25):
        self._flops = float(flops_per_step or 0.0)
        self._peak = (peak_flops if peak_flops is not None
                      else resolve_peak_flops())
        self._alpha = alpha
        self._ema: Optional[float] = None
        self.enabled = bool(self._flops > 0 and self._peak)
        if self.enabled:
            reg = registry if registry is not None else default_registry()
            self._g_mfu = reg.gauge(
                "ptpu_train_mfu",
                "Model FLOPs utilization of the training step (0..1)")

    def observe_step(self, seconds: float) -> Optional[float]:
        """Feed one productive step's wall time; returns current MFU."""
        if not self.enabled or seconds <= 0:
            return None
        mfu = self._flops / (seconds * self._peak)
        self._ema = (mfu if self._ema is None
                     else self._alpha * mfu + (1 - self._alpha) * self._ema)
        self._g_mfu.set(self._ema)
        return self._ema

    @property
    def mfu(self) -> Optional[float]:
        return self._ema

"""Serving telemetry (OBSERVABILITY.md): metrics, tracing, exposition.

The layer every serving subsystem reports through:

- `metrics` — thread-safe Counter/Gauge/Histogram registry with label
  sets and log-bucketed quantiles; Prometheus text exposition +
  periodic `obs_snapshot` JSON lines on the shared event stream.
- `tracing` — per-request lifecycle spans (queued -> prefill ->
  decode, preemption re-entries), exported as Chrome trace and
  mergeable with the host profiler timeline.
- `http` — stdlib-only scrape server: `/metrics`, `/healthz`
  (liveness), `/readyz` (readiness callback), mountable extra routes.
- `slo` — SLOMonitor: objectives over the live registry, multi-window
  burn rates, `/slo` verdict — what admission control and the replica
  router consume.

ServeEngine / Scheduler / PagedKVCache and the resilience runtime
record into `default_registry()` unless constructed with an explicit
`registry=` (what serve_bench does to isolate its A/B cells).
"""

from paddle_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Snapshotter,
    default_registry,
    log_buckets,
)
from paddle_tpu.obs.tracing import RequestTracer, merged_chrome_trace
from paddle_tpu.obs.http import MetricsServer, json_route, obs_response
from paddle_tpu.obs.slo import SLOMonitor, SLOObjective, default_objectives

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "Snapshotter", "default_registry", "log_buckets",
    "RequestTracer", "merged_chrome_trace", "MetricsServer",
    "json_route", "obs_response",
    "SLOMonitor", "SLOObjective", "default_objectives",
]

"""Serving telemetry (OBSERVABILITY.md): metrics, tracing, exposition.

The layer every serving subsystem reports through:

- `metrics` — thread-safe Counter/Gauge/Histogram registry with label
  sets and log-bucketed quantiles; Prometheus text exposition +
  periodic `obs_snapshot` JSON lines on the shared event stream.
- `tracing` — per-request lifecycle spans (queued -> prefill ->
  decode, preemption re-entries), exported as Chrome trace and
  mergeable with the host profiler timeline.
- `http` — stdlib-only scrape server: `/metrics`, `/healthz`
  (liveness), `/readyz` (readiness callback), mountable extra routes.
- `slo` — SLOMonitor: objectives over the live registry, multi-window
  burn rates, `/slo` verdict — what admission control and the replica
  router consume.
- `fleetmetrics` — federation of per-replica expositions into one
  fleet-wide scrape body (`/metrics/fleet` on the router): counters
  sum, log-bucketed histograms merge exactly, gauges re-label per
  replica.
- `flightrec` — FlightRecorder: bounded ring of recent serve /
  resilience events plus an engine state snapshot, dumped as a
  postmortem JSON bundle on watchdog stall, SLO burn, drain timeout,
  or engine-loop crash.
- `goodput` — GoodputLedger (training goodput + per-cause lost time
  off the resilience event stream) and MFUMeter / analytic FLOPs
  helpers for `ptpu_train_mfu`.
- `devicemem` — DeviceMemoryMonitor: per-device HBM in-use and peak
  gauges, live-buffer fallback on CPU.
- `straggler` — StragglerDetector: cross-worker input-stall blame and
  step-time dispersion over scraped worker expositions.

ServeEngine / Scheduler / PagedKVCache and the resilience runtime
record into `default_registry()` unless constructed with an explicit
`registry=` (what serve_bench does to isolate its A/B cells).
"""

from paddle_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Snapshotter,
    default_registry,
    log_buckets,
)
from paddle_tpu.obs.tracing import (
    RequestTracer,
    merged_chrome_trace,
    stitch_fragments,
)
from paddle_tpu.obs.http import MetricsServer, json_route, obs_response
from paddle_tpu.obs.slo import SLOMonitor, SLOObjective, default_objectives
from paddle_tpu.obs.fleetmetrics import (
    counter_totals,
    federate,
    histogram_buckets,
    parse_exposition,
)
from paddle_tpu.obs.flightrec import FlightRecorder
from paddle_tpu.obs.goodput import (
    GoodputLedger,
    MFUMeter,
    causal_lm_step_flops,
    param_count,
    resolve_peak_flops,
)
from paddle_tpu.obs.devicemem import DeviceMemoryMonitor
from paddle_tpu.obs.straggler import StragglerDetector

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "Snapshotter", "default_registry", "log_buckets",
    "RequestTracer", "merged_chrome_trace", "stitch_fragments",
    "MetricsServer", "json_route", "obs_response",
    "SLOMonitor", "SLOObjective", "default_objectives",
    "counter_totals", "federate", "histogram_buckets", "parse_exposition",
    "FlightRecorder",
    "GoodputLedger", "MFUMeter", "causal_lm_step_flops", "param_count",
    "resolve_peak_flops",
    "DeviceMemoryMonitor", "StragglerDetector",
]

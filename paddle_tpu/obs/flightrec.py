"""Flight recorder: postmortem ring buffer + state snapshot dumps.

A long-lived serving replica fails in ways a live scrape cannot
explain after the fact: by the time an operator looks, the stalled
step, the queue that backed up, and the preemption storm that caused
it are gone. The flight recorder keeps the LAST `capacity` structured
events (the same `serve`/`resilience`/`obs` records utils/log.py
emits on stdout, captured via its event-tap hook — zero changes at
any emit site) in a bounded ring, and on a trigger dumps a single
JSON bundle:

    {"trigger": ..., "context": {...}, "events": [...ring...],
     "state": <snapshot_fn()>, "dumped_ts": <monotonic s>}

Triggers wired by the serve front-end (serve/frontend.py):
- watchdog stall       — RunSupervisor.on_hang fires mid-step;
- SLO burn             — the burn-rate monitor crosses threshold;
- drain deadline       — SIGTERM drain aborts still-active streams;
- engine-loop crash    — unhandled exception in the serve loop.

`snapshot_fn` is typically `ServeEngine.debug_state` — queue and
running set, block-pool occupancy, tier LRU summary. It is called
best-effort from WHATEVER thread triggered the dump (a watchdog
firing means the engine thread is wedged, so a locked snapshot could
never be taken); a snapshot that raises is recorded as an error
rather than losing the bundle.

Bundles write to `out_dir` (flightrec-<trigger>-<n>.json) and are
announced as an `obs_postmortem` event on the obs stream, so log
scrapers see the dump happen; the latest bundle is also held in
memory for the `/debug/flightrec` route.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence

from paddle_tpu.obs.metrics import MetricsRegistry
from paddle_tpu.utils.log import add_event_tap, obs_event, remove_event_tap


class FlightRecorder:
    """Bounded in-memory ring of recent events + triggered postmortem
    bundles. `install()` hooks the process-wide event streams; always
    `uninstall()` (or use as a context manager) so a torn-down replica
    does not keep recording."""

    def __init__(self, capacity: int = 512,
                 streams: Sequence[str] = ("serve", "resilience"),
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 out_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.capacity = int(capacity)
        self.streams = frozenset(streams)
        self.snapshot_fn = snapshot_fn
        self.out_dir = out_dir
        self._lock = threading.Lock()
        self._ring: Deque[dict] = deque(maxlen=self.capacity)  # guarded-by: self._lock
        self._dumps: List[str] = []          # guarded-by: self._lock
        self._last: Optional[dict] = None    # guarded-by: self._lock
        self._seq = 0                        # guarded-by: self._lock
        self._installed = False
        self._c_dumps = None
        if registry is not None:
            self._c_dumps = registry.counter(
                "ptpu_flightrec_dumps_total",
                "Flight-recorder postmortem bundles dumped", ("trigger",))

    # -- capture -----------------------------------------------------------
    def install(self) -> "FlightRecorder":
        if not self._installed:
            add_event_tap(self._tap)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            remove_event_tap(self._tap)
            self._installed = False

    @property
    def installed(self) -> bool:
        return self._installed

    def __enter__(self) -> "FlightRecorder":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    def _tap(self, stream: str, rec: dict) -> None:
        if stream not in self.streams:
            return
        with self._lock:
            self._ring.append({"stream": stream, **rec})

    def record(self, stream: str, evt: str, **fields) -> None:
        """Append an event to the ring directly (no stdout emission) —
        for components that want flight-recorder-only breadcrumbs."""
        rec = {"stream": stream, "evt": evt, **fields}
        rec["ts"] = round(time.monotonic(), 6)
        with self._lock:
            self._ring.append(rec)

    # -- postmortem --------------------------------------------------------
    def _snapshot(self) -> dict:
        if self.snapshot_fn is None:
            return {}
        try:
            return self.snapshot_fn()
        except Exception as e:  # snapshot is best-effort by design
            return {"snapshot_error": repr(e)}

    def dump(self, trigger: str, **context) -> dict:
        """Freeze the ring + a state snapshot into one bundle; write it
        to out_dir when configured and announce it on the obs stream.
        Safe to call from any thread, including a watchdog observing a
        wedged engine loop."""
        with self._lock:
            events = list(self._ring)
            self._seq += 1
            seq = self._seq
        bundle = {
            "trigger": trigger,
            "context": context,
            "events": events,
            "state": self._snapshot(),
            "dumped_ts": round(time.monotonic(), 6),
        }
        path = None
        if self.out_dir:
            try:
                os.makedirs(self.out_dir, exist_ok=True)
                path = os.path.join(
                    self.out_dir, f"flightrec-{trigger}-{seq}.json")
                with open(path, "w") as f:
                    json.dump(bundle, f, default=str)
            except OSError as e:
                bundle["write_error"] = repr(e)
                path = None
        if path:
            bundle["path"] = path
        with self._lock:
            self._last = bundle
            if path:
                self._dumps.append(path)
        if self._c_dumps is not None:
            self._c_dumps.labels(trigger=trigger).inc()
        obs_event("obs_postmortem", trigger=trigger,
                  path=path, events=len(events), **context)
        return bundle

    # -- reads -------------------------------------------------------------
    def ring(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def last_bundle(self) -> Optional[dict]:
        with self._lock:
            return self._last

    def dump_paths(self) -> List[str]:
        with self._lock:
            return list(self._dumps)

    def debug_payload(self) -> Dict[str, object]:
        """JSON body for /debug/flightrec: recorder config, dump
        inventory, and the latest bundle inline."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "streams": sorted(self.streams),
                "installed": self._installed,
                "ring_len": len(self._ring),
                "dumps": list(self._dumps),
                "last": self._last,
            }

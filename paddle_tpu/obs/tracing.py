"""Per-request lifecycle tracing for the serve engine.

Each request's life is a sequence of host-side SPANS —
queued -> prefill -> decode, re-entering queued on preemption — plus
instant marks (per prefill chunk, first token, preempt, done).
ServeEngine/Scheduler drive the transitions (engine/engine.py), and
the tracer turns them into:

- derived latencies (`durations_ms`) — what feeds the TTFT / TPOT /
  queue-wait / e2e histograms in the metrics registry;
- a Chrome-trace JSON (`to_chrome_trace`) with one trace-row (tid)
  per request, timestamped on the SAME epoch-anchored clock as the
  host profiler's spans (profiler.now_us), so
  `merged_chrome_trace()` lays request lifecycles and engine host
  spans on one chrome://tracing / perfetto timeline.

Completed requests are retained in a bounded deque (`keep_last`) so a
long-lived engine cannot leak trace state; live requests hold only
their own spans.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from paddle_tpu.profiler.profiler import now_us

# span names, in lifecycle order
QUEUED, PREFILL, DECODE = "queued", "prefill", "decode"


class RequestTracer:
    """Records span transitions per req_id; every hook is a no-op when
    `enabled` is False (flip at runtime — no engine restart)."""

    def __init__(self, keep_last: int = 2048, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: Dict[int, List[dict]] = {}     # guarded-by: self._lock
        self._open: Dict[int, dict] = {}             # guarded-by: self._lock
        self._done: Deque[Tuple[int, List[dict]]] = deque(maxlen=keep_last)  # guarded-by: self._lock

    # -- lifecycle hooks (engine-facing) ----------------------------------
    def on_enqueue(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._open_span(req_id, QUEUED)

    def on_admit(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._open_span(req_id, PREFILL)

    def on_chunk(self, req_id: int, start: int, length: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "chunk", start=start, length=length)

    def on_first_token(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "first_token")
            self._open_span(req_id, DECODE)

    def on_preempt(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "preempt")
            self._open_span(req_id, QUEUED)   # back to the wait queue

    def on_finish(self, req_id: int, reason: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "done", reason=reason)
            self._close_span(req_id)
            evs = self._events.pop(req_id, None)
            if evs is not None:
                self._done.append((req_id, evs))

    # -- internals (lock held) --------------------------------------------
    # requires-lock: self._lock
    def _open_span(self, req_id: int, name: str) -> None:
        self._close_span(req_id)
        ev = {"name": name, "ph": "X", "ts": now_us(), "dur": None}
        self._open[req_id] = ev
        self._events.setdefault(req_id, []).append(ev)

    # requires-lock: self._lock
    def _close_span(self, req_id: int) -> None:
        ev = self._open.pop(req_id, None)
        if ev is not None:
            ev["dur"] = now_us() - ev["ts"]

    # requires-lock: self._lock
    def _mark(self, req_id: int, name: str, **args) -> None:
        self._events.setdefault(req_id, []).append(
            {"name": name, "ph": "i", "ts": now_us(), "args": args})

    # -- reads ------------------------------------------------------------
    def _events_of(self, req_id: int) -> List[dict]:
        with self._lock:
            evs = list(self._events.get(req_id, ()))
            if not evs:
                for rid, done in self._done:
                    if rid == req_id:
                        evs = list(done)
            return evs

    def durations_ms(self, req_id: int) -> Dict[str, float]:
        """Total CLOSED-span wall time per phase (ms), summed across
        preemption re-entries; phases with no closed span are absent."""
        out: Dict[str, float] = {}
        for ev in self._events_of(req_id):
            if ev["ph"] == "X" and ev["dur"] is not None:
                out[ev["name"]] = out.get(ev["name"], 0.0) + ev["dur"] / 1e3
        return out

    def to_chrome_trace(self, pid: int = 1) -> dict:
        """Chrome trace: one tid per request, spans as 'X' (unfinished
        ones clipped to now), marks as thread-scoped instants."""
        with self._lock:
            per_req = [(rid, list(evs)) for rid, evs in self._done]
            per_req += [(rid, list(evs))
                        for rid, evs in sorted(self._events.items())]
        events: List[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "serve requests"}}]
        now = now_us()
        for rid, evs in per_req:
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": rid, "args": {"name": f"req {rid}"}})
            for ev in evs:
                if ev["ph"] == "X":
                    events.append({
                        "name": ev["name"], "ph": "X", "cat": "request",
                        "ts": ev["ts"],
                        "dur": ev["dur"] if ev["dur"] is not None
                        else now - ev["ts"],
                        "pid": pid, "tid": rid, "args": {}})
                else:
                    events.append({
                        "name": ev["name"], "ph": "i", "s": "t",
                        "cat": "request", "ts": ev["ts"],
                        "pid": pid, "tid": rid,
                        "args": ev.get("args", {})})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._open.clear()
            self._done.clear()

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


def merged_chrome_trace(tracer: RequestTracer,
                        include_host_spans: bool = True,
                        path: Optional[str] = None) -> dict:
    """Merge the request-lifecycle trace with the host profiler's
    recorded spans (profiler.get_events between start/stop_profiler)
    into ONE Chrome trace via the multi-process timeline merger —
    request rows and engine host spans share the epoch-anchored
    clock, so they line up without shifting."""
    from paddle_tpu.profiler.profiler import events_to_chrome_trace
    from paddle_tpu.profiler.timeline import Timeline

    tl = Timeline()
    if include_host_spans:
        tl.add_profile("engine host", events_to_chrome_trace())
    tl.add_profile("serve requests", tracer.to_chrome_trace())
    trace = tl.trace()
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace

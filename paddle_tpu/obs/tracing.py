"""Per-request lifecycle tracing for the serve engine.

Each request's life is a sequence of host-side SPANS —
queued -> prefill -> decode, re-entering queued on preemption — plus
instant marks (per prefill chunk, first token, preempt, done).
ServeEngine/Scheduler drive the transitions (engine/engine.py), and
the tracer turns them into:

- derived latencies (`durations_ms`) — what feeds the TTFT / TPOT /
  queue-wait / e2e histograms in the metrics registry;
- a Chrome-trace JSON (`to_chrome_trace`) with one trace-row (tid)
  per request, timestamped on the SAME epoch-anchored clock as the
  host profiler's spans (profiler.now_us), so
  `merged_chrome_trace()` lays request lifecycles and engine host
  spans on one chrome://tracing / perfetto timeline.

Completed requests are retained in a bounded deque (`keep_last`) so a
long-lived engine cannot leak trace state; live requests hold only
their own spans.

FLEET TRACING: a request that crosses processes (router -> replica)
carries an `x-ptpu-trace` header; each process tags its local req_id
with the fleet trace id via `set_trace_id`, and `trace_fragment(tid)`
exports just that request's spans (each span arg-tagged with the
trace id) as a standalone Chrome-trace fragment. The router's
/trace/<id> endpoint fetches every replica's fragment plus its own
relay spans and stitches them per-process with the timeline merger —
one trace id, one timeline, per-process pids. Because now_us() is
epoch-anchored, fragments from different processes line up without
clock shifting.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from paddle_tpu.profiler.profiler import now_us

# span names, in lifecycle order
QUEUED, PREFILL, DECODE = "queued", "prefill", "decode"


class RequestTracer:
    """Records span transitions per req_id; every hook is a no-op when
    `enabled` is False (flip at runtime — no engine restart)."""

    def __init__(self, keep_last: int = 2048, enabled: bool = True,
                 process_name: str = "serve requests"):
        self.enabled = enabled
        self.process_name = process_name
        self._lock = threading.Lock()
        self._events: Dict[int, List[dict]] = {}     # guarded-by: self._lock
        self._open: Dict[int, dict] = {}             # guarded-by: self._lock
        self._done: Deque[Tuple[int, List[dict]]] = deque(maxlen=keep_last)  # guarded-by: self._lock
        self._trace_of: Dict[int, str] = {}          # guarded-by: self._lock
        self._req_of: Dict[str, int] = {}            # guarded-by: self._lock

    # -- lifecycle hooks (engine-facing) ----------------------------------
    def on_enqueue(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._open_span(req_id, QUEUED)

    def on_admit(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._open_span(req_id, PREFILL)

    def on_chunk(self, req_id: int, start: int, length: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "chunk", start=start, length=length)

    def on_first_token(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "first_token")
            self._open_span(req_id, DECODE)

    def on_preempt(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "preempt")
            self._open_span(req_id, QUEUED)   # back to the wait queue

    def on_finish(self, req_id: int, reason: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, "done", reason=reason)
            self._close_span(req_id)
            evs = self._events.pop(req_id, None)
            if evs is not None:
                if len(self._done) == self._done.maxlen:
                    # the deque is about to evict its oldest entry —
                    # drop that request's trace-id mapping with it so
                    # the id maps stay bounded by keep_last too
                    old_rid, _ = self._done[0]
                    old_tid = self._trace_of.pop(old_rid, None)
                    if old_tid is not None:
                        self._req_of.pop(old_tid, None)
                self._done.append((req_id, evs))

    # -- fleet trace ids ---------------------------------------------------
    def set_trace_id(self, req_id: int, trace_id: str) -> None:
        """Tag a local request with the fleet-wide trace id it arrived
        with (`x-ptpu-trace`); idempotent, survives until the request
        is evicted from the done deque."""
        if not self.enabled or not trace_id:
            return
        with self._lock:
            self._trace_of[req_id] = trace_id
            self._req_of[trace_id] = req_id

    def trace_id_of(self, req_id: int) -> Optional[str]:
        with self._lock:
            return self._trace_of.get(req_id)

    def request_of_trace(self, trace_id: str) -> Optional[int]:
        with self._lock:
            return self._req_of.get(trace_id)

    # -- generic spans (router relay rows) ---------------------------------
    def span_begin(self, req_id: int, name: str) -> None:
        """Open an arbitrary named span (closing any open one) — what
        the router uses for its route/relay rows, where the lifecycle
        hooks above don't apply."""
        if not self.enabled:
            return
        with self._lock:
            self._open_span(req_id, name)

    def span_end(self, req_id: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._close_span(req_id)

    def mark(self, req_id: int, name: str, **args) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mark(req_id, name, **args)

    # -- internals (lock held) --------------------------------------------
    # requires-lock: self._lock
    def _open_span(self, req_id: int, name: str) -> None:
        self._close_span(req_id)
        ev = {"name": name, "ph": "X", "ts": now_us(), "dur": None}
        self._open[req_id] = ev
        self._events.setdefault(req_id, []).append(ev)

    # requires-lock: self._lock
    def _close_span(self, req_id: int) -> None:
        ev = self._open.pop(req_id, None)
        if ev is not None:
            ev["dur"] = now_us() - ev["ts"]

    # requires-lock: self._lock
    def _mark(self, req_id: int, name: str, **args) -> None:
        self._events.setdefault(req_id, []).append(
            {"name": name, "ph": "i", "ts": now_us(), "args": args})

    # -- reads ------------------------------------------------------------
    def _events_of(self, req_id: int) -> List[dict]:
        with self._lock:
            evs = list(self._events.get(req_id, ()))
            if not evs:
                for rid, done in self._done:
                    if rid == req_id:
                        evs = list(done)
            return evs

    def durations_ms(self, req_id: int) -> Dict[str, float]:
        """Total CLOSED-span wall time per phase (ms), summed across
        preemption re-entries; phases with no closed span are absent."""
        out: Dict[str, float] = {}
        for ev in self._events_of(req_id):
            if ev["ph"] == "X" and ev["dur"] is not None:
                out[ev["name"]] = out.get(ev["name"], 0.0) + ev["dur"] / 1e3
        return out

    def to_chrome_trace(self, pid: int = 1) -> dict:
        """Chrome trace: one tid per request, spans as 'X' (unfinished
        ones clipped to now), marks as thread-scoped instants. Spans of
        requests tagged with a fleet trace id carry it in args."""
        with self._lock:
            per_req = [(rid, list(evs)) for rid, evs in self._done]
            per_req += [(rid, list(evs))
                        for rid, evs in sorted(self._events.items())]
            trace_of = dict(self._trace_of)
        events: List[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": self.process_name}}]
        now = now_us()
        for rid, evs in per_req:
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": rid, "args": {"name": f"req {rid}"}})
            events.extend(self._chrome_events(
                rid, evs, pid, now, trace_of.get(rid)))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    @staticmethod
    def _chrome_events(rid: int, evs: List[dict], pid: int, now: float,
                       trace_id: Optional[str]) -> List[dict]:
        out: List[dict] = []
        span_args = {"trace_id": trace_id} if trace_id else {}
        for ev in evs:
            if ev["ph"] == "X":
                out.append({
                    "name": ev["name"], "ph": "X", "cat": "request",
                    "ts": ev["ts"],
                    "dur": ev["dur"] if ev["dur"] is not None
                    else now - ev["ts"],
                    "pid": pid, "tid": rid, "args": dict(span_args)})
            else:
                args = dict(ev.get("args", {}))
                args.update(span_args)
                out.append({
                    "name": ev["name"], "ph": "i", "s": "t",
                    "cat": "request", "ts": ev["ts"],
                    "pid": pid, "tid": rid, "args": args})
        return out

    def trace_fragment(self, trace_id: str, pid: int = 1) -> Optional[dict]:
        """Standalone Chrome-trace fragment for ONE fleet trace id —
        what a replica serves on /trace/<id> and the router stitches
        into the cross-process timeline. None when the id is unknown
        here (the router treats that as 'not my request')."""
        with self._lock:
            rid = self._req_of.get(trace_id)
            if rid is None:
                return None
            evs = list(self._events.get(rid, ()))
            if not evs:
                for drid, done in self._done:
                    if drid == rid:
                        evs = list(done)
        events: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": self.process_name}},
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": rid,
             "args": {"name": f"req {rid}"}},
        ]
        events.extend(self._chrome_events(rid, evs, pid, now_us(), trace_id))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "trace_id": trace_id, "req_id": rid}

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._open.clear()
            self._done.clear()
            self._trace_of.clear()
            self._req_of.clear()

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


def merged_chrome_trace(tracer: RequestTracer,
                        include_host_spans: bool = True,
                        path: Optional[str] = None) -> dict:
    """Merge the request-lifecycle trace with the host profiler's
    recorded spans (profiler.get_events between start/stop_profiler)
    into ONE Chrome trace via the multi-process timeline merger —
    request rows and engine host spans share the epoch-anchored
    clock, so they line up without shifting."""
    from paddle_tpu.profiler.profiler import events_to_chrome_trace
    from paddle_tpu.profiler.timeline import Timeline

    tl = Timeline()
    if include_host_spans:
        tl.add_profile("engine host", events_to_chrome_trace())
    tl.add_profile("serve requests", tracer.to_chrome_trace())
    trace = tl.trace()
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def stitch_fragments(fragments: List[Tuple[str, dict]],
                     trace_id: Optional[str] = None) -> dict:
    """Stitch per-process trace fragments (label, chrome-trace dict)
    into ONE Chrome trace with a distinct pid per process — the
    router's /trace/<id> body. Fragments share the epoch-anchored
    clock, so no time shifting is needed; the timeline merger re-pids
    each profile and keeps thread_name metadata."""
    from paddle_tpu.profiler.timeline import Timeline

    tl = Timeline()
    for label, frag in fragments:
        if frag:
            tl.add_profile(label, frag)
    trace = tl.trace()
    if trace_id:
        trace["trace_id"] = trace_id
    return trace

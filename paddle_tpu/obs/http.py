"""Stdlib-only HTTP scrape endpoint for the metrics registry.

One small ThreadingHTTPServer (no third-party deps — the container
rule) serving:

- `GET /metrics`  -> Prometheus text exposition 0.0.4 of the bound
  registry (obs/metrics.py render_prometheus);
- `GET /healthz`  -> `ok` (liveness for a replica router / k8s probe).

`port=0` binds an ephemeral port (read it back from `.port` — what
tests use); the server runs on a daemon thread so it can never hold a
draining process open. A scrape renders under the registry locks
child-by-child, so it is safe concurrent with the serve loop's
recording — that is the point: pull-based exposition without pausing
the engine.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """`with MetricsServer(registry, port=9090) as srv:` or
    start()/stop(); `srv.url` is the scrape address."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry if registry is not None \
            else default_registry()
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsServer":
        if self._server is not None:
            return self
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):                           # noqa: N802 (stdlib)
                if self.path.split("?")[0] == "/metrics":
                    body = registry.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                elif self.path == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):               # silence stderr
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ptpu-metrics-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

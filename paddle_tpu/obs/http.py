"""Stdlib-only HTTP scrape endpoint for the metrics registry.

One small ThreadingHTTPServer (no third-party deps — the container
rule) serving the observability surface every replica exposes:

- `GET /metrics` -> Prometheus text exposition 0.0.4 of the bound
  registry (obs/metrics.py render_prometheus);
- `GET /healthz` -> `ok` — pure LIVENESS: the process is up and can
  answer a socket. Never consults engine state, so a draining or
  still-compiling replica is alive, just not ready;
- `GET /readyz` -> READINESS: 200 only when the bound `readiness`
  callback says so (the serve front-end reports not-ready until the
  engine's one compiled step is warm, and again once a drain begins),
  503 with the reason in the body otherwise. Routers and k8s probes
  gate on THIS one; a replica failing /readyz but passing /healthz is
  cold or draining, not dead;
- any extra mounted route (e.g. `/slo` -> the SLOMonitor verdict JSON,
  obs/slo.py) via `routes={path: callable -> (status, ctype, body)}`;
- parameterised routes (e.g. `/trace/<id>`) via
  `prefix_routes={prefix: callable(path) -> (status, ctype, body)}` —
  exact routes win, then the longest matching prefix gets the FULL
  path so it can parse the tail itself.

`port=0` binds an ephemeral port (read it back from `.port` — what
tests use); the server runs on a daemon thread so it can never hold a
draining process open. A scrape renders under the registry locks
child-by-child, so it is safe concurrent with the serve loop's
recording — that is the point: pull-based exposition without pausing
the engine.

`obs_response()` is the routing logic factored out of the server so
the serve front-end (serve/frontend.py), which multiplexes these paths
with its own /v1/* API on ONE port, answers byte-identically to a
standalone MetricsServer.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# (status, content-type, body)
Response = Tuple[int, str, bytes]
# readiness callback: (ready, reason) — reason lands in the 503 body
Readiness = Callable[[], Tuple[bool, str]]


def json_route(fn: Callable[[], dict]) -> Callable[[], Response]:
    """Wrap a dict-producing callable (e.g. SLOMonitor.verdict) as a
    mountable JSON route."""
    def route() -> Response:
        return 200, "application/json", (
            json.dumps(fn()) + "\n").encode()
    return route


def obs_response(path: str, registry: MetricsRegistry,
                 readiness: Optional[Readiness] = None,
                 routes: Optional[Dict[str, Callable[[], Response]]] = None,
                 prefix_routes: Optional[
                     Dict[str, Callable[[str], Response]]] = None
                 ) -> Optional[Response]:
    """Answer one observability GET; None when the path is not ours
    (the caller 404s or falls through to its own API)."""
    path = path.split("?")[0]
    if routes and path in routes:
        return routes[path]()
    if prefix_routes:
        for pfx in sorted(prefix_routes, key=len, reverse=True):
            if path.startswith(pfx):
                return prefix_routes[pfx](path)
    if path == "/metrics":
        return 200, CONTENT_TYPE, registry.render_prometheus().encode()
    if path == "/healthz":
        return 200, "text/plain", b"ok\n"
    if path == "/readyz":
        if readiness is None:
            return 200, "text/plain", b"ready\n"
        ready, reason = readiness()
        if ready:
            return 200, "text/plain", b"ready\n"
        return 503, "text/plain", f"not ready: {reason}\n".encode()
    return None


class MetricsServer:
    """`with MetricsServer(registry, port=9090) as srv:` or
    start()/stop(); `srv.url` is the scrape address. `readiness` gates
    /readyz; `routes` mounts extra GET paths (e.g. /slo)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 readiness: Optional[Readiness] = None,
                 routes: Optional[Dict[str, Callable[[], Response]]] = None,
                 prefix_routes: Optional[
                     Dict[str, Callable[[str], Response]]] = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.host = host
        self.port = port
        self.readiness = readiness
        self.routes = dict(routes or {})
        self.prefix_routes = dict(prefix_routes or {})
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsServer":
        if self._server is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):                           # noqa: N802 (stdlib)
                resp = obs_response(self.path, outer.registry,
                                    outer.readiness, outer.routes,
                                    outer.prefix_routes)
                if resp is None:
                    resp = (404, "text/plain", b"not found\n")
                status, ctype, body = resp
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):               # silence stderr
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ptpu-metrics-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

The serving telemetry core (OBSERVABILITY.md): every subsystem the serve
path crosses — ServeEngine, Scheduler, PagedKVCache, the resilience
runtime — records into one thread-safe registry, and three exposition
paths read it back out:

1. `MetricsRegistry.render_prometheus()` — Prometheus text format
   (the `/metrics` scrape body, obs/http.py serves it);
2. `MetricsRegistry.emit_snapshot()` — one `obs_snapshot` single-line
   JSON record on stdout through the unified event emitter
   (utils/log.py), so the existing log-scraping consumers (subprocess
   tests, serve_bench, operators tailing a pod log) get periodic
   metric state with zero extra infrastructure; `Snapshotter` runs it
   on an interval thread;
3. direct reads (`.value`, `.quantile(q)`, `.mean()`) — what
   tools/serve_bench.py verdicts and tests/test_obs.py key off.

Histograms are LOG-BUCKETED: bounds grow geometrically (default 10
buckets per decade across 1e-3..1e7, sized for millisecond latencies),
so one fixed ~100-int array covers microseconds to hours with a
bounded RELATIVE quantile error — the p50/p90/p99 estimate
log-interpolates inside the landing bucket and clamps to the observed
min/max, so the worst-case error is one bucket's growth factor
(~26%), and far less on smooth distributions. That is the right trade
for latency SLOs, where 5ms vs 6ms matters but 500ms vs 630ms is the
same outage.

Hot-path discipline: a counter inc is one lock + one float add, a
histogram observe is a bisect + two adds; nothing here ever touches
jax or device state, so instrumentation can never add a compile or a
device sync (the one-compile invariant serve_bench's mixed scenario
guards stays intact with metrics on).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from paddle_tpu.utils.log import obs_event


def log_buckets(lo: float = 1e-3, hi: float = 1e7,
                per_decade: int = 10) -> Tuple[float, ...]:
    """Geometric bucket bounds: `per_decade` buckets per power of ten
    spanning [lo, hi]. Relative width of each bucket is
    10**(1/per_decade) (~1.26 at the default), which bounds the
    worst-case quantile estimation error."""
    k0 = round(math.log10(lo) * per_decade)
    k1 = round(math.log10(hi) * per_decade)
    return tuple(10.0 ** (k / per_decade) for k in range(k0, k1 + 1))


DEFAULT_BUCKETS = log_buckets()


def _fmt(v: float) -> str:
    """Compact float rendering for exposition ('0.001', '2', '1e+07')."""
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: Sequence[str], values: Sequence[str],
               extra: str = "") -> str:
    parts = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


# -- children (one per label-value set) -------------------------------------

class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0               # guarded-by: self._lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class _GaugeChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0               # guarded-by: self._lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class _HistogramChild:
    """Fixed log-bucket histogram; `observe` is O(log buckets)."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count",
                 "_min", "_max")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds           # immutable after construction
        self._counts = [0] * (len(bounds) + 1)   # guarded-by: self._lock
        self._sum = 0.0                 # guarded-by: self._lock
        self._count = 0                 # guarded-by: self._lock
        self._min = math.inf            # guarded-by: self._lock
        self._max = -math.inf           # guarded-by: self._lock

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def minimum(self) -> float:
        """Smallest observed value (nan when empty)."""
        with self._lock:
            return self._min if self._count else math.nan

    @property
    def maximum(self) -> float:
        """Largest observed value (nan when empty)."""
        with self._lock:
            return self._max if self._count else math.nan

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else math.nan

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (q in [0, 1]) from the buckets:
        find the bucket holding rank q*count, then log-interpolate
        between its bounds, clamped to the observed min/max. Relative
        error is bounded by one bucket's growth factor."""
        with self._lock:
            if not self._count:
                return math.nan
            counts = list(self._counts)
            total, mn, mx = self._count, self._min, self._max
        rank = min(max(q, 0.0), 1.0) * total
        cum = 0
        idx, in_bucket = len(counts) - 1, 1
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                idx, in_bucket = i, c
                break
            cum += c
        lo = self._bounds[idx - 1] if idx > 0 else mn
        hi = self._bounds[idx] if idx < len(self._bounds) else mx
        lo, hi = max(lo, mn), min(hi, mx)
        if hi <= lo:
            return lo
        frac = min(max((rank - cum) / in_bucket, 0.0), 1.0)
        if lo > 0:
            return lo * (hi / lo) ** frac       # geometric interpolation
        return lo + (hi - lo) * frac

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """CUMULATIVE (le, count) pairs, Prometheus-style, ending +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for bound, c in zip(self._bounds + (math.inf,), counts):
            cum += c
            out.append((bound, cum))
        return out

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = math.inf
            self._max = -math.inf


# -- families (name + label schema; children per label-value set) -----------

class _Family:
    """One named metric; labelled children are created on first use and
    cached by label VALUES (kwargs order never matters), so
    `m.labels(a="x", b="y") is m.labels(b="y", a="x")`. A family with
    no labelnames proxies the single default child's methods."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}  # guarded-by: self._lock
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()")
        return self._children[()]

    def children(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._children)

    def _reset(self) -> None:
        for child in self.children().values():
            child._reset()

    # -- exposition -------------------------------------------------------
    def _header(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines

    def render(self) -> List[str]:
        lines = self._header()
        for key in sorted(self.children()):
            lines.extend(self._render_child(key, self._children[key]))
        return lines

    def _render_child(self, key, child) -> List[str]:
        lbl = _label_str(self.labelnames, key)
        return [f"{self.name}{lbl} {_fmt(child.value)}"]


class Counter(_Family):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value

    def total(self) -> float:
        """Sum over every labelled child."""
        return sum(c.value for c in self.children().values())


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Iterable[float]] = None):
        self._bounds = tuple(sorted(buckets)) if buckets is not None \
            else DEFAULT_BUCKETS
        if not self._bounds:
            raise ValueError(f"{name}: need at least one bucket bound")
        super().__init__(name, help, labelnames)

    def _new_child(self):
        return _HistogramChild(self._bounds)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def mean(self) -> float:
        return self._default().mean()

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)

    # aggregates over all labelled children (bench verdict helpers)
    def total_count(self) -> int:
        return sum(c.count for c in self.children().values())

    def total_sum(self) -> float:
        return sum(c.sum for c in self.children().values())

    def max_value(self) -> float:
        vals = [c.maximum for c in self.children().values() if c.count]
        return max(vals) if vals else math.nan

    def _render_child(self, key, child) -> List[str]:
        lines = []
        for bound, cum in child.bucket_counts():
            lbl = _label_str(self.labelnames, key,
                             extra=f'le="{_fmt(bound)}"')
            lines.append(f"{self.name}_bucket{lbl} {cum}")
        lbl = _label_str(self.labelnames, key)
        lines.append(f"{self.name}_sum{lbl} {_fmt(child.sum)}")
        lines.append(f"{self.name}_count{lbl} {child.count}")
        return lines


# -- the registry -----------------------------------------------------------

class MetricsRegistry:
    """Thread-safe name -> metric-family map with get-or-create
    accessors (re-registering the same name returns the SAME family —
    two ServeEngines sharing the process registry share its series —
    and a kind/label-schema mismatch fails loud)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Family] = {}  # guarded-by: self._lock

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Family:
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                fam = self._metrics[name] = cls(
                    name, help=help, labelnames=labelnames, **kw)
                return fam
        if not isinstance(fam, cls):
            raise ValueError(f"{name} already registered as {fam.kind}")
        if fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"{name} already registered with labels {fam.labelnames}, "
                f"asked for {tuple(labelnames)}")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Zero every child IN PLACE (handles held by instrumented code
        stay valid) — the post-warmup reset serve_bench and
        ServeEngine.reset_stats() use."""
        with self._lock:
            fams = list(self._metrics.values())
        for fam in fams:
            fam._reset()

    # -- exposition -------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 (the /metrics body)."""
        with self._lock:
            fams = sorted(self._metrics.values(), key=lambda f: f.name)
        lines: List[str] = []
        for fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, object]:
        """Flat JSON-able view: counters/gauges as values, histograms as
        {count, sum, mean, p50, p90, p99, max}. Labelled children key
        as name{a=x,b=y}."""
        with self._lock:
            fams = sorted(self._metrics.values(), key=lambda f: f.name)
        out: Dict[str, object] = {}
        for fam in fams:
            for key, child in sorted(fam.children().items()):
                k = fam.name + ("{" + ",".join(
                    f"{n}={v}" for n, v in zip(fam.labelnames, key)) + "}"
                    if key else "")
                if fam.kind == "histogram":
                    if not child.count:
                        out[k] = {"count": 0}
                        continue
                    out[k] = {
                        "count": child.count,
                        "sum": round(child.sum, 6),
                        "mean": round(child.mean(), 6),
                        "p50": round(child.quantile(0.5), 6),
                        "p90": round(child.quantile(0.9), 6),
                        "p99": round(child.quantile(0.99), 6),
                        "max": round(child.maximum, 6),
                    }
                else:
                    out[k] = round(child.value, 6)
        return out

    def emit_snapshot(self, **extra) -> dict:
        """One `obs_snapshot` single-line JSON record on stdout via the
        unified event emitter (ts/seq stamped like every stream)."""
        return obs_event("obs_snapshot", metrics=self.snapshot(), **extra)


class Snapshotter:
    """Daemon thread emitting `registry.emit_snapshot()` every
    `interval_s`; `with Snapshotter(reg, 10):` or start()/stop()."""

    def __init__(self, registry: MetricsRegistry, interval_s: float = 10.0):
        self.registry = registry
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Snapshotter":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ptpu-obs-snapshot")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.registry.emit_snapshot()

    def stop(self, final_snapshot: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_snapshot:
            self.registry.emit_snapshot()

    def __enter__(self) -> "Snapshotter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem records into unless
    handed an explicit one (ServeEngine/PagedKVCache take registry=)."""
    return _DEFAULT

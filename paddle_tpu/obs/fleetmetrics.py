"""Fleet-level federation of per-replica Prometheus expositions.

The router scrapes every replica's `/metrics` body and serves ONE
merged exposition on `/metrics/fleet`, so serve_bench and operators
read fleet-wide series without per-replica math:

- **counters** sum exactly across replicas per label set — a fleet
  total equals the sum of the per-replica scrapes by construction;
- **histograms** merge by summing the per-`le` cumulative bucket
  counts plus `_sum`/`_count`. Every replica builds its histograms
  from the same `DEFAULT_BUCKETS` layout (obs/metrics.py), so the
  bucket edges line up and the merge is exact — quantiles estimated
  from the merged buckets are the same as quantiles over the pooled
  observations, up to the usual one-bucket interpolation error;
- **gauges** do NOT sum meaningfully (occupancy is per-process), so
  each child is re-labelled with a `replica` label and exposed
  side by side.

Everything here is pure text -> text: the parser understands the
0.0.4 exposition format obs/metrics.py renders (HELP/TYPE headers,
escaped label values, `_bucket`/`_sum`/`_count` histogram children)
and the renderer re-emits the same format, so a fleet exposition is
scrapeable by the same consumers (sse.parse_prometheus_values,
serve_bench quantile helpers) as a single replica's.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from paddle_tpu.obs.metrics import _escape, _fmt

LabelSet = Tuple[Tuple[str, str], ...]   # sorted (name, value) pairs


class ParsedFamily:
    """One metric family parsed out of an exposition body."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str = "untyped", help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        # (suffix, labels, value): suffix is "", "_bucket", "_sum",
        # or "_count"; labels EXCLUDE `le` for buckets (it rides the
        # labels of the sample line but is split out by the parser)
        self.samples: List[Tuple[str, LabelSet, Optional[str], float]] = []


def _parse_labels(body: str) -> Dict[str, str]:
    """Parse `a="x",b="y"` honouring \\" and \\\\ escapes."""
    out: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.index("=", i)
        name = body[i:eq].strip().lstrip(",").strip()
        i = eq + 1
        if i >= n or body[i] != '"':
            raise ValueError(f"bad label value at {body[i:]!r}")
        i += 1
        chars: List[str] = []
        while i < n:
            c = body[i]
            if c == "\\" and i + 1 < n:
                nxt = body[i + 1]
                chars.append({"n": "\n"}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            chars.append(c)
            i += 1
        out[name] = "".join(chars)
        while i < n and body[i] in ", ":
            i += 1
    return out


def _parse_sample(line: str) -> Tuple[str, Dict[str, str], float]:
    """`name{labels} value` -> (name, labels, value)."""
    if "{" in line:
        name, rest = line.split("{", 1)
        body, tail = rest.rsplit("}", 1)
        labels = _parse_labels(body) if body else {}
        value = float(tail.strip())
    else:
        name, tail = line.split(None, 1)
        labels = {}
        value = float(tail.strip())
    return name.strip(), labels, value


def parse_exposition(text: str) -> Dict[str, ParsedFamily]:
    """Parse one Prometheus 0.0.4 text body into families keyed by
    family name (histogram `_bucket`/`_sum`/`_count` samples fold into
    the histogram family declared by its `# TYPE` line)."""
    fams: Dict[str, ParsedFamily] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("TYPE", "HELP"):
                name = parts[2]
                fam = fams.setdefault(name, ParsedFamily(name))
                if parts[1] == "TYPE":
                    fam.kind = parts[3].strip() if len(parts) > 3 \
                        else "untyped"
                else:
                    fam.help = parts[3] if len(parts) > 3 else ""
            continue
        sample_name, labels, value = _parse_sample(line)
        fam, suffix = _resolve_family(fams, sample_name)
        le = labels.pop("le", None) if suffix == "_bucket" else None
        key: LabelSet = tuple(sorted(labels.items()))
        fam.samples.append((suffix, key, le, value))
    return fams


def _resolve_family(fams: Dict[str, ParsedFamily],
                    sample_name: str) -> Tuple[ParsedFamily, str]:
    if sample_name in fams:
        return fams[sample_name], ""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            fam = fams.get(base)
            if fam is not None and fam.kind == "histogram":
                return fam, suffix
    return fams.setdefault(sample_name, ParsedFamily(sample_name)), ""


def _le_sort_key(le: str) -> float:
    return math.inf if le == "+Inf" else float(le)


def _render_labels(key: LabelSet, extra: str = "") -> str:
    parts = [f'{n}="{_escape(v)}"' for n, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def federate(expositions: Dict[str, str]) -> str:
    """Merge `{replica_label: exposition_text}` into one fleet-wide
    exposition. Counters/histograms aggregate across replicas per
    label set; gauges (and untyped samples) gain a `replica` label
    and stay per-replica."""
    parsed = {rep: parse_exposition(text)
              for rep, text in expositions.items()}
    # family name -> (kind, help), first declaration wins
    meta: Dict[str, Tuple[str, str]] = {}
    for fams in parsed.values():
        for name, fam in fams.items():
            if name not in meta or meta[name][0] == "untyped":
                meta[name] = (fam.kind, fam.help)

    lines: List[str] = []
    for name in sorted(meta):
        kind, help_text = meta[name]
        per_rep = [(rep, parsed[rep].get(name))
                   for rep in sorted(parsed)]
        per_rep = [(rep, fam) for rep, fam in per_rep
                   if fam is not None and fam.samples]
        if not per_rep:
            continue
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "counter":
            lines.extend(_merge_sums(name, per_rep))
        elif kind == "histogram":
            lines.extend(_merge_histograms(name, per_rep))
        else:
            lines.extend(_label_by_replica(name, per_rep))
    return "\n".join(lines) + ("\n" if lines else "")


def _merge_sums(name: str,
                per_rep: List[Tuple[str, ParsedFamily]]) -> List[str]:
    totals: Dict[LabelSet, float] = {}
    for _, fam in per_rep:
        for suffix, key, _, value in fam.samples:
            if suffix:
                continue
            totals[key] = totals.get(key, 0.0) + value
    return [f"{name}{_render_labels(key)} {_fmt(totals[key])}"
            for key in sorted(totals)]


def _merge_histograms(name: str,
                      per_rep: List[Tuple[str, ParsedFamily]]
                      ) -> List[str]:
    buckets: Dict[LabelSet, Dict[str, float]] = {}
    sums: Dict[LabelSet, float] = {}
    counts: Dict[LabelSet, float] = {}
    for _, fam in per_rep:
        for suffix, key, le, value in fam.samples:
            if suffix == "_bucket" and le is not None:
                b = buckets.setdefault(key, {})
                b[le] = b.get(le, 0.0) + value
            elif suffix == "_sum":
                sums[key] = sums.get(key, 0.0) + value
            elif suffix == "_count":
                counts[key] = counts.get(key, 0.0) + value
    lines: List[str] = []
    for key in sorted(buckets):
        for le in sorted(buckets[key], key=_le_sort_key):
            lbl = _render_labels(key, extra=f'le="{le}"')
            lines.append(f"{name}_bucket{lbl} {_fmt(buckets[key][le])}")
        lbl = _render_labels(key)
        lines.append(f"{name}_sum{lbl} {_fmt(sums.get(key, 0.0))}")
        lines.append(
            f"{name}_count{lbl} {_fmt(counts.get(key, 0.0))}")
    return lines


def _label_by_replica(name: str,
                      per_rep: List[Tuple[str, ParsedFamily]]
                      ) -> List[str]:
    lines: List[str] = []
    for rep, fam in per_rep:
        for suffix, key, _, value in fam.samples:
            if suffix:
                continue
            merged: LabelSet = tuple(sorted(
                dict(key, replica=rep).items()))
            lines.append(f"{name}{_render_labels(merged)} {_fmt(value)}")
    return lines


def counter_totals(text: str) -> Dict[str, float]:
    """{family: summed value across label sets} for every counter in
    an exposition — the equality check serve_bench's fleet-obs cell
    runs between /metrics/fleet and the per-replica scrapes.
    Declaration-only families (a TYPE/HELP header whose labelled
    children have never incremented render no sample lines) are
    omitted, mirroring federate(), which drops them from the fleet
    body."""
    out: Dict[str, float] = {}
    for name, fam in parse_exposition(text).items():
        if fam.kind != "counter":
            continue
        values = [v for sfx, _, _, v in fam.samples if not sfx]
        if values:
            out[name] = sum(values)
    return out


def histogram_buckets(text: str, family: str) -> Dict[str, float]:
    """Per-`le` cumulative counts for one histogram family, summed
    over label sets — exact-merge comparison helper."""
    fam = parse_exposition(text).get(family)
    if fam is None:
        return {}
    out: Dict[str, float] = {}
    for suffix, _, le, value in fam.samples:
        if suffix == "_bucket" and le is not None:
            out[le] = out.get(le, 0.0) + value
    return out

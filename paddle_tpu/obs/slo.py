"""SLO monitor: objectives evaluated against the live metrics registry.

Observability as a CONTROL PLANE (OBSERVABILITY.md §SLOs): the serving
front-end doesn't guess whether it is overloaded — it asks the same
latency histograms a `/metrics` scrape exposes. An `SLOObjective` is a
statement like "99% of requests see TTFT <= 200ms"; the monitor turns
the registry's log-bucketed histograms into per-objective BURN RATES
and a machine-readable verdict, and the front-end sheds load while the
verdict says `burning` (serve/frontend.py admission control).

Burn rate is the SRE-workbook quantity: the fraction of requests that
violated the objective in a window, divided by the error budget
(1 - target). burn == 1.0 means "violations arriving exactly at the
rate the budget tolerates"; burn == 10 means the budget for the whole
window is gone in a tenth of it. The monitor evaluates burn over TWO
windows (multi-window alerting): the SHORT window makes shedding react
within seconds of an overload, the LONG window keeps one straggler
request from flapping the verdict — `burning` requires BOTH to exceed
the threshold, and recovery is immediate once the short window drains.

Windowing works on SNAPSHOT DELTAS, not cumulative counts: `tick()`
(called on an interval thread or inline by the front-end) records each
objective histogram's (total, violating) cumulative counts; a window's
burn is the delta between now and the sample one window ago. The
histograms are cumulative and monotone, so deltas are exact — no
per-request state, and a scrape-side consumer could compute the same
number from two `/metrics` pulls.

Violation counting uses the histogram's own buckets: the threshold is
rounded DOWN to a bucket bound, so the violating count is never
underestimated (an SLO that errs, errs strict — by at most one bucket's
growth factor, ~26% at the default resolution).

Everything the monitor concludes is re-exported as gauges
(`ptpu_slo_burn_rate{objective,window}`, `ptpu_slo_burning{objective}`,
`ptpu_slo_ok`) so dashboards and the replica router read verdicts from
the ordinary scrape, and as a JSON verdict served at `GET /slo`
(obs/http.py route; serve/frontend.py mounts it).
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from paddle_tpu.obs.metrics import Histogram, MetricsRegistry


@dataclass(frozen=True)
class SLOObjective:
    """`target` fraction of observations of `metric` must be <=
    `threshold_ms`. The error budget is 1 - target."""
    name: str                 # short label ("ttft", "tpot", "queue_wait")
    metric: str               # histogram family name in the registry
    threshold_ms: float
    target: float = 0.99

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"{self.name}: target must be in (0, 1), "
                             f"got {self.target}")
        if self.threshold_ms <= 0:
            raise ValueError(f"{self.name}: threshold_ms must be > 0")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def default_objectives(ttft_ms: float = 500.0, tpot_ms: float = 200.0,
                       queue_wait_ms: float = 1000.0,
                       target: float = 0.99) -> List[SLOObjective]:
    """The serving objectives every replica watches by default, over
    the engine's own histogram names (engine/engine.py)."""
    return [
        SLOObjective("ttft", "ptpu_serve_ttft_ms", ttft_ms, target),
        SLOObjective("tpot", "ptpu_serve_tpot_ms", tpot_ms, target),
        SLOObjective("queue_wait", "ptpu_serve_queue_wait_ms",
                     queue_wait_ms, target),
    ]


@dataclass
class _Sample:
    ts: float
    total: int
    bad: int


class SLOMonitor:
    """Evaluates objectives against `registry` on every `tick()`.

    `burning(name)` / `any_burning()` are what admission control keys
    off; `verdict()` is the `/slo` body. Thread-safe: tick() may run on
    an interval thread while HTTP handlers read verdicts.
    """

    def __init__(self, registry: MetricsRegistry,
                 objectives: Optional[List[SLOObjective]] = None,
                 short_window_s: float = 5.0,
                 long_window_s: float = 60.0,
                 burn_threshold: float = 1.0,
                 min_samples: int = 4):
        if short_window_s <= 0 or long_window_s < short_window_s:
            raise ValueError(
                f"need 0 < short_window_s <= long_window_s, got "
                f"{short_window_s}/{long_window_s}")
        self.registry = registry
        self.objectives = list(objectives if objectives is not None
                               else default_objectives())
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.short_window_s = short_window_s
        self.long_window_s = long_window_s
        self.burn_threshold = burn_threshold
        # below this many new observations in the short window the
        # verdict holds OK: a single slow request on an idle replica is
        # not an outage, and shedding needs evidence
        self.min_samples = min_samples
        self._lock = threading.Lock()
        self._history: Dict[str, Deque[_Sample]] = {
            o.name: deque() for o in self.objectives}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # verdict gauges: the scrape-visible face of the monitor
        self._g_burn = registry.gauge(
            "ptpu_slo_burn_rate",
            "Error-budget burn rate per objective and window "
            "(1.0 = budget consumed exactly at tolerance)",
            labelnames=("objective", "window"))
        self._g_burning = registry.gauge(
            "ptpu_slo_burning",
            "1 when an objective burns in BOTH windows (sheds load)",
            labelnames=("objective",))
        self._g_threshold = registry.gauge(
            "ptpu_slo_threshold_ms", "Configured objective threshold",
            labelnames=("objective",))
        self._g_ok = registry.gauge(
            "ptpu_slo_ok", "1 when no objective is burning")
        for o in self.objectives:
            self._g_threshold.labels(objective=o.name).set(o.threshold_ms)
            self._g_burning.labels(objective=o.name).set(0.0)
        self._g_ok.set(1.0)

    # -- sampling ---------------------------------------------------------
    def _counts(self, obj: SLOObjective) -> Tuple[int, int]:
        """Cumulative (total, violating) for one objective, summed over
        the histogram's labelled children. The threshold rounds down to
        a bucket bound so `bad` is never underestimated."""
        fam = self.registry.get(obj.metric)
        if fam is None or not isinstance(fam, Histogram):
            return 0, 0
        total = bad = 0
        for child in fam.children().values():
            pairs = child.bucket_counts()      # cumulative (le, count)
            if not pairs:
                continue
            n = pairs[-1][1]
            bounds = [le for le, _ in pairs]
            # last bound <= threshold: everything above it counts bad
            i = bisect.bisect_right(bounds, obj.threshold_ms) - 1
            good = pairs[i][1] if i >= 0 else 0
            total += n
            bad += n - good
        return total, bad

    def tick(self, now: Optional[float] = None) -> None:
        """Record one snapshot per objective and refresh the verdict
        gauges. Call on an interval (start()) or inline from the serve
        loop — both work; more ticks only sharpen the windows."""
        ts = time.monotonic() if now is None else now
        with self._lock:
            for obj in self.objectives:
                total, bad = self._counts(obj)
                hist = self._history[obj.name]
                # a registry reset (warmup baseline) rewinds the
                # cumulative counts; stale pre-reset samples would read
                # as negative deltas — drop them
                while hist and hist[-1].total > total:
                    hist.pop()
                hist.append(_Sample(ts, total, bad))
                horizon = ts - self.long_window_s - 1.0
                while len(hist) > 2 and hist[1].ts <= horizon:
                    hist.popleft()
        self._refresh_gauges(ts)

    def _window_burn(self, obj: SLOObjective, window_s: float,
                     now: float) -> Tuple[float, int]:
        """(burn rate, observations) over the trailing window — delta
        between the newest sample and the newest sample at least
        `window_s` old (or the oldest retained)."""
        hist = self._history[obj.name]
        if not hist:
            return 0.0, 0
        latest = hist[-1]
        base = hist[0]
        for s in reversed(hist):
            if now - s.ts >= window_s:
                base = s
                break
        total = latest.total - base.total
        bad = latest.bad - base.bad
        if total <= 0:
            return 0.0, 0
        return (bad / total) / obj.budget, total

    def _evaluate_locked(self, now: float) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for obj in self.objectives:
            short, n_short = self._window_burn(obj, self.short_window_s,
                                               now)
            long_, n_long = self._window_burn(obj, self.long_window_s, now)
            burning = (n_short >= self.min_samples
                       and short >= self.burn_threshold
                       and long_ >= self.burn_threshold)
            out[obj.name] = {
                "metric": obj.metric,
                "threshold_ms": obj.threshold_ms,
                "target": obj.target,
                "burn_short": round(short, 4),
                "burn_long": round(long_, 4),
                "window_short_s": self.short_window_s,
                "window_long_s": self.long_window_s,
                "observations_short": n_short,
                "burning": burning,
            }
        return out

    def _refresh_gauges(self, now: float) -> None:
        with self._lock:
            ev = self._evaluate_locked(now)
        ok = True
        for name, st in ev.items():
            self._g_burn.labels(objective=name, window="short").set(
                st["burn_short"])
            self._g_burn.labels(objective=name, window="long").set(
                st["burn_long"])
            self._g_burning.labels(objective=name).set(
                1.0 if st["burning"] else 0.0)
            ok = ok and not st["burning"]
        self._g_ok.set(1.0 if ok else 0.0)

    # -- verdicts ---------------------------------------------------------
    def burning(self, name: str) -> bool:
        with self._lock:
            ev = self._evaluate_locked(time.monotonic())
        return ev[name]["burning"]

    def burning_objectives(self) -> List[str]:
        """Names of objectives currently burning (admission control
        sheds with the FIRST one as the labeled reason)."""
        with self._lock:
            ev = self._evaluate_locked(time.monotonic())
        return [n for n, st in ev.items() if st["burning"]]

    def any_burning(self) -> bool:
        return bool(self.burning_objectives())

    def verdict(self) -> dict:
        """The machine-readable `/slo` body: per-objective burn rates,
        thresholds, and the overall ok bit — same numbers the
        `ptpu_slo_*` gauges expose."""
        with self._lock:
            ev = self._evaluate_locked(time.monotonic())
        return {"ok": not any(st["burning"] for st in ev.values()),
                "burn_threshold": self.burn_threshold,
                "objectives": ev}

    # -- interval thread --------------------------------------------------
    def start(self, interval_s: float = 1.0) -> "SLOMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        # synchronous baseline sample: without it, traffic completing
        # before the first interval tick would be invisible (the first
        # sample would already contain it and every delta would be 0)
        self.tick()

        def _run():
            while not self._stop.wait(interval_s):
                self.tick()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="ptpu-slo-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "SLOMonitor":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

"""Device (HBM) memory telemetry.

Polls per-device memory into two gauge families so a training scrape
shows the footprint and the high-water mark the way a replica scrape
shows KV occupancy:

- `ptpu_hbm_bytes_in_use{device=}` — current allocated bytes;
- `ptpu_hbm_peak_bytes{device=}` — peak watermark.

Source of truth is the runtime's own `Device.memory_stats()` when the
backend implements it (TPU/GPU: `bytes_in_use`, `peak_bytes_in_use`).
CPU backends generally don't, so the monitor degrades to summing the
live `jax.Array` buffers per device (`jax.live_arrays()`) and tracks
its own peak across samples — the gauges stay populated, just from
host-side accounting instead of allocator truth. `sample()` is an
explicit poll (cheap, no device sync); callers decide the cadence.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from paddle_tpu.obs.metrics import MetricsRegistry, default_registry


def _stats_for(dev) -> Optional[Dict[str, float]]:
    fn = getattr(dev, "memory_stats", None)
    if fn is None:
        return None
    try:
        stats = fn()
    except Exception:
        return None
    if not stats or "bytes_in_use" not in stats:
        return None
    return stats


def _live_bytes_by_device() -> Dict[object, int]:
    totals: Dict[object, int] = {}
    try:
        arrays = jax.live_arrays()
    except Exception:
        return totals
    for arr in arrays:
        try:
            for shard in arr.addressable_shards:
                dev = shard.device
                nbytes = getattr(shard.data, "nbytes", 0)
                totals[dev] = totals.get(dev, 0) + int(nbytes)
        except Exception:
            continue
    return totals


class DeviceMemoryMonitor:
    """Per-device HBM gauges with allocator stats when available and a
    live-buffer fallback otherwise."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 devices=None):
        reg = registry if registry is not None else default_registry()
        self._g_bytes = reg.gauge(
            "ptpu_hbm_bytes_in_use",
            "Current allocated device memory bytes",
            labelnames=("device",))
        self._g_peak = reg.gauge(
            "ptpu_hbm_peak_bytes",
            "Peak allocated device memory bytes seen",
            labelnames=("device",))
        self._devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        self._own_peak: Dict[str, float] = {}
        #: True once any sampled device reported allocator stats
        self.allocator_backed = False

    def sample(self) -> Dict[str, Dict[str, float]]:
        """Poll every device; update gauges; return
        {device_label: {"bytes_in_use": .., "peak_bytes": ..}}."""
        live = None
        out: Dict[str, Dict[str, float]] = {}
        for dev in self._devices:
            label = f"d{dev.id}"
            stats = _stats_for(dev)
            if stats is not None:
                self.allocator_backed = True
                in_use = float(stats["bytes_in_use"])
                peak = float(stats.get("peak_bytes_in_use", in_use))
            else:
                if live is None:
                    live = _live_bytes_by_device()
                in_use = float(live.get(dev, 0))
                peak = max(self._own_peak.get(label, 0.0), in_use)
            self._own_peak[label] = max(self._own_peak.get(label, 0.0),
                                        peak)
            peak = self._own_peak[label]
            self._g_bytes.labels(device=label).set(in_use)
            self._g_peak.labels(device=label).set(peak)
            out[label] = {"bytes_in_use": in_use, "peak_bytes": peak}
        return out

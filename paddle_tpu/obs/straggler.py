"""Cross-worker straggler detection for multi-process dp training.

In an SPMD dp job the collectives run the workers in lock step: a slow
worker stalls *everyone's* step, so per-worker total step time is
useless for blame — every worker's `ptpu_train_step_ms` inflates
identically. What stays local is the **host input stall**: the wall
time a worker spends producing/feeding its batch before it joins the
collective (`ptpu_train_input_wait_ms`, timed around `batch_for` in
train_resilient). A worker whose input wait dwarfs the fleet baseline
is the straggler, even though step times agree.

The detector consumes raw `/metrics` exposition bodies (one per
worker, scraped from each worker's MetricsServer), reuses
`obs.fleetmetrics.parse_exposition` for the per-worker stats and
`obs.fleetmetrics.federate` for the merged fleet body, and publishes:

- `ptpu_train_straggler{worker=}` — 1.0 when that worker's mean input
  wait exceeds `ratio` x the fleet baseline (median for >= 3 workers,
  min for 2), else 0.0;
- `ptpu_train_step_dispersion` — max/min of per-worker mean step
  time, the lock-step sanity check (should sit near 1.0 in dp).
"""

from __future__ import annotations

from typing import Dict, Optional

from paddle_tpu.obs.fleetmetrics import federate, parse_exposition
from paddle_tpu.obs.metrics import MetricsRegistry, default_registry


def _family_mean(fams, name: str) -> Optional[float]:
    """sum/count over every label set of one histogram family."""
    fam = fams.get(name)
    if fam is None:
        return None
    total = count = 0.0
    for suffix, _, _, value in fam.samples:
        if suffix == "_sum":
            total += value
        elif suffix == "_count":
            count += value
    return (total / count) if count else None


def _baseline(values) -> float:
    vals = sorted(values)
    if len(vals) >= 3:
        mid = len(vals) // 2
        if len(vals) % 2:
            return vals[mid]
        return 0.5 * (vals[mid - 1] + vals[mid])
    return vals[0]


class StragglerDetector:
    """Flags dp workers whose input stall leaves the fleet baseline."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 ratio: float = 2.0, min_gap_ms: float = 5.0,
                 wait_family: str = "ptpu_train_input_wait_ms",
                 step_family: str = "ptpu_train_step_ms"):
        reg = registry if registry is not None else default_registry()
        self._g_straggler = reg.gauge(
            "ptpu_train_straggler",
            "1 when the worker's input wait exceeds ratio x baseline",
            labelnames=("worker",))
        self._g_dispersion = reg.gauge(
            "ptpu_train_step_dispersion",
            "max/min of per-worker mean step time")
        self.ratio = ratio
        # sub-ms jitter between healthy workers must not trip the flag:
        # a straggler must beat the baseline by ratio AND by a real gap
        self.min_gap_ms = min_gap_ms
        self.wait_family = wait_family
        self.step_family = step_family

    def update(self, expositions: Dict[str, str]) -> Dict[str, Dict]:
        """Feed {worker: exposition_text}; update gauges; return
        {worker: {input_wait_ms, step_ms, straggler}}."""
        parsed = {w: parse_exposition(t) for w, t in expositions.items()}
        waits = {w: _family_mean(f, self.wait_family)
                 for w, f in parsed.items()}
        steps = {w: _family_mean(f, self.step_family)
                 for w, f in parsed.items()}

        known_waits = [v for v in waits.values() if v is not None]
        base = _baseline(known_waits) if known_waits else None
        out: Dict[str, Dict] = {}
        for worker in sorted(parsed):
            wait = waits.get(worker)
            slow = bool(base is not None and wait is not None
                        and wait > self.ratio * max(base, 1e-9)
                        and wait - base > self.min_gap_ms)
            self._g_straggler.labels(worker=worker).set(
                1.0 if slow else 0.0)
            out[worker] = {"input_wait_ms": wait,
                           "step_ms": steps.get(worker),
                           "straggler": slow}

        known_steps = [v for v in steps.values() if v is not None and v > 0]
        if known_steps:
            self._g_dispersion.set(max(known_steps) / min(known_steps))
        return out

    def fleet_exposition(self, expositions: Dict[str, str]) -> str:
        """Merged fleet body for the aggregator's own /metrics/fleet —
        counters/histograms sum exactly, gauges gain a replica label."""
        return federate(expositions)

"""On-hardware flash-attention correctness gate.

CI exercises the Pallas kernels in interpret mode (CPU); the only place
they execute on a real TPU is the benchmark. A wrong-but-fast kernel
would ship silently, so the bench calls `flash_selfcheck()` on the real
device: it runs the flash path and the XLA reference path on the same
batch — forward AND backward — asserts the flash branch was actually
taken, and compares numerics (VERDICT r2 weak #2 / next-step #2).
"""

from __future__ import annotations

# graftlint: skip-file=EH001 — this module IS the assert: an on-device
# correctness gate whose whole contract is raising AssertionError (the
# bench and tests/test_flash_selfcheck.py catch it by type).

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.kernels import attention as A
from paddle_tpu.utils.flags import FLAGS


def flash_selfcheck(batch: int = 2, heads: int = 4, seq: int = 1024,
                    head_dim: int = 64, causal: bool = True,
                    dtype=jnp.bfloat16, atol: float = 5e-2) -> Dict:
    """Compare flash vs reference attention fwd+bwd on one batch.

    Returns {"flash_check": "ok", "max_err": ...} or raises AssertionError.
    Tolerance is bf16-scale: both paths use fp32 softmax/accumulation, so
    outputs agree to bf16 rounding.
    """
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(batch, seq, heads, head_dim), dtype) * 0.3
    k = jnp.asarray(rs.randn(batch, seq, heads, head_dim), dtype) * 0.3
    v = jnp.asarray(rs.randn(batch, seq, heads, head_dim), dtype) * 0.3

    # 1. the dispatch gate must choose flash for this shape on this device
    from paddle_tpu.kernels import flash as flash_mod
    taken = {"flash": False}
    orig = flash_mod.flash_attention

    def spy(*args, **kw):
        taken["flash"] = True
        return orig(*args, **kw)

    flash_mod.flash_attention, spy_token = spy, None
    try:
        def loss_flash(q, k, v):
            return jnp.sum(A.mha(q, k, v, causal=causal).astype(jnp.float32)
                           ** 2)

        f_out = A.mha(q, k, v, causal=causal)
        f_grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    finally:
        flash_mod.flash_attention = orig
    assert taken["flash"], (
        "flash_selfcheck: dispatch gate did NOT take the flash path "
        f"(platform={jax.devices()[0].platform}, "
        f"flag={FLAGS.get('flash_attention')})")

    # 2. reference path on the same batch
    def loss_ref(q, k, v):
        return jnp.sum(A.reference_attention(
            q, k, v, mask=_causal_mask(seq) if causal else None)
            .astype(jnp.float32) ** 2)

    r_out = A.reference_attention(
        q, k, v, mask=_causal_mask(seq) if causal else None)
    r_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)

    max_rel = 0.0
    for a, b in zip((f_out, *f_grads), (r_out, *r_grads)):
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        scale = float(jnp.max(jnp.abs(b))) + 1e-6
        max_rel = max(max_rel, float(jnp.max(jnp.abs(a - b))) / scale)
    assert max_rel < atol, (
        f"flash_selfcheck: flash vs reference mismatch: max relative "
        f"error {max_rel:.4f} (tol {atol})")

    # 3. segment-id (packed-batch) masking on hardware: block-sparse
    # skipping must not change values vs the dense masked reference
    segs = np.zeros((batch, seq), np.int32)
    segs[:, seq // 3:] = 1
    segs[:, 2 * seq // 3:] = 2
    segs_j = jnp.asarray(segs)
    s_out = A.mha(q, k, v, causal=causal, segment_ids=segs_j)
    smask = (segs_j[:, None, :, None] == segs_j[:, None, None, :])
    if causal:
        smask = jnp.logical_and(smask, _causal_mask(seq))
    s_ref = A.reference_attention(q, k, v, mask=smask)
    seg_err = float(jnp.max(jnp.abs(s_out.astype(jnp.float32)
                                    - s_ref.astype(jnp.float32)))) / (
        float(jnp.max(jnp.abs(s_ref.astype(jnp.float32)))) + 1e-6)
    assert seg_err < atol, (
        f"flash_selfcheck: segment-id path mismatch: {seg_err:.4f}")

    # 4. in-kernel dropout: deterministic per key, ~rate zeros, and the
    # no-dropout average is recovered in expectation (loose bound)
    key = jax.random.PRNGKey(3)
    d1 = A.mha(q, k, v, causal=causal, dropout_rate=0.5,
               dropout_rng=key)
    d2 = A.mha(q, k, v, causal=causal, dropout_rate=0.5,
               dropout_rng=key)
    drop_det = float(jnp.max(jnp.abs(d1.astype(jnp.float32)
                                     - d2.astype(jnp.float32))))
    assert drop_det == 0.0, (
        f"flash_selfcheck: dropout not deterministic per key: {drop_det}")
    assert not np.allclose(np.asarray(d1, np.float32),
                           np.asarray(f_out, np.float32)), (
        "flash_selfcheck: dropout_rate=0.5 did not change the output "
        "(in-kernel dropout is not being applied)")

    return {"flash_check": "ok", "flash_max_rel_err": round(max_rel, 5),
            "flash_seg_rel_err": round(seg_err, 5),
            "flash_platform": jax.devices()[0].platform}


def _causal_mask(t: int):
    return (jnp.arange(t)[None, :] <= jnp.arange(t)[:, None])[None, None]

"""Flash attention in Pallas (TPU) — forward AND backward kernels.

The Pallas tier is this framework's analog of the reference's hand-fused
CUDA/JIT kernels (operators/fused/, operators/jit/): XLA fuses most things,
but attention's softmax-rescaling loop is the canonical case where a custom
kernel beats the compiler by keeping the [Tq, Tk] score matrix out of HBM.

Design (TPU-idiomatic, layout [BH, T, D]):
- Forward: grid (bh, q_blocks, k_blocks); the k dimension is sequential
  ("arbitrary" semantics) and K/V stream through VMEM one block at a time —
  VMEM holds O(block_q*D + block_k*D), never the full K/V. Online-softmax
  state (running max m, denom l, accumulator) lives in VMEM scratch that
  persists across the sequential k steps. Also emits the log-sum-exp
  residual (lane-broadcast, the standard TPU layout) for the backward pass.
- Backward: two recompute kernels wired through jax.custom_vjp (pallas_call
  has no autodiff rule). dq streams K/V blocks per q block; dk/dv streams
  Q/dO blocks per k block. Both recompute p = exp(s - lse) from the saved
  lse instead of storing the [Tq, Tk] probability matrix.

Structured masking (all handled block-wise, never as a dense [Tq, Tk]
tensor):
- `causal` + `kv_len` right-padding, as before;
- `segment_ids` — packed ragged batches (the reference's LoD→dense packing
  idiom, lod_tensor.h:44-58; SURVEY §5.7): tokens attend only within their
  own segment. Blocks whose q/kv segment ranges do not overlap are SKIPPED
  (block-sparse), so a packed batch of short documents costs
  ~sum(len_i^2), not T^2.
- `dropout_rate` — in-kernel attention dropout via a stateless integer
  hash (murmur3 finalizer) on (seed, batch*head, q_pos, k_pos). Using
  global positions makes the keep-mask identical in the forward and both
  backward kernels regardless of block shape, with no [Tq, Tk] mask
  materialized. The softmax denominator uses UNdropped probabilities
  (dropout applies after normalization, matching the XLA reference path's
  bernoulli-on-probs semantics); only the accumulator sees dropped ones.

Only arbitrary dense masks fall back to the XLA reference path in
kernels/attention.py.

On CPU (tests) runs in interpret mode so forward and backward numerics are
validated against reference_attention without TPU hardware.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds; interpret mode needs no TPU.
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30
LANES = 128     # f32 lane width: m/l/lse scratch is lane-broadcast
SUBLANES = 8    # kv segment ids ride the sublane dim: [B, SUBLANES, Tk]

# Defaults are resolved adaptively in flash_attention() (None = choose by
# sequence length). Measured on v5e (bf16, causal, fwd+bwd): large square
# blocks win at moderate T ((512,512): 3.5x over (128,128) at T=1024,
# 4.8x over XLA dense); (256,512) wins at T>=4096. Small blocks
# under-fill the MXU and pay per-iteration scratch/loop overhead.
DEFAULT_BLOCK_Q = None
DEFAULT_BLOCK_K = None


def normalize_segment_ids(segment_ids, b: int, t_q: int, t_k: int):
    """Normalize the segment_ids argument shared by the flash and dense
    attention paths: a [B, T] array (self-attention, ids shared by q and
    kv) or a (q_seg [B, Tq], kv_seg [B, Tk]) pair -> (q_seg, kv_seg)
    int32, shape-checked. One helper so the two dispatch paths of the
    same semantic contract cannot drift."""
    if isinstance(segment_ids, (tuple, list)):
        q_seg, kv_seg = segment_ids
    else:
        q_seg = kv_seg = segment_ids
    q_seg = q_seg.astype(jnp.int32)
    kv_seg = kv_seg.astype(jnp.int32)
    if q_seg.shape != (b, t_q) or kv_seg.shape != (b, t_k):
        raise ValueError(
            f"segment_ids shapes {q_seg.shape}/{kv_seg.shape} do not "
            f"match q [{b},{t_q}] / kv [{b},{t_k}]")
    return q_seg, kv_seg


def _default_blocks(t_q: int, t_k: int):
    # v5e-measured: (512,512) best at T<=2048 (2.91 ms @1024/bs16);
    # (1024,1024) best at long T — the round-5 roofline sweep
    # (tools/flash_roofline.py, ceiling-relative): fwd 85.9% of the
    # same-day sustained-matmul rate at 16k vs 70.7% for the previous
    # (512,1024) default (arithmetic intensity 334 vs 204 FLOP/B —
    # comfortably compute-bound either way; the win is fewer grid steps
    # amortizing per-block scratch/loop overhead).
    if t_k > 2048:
        return 1024, 1024
    return 512, 512


def _scratch(shape):
    if _VMEM is None:  # pragma: no cover
        raise RuntimeError(
            "Pallas TPU support unavailable in this jax build; force the "
            "XLA reference path with FLAGS_flash_attention=0")
    return _VMEM(shape, jnp.float32)


def _compiler_params(*semantics):
    if pltpu is None:  # pragma: no cover
        return None
    # jax <= 0.4.x spells it TPUCompilerParams; newer jax CompilerParams
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(dimension_semantics=semantics)


def _smem_spec():
    """Whole-array scalar input (the dropout seed) in SMEM."""
    if pltpu is None:  # pragma: no cover
        return pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))
    return pl.BlockSpec(memory_space=pltpu.SMEM)


# --------------------------------------------------------------------------
# Stateless in-kernel dropout: murmur3-finalizer hash of
# (seed, bh, q_pos, k_pos). Global positions => the keep-mask is identical
# across the forward and both backward kernels by construction, independent
# of block shape.
# --------------------------------------------------------------------------

def _mix32(x):
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _dropout_keep(seed, bh, q_start, k_start, shape, rate: float):
    """Boolean keep-mask [BQ, BK]; P(drop) = rate (to within 2^-32)."""
    qpos = (q_start + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
            ).astype(jnp.uint32)
    kpos = (k_start + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
            ).astype(jnp.uint32)
    key = _mix32(seed.astype(jnp.uint32)
                 + bh.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D))
    u = _mix32((qpos * jnp.uint32(0x9E3779B1)
                + kpos * jnp.uint32(0x85EBCA77)) ^ key)
    return u >= jnp.uint32(rate * 4294967296.0)


def _block_mask(s, q_start, k_start, *, causal: bool, limit: Optional[int],
                q_seg=None, kv_seg=None):
    """Apply causal / length-bound / segment masking to a [BQ, BK] block.

    q_seg: [BQ, 1] int32; kv_seg: [1, BK] int32 (or both None)."""
    bq, bk = s.shape
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if causal:
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        s = jnp.where(kpos <= qpos, s, NEG_INF)
    if limit is not None:
        # Bounds every block: covers kv_len right-padding AND the ragged
        # final block when t_k % block_k != 0 (pl.ds clamping would
        # otherwise double-count tail rows).
        s = jnp.where(kpos < limit, s, NEG_INF)
    if q_seg is not None:
        s = jnp.where(q_seg == kv_seg, s, NEG_INF)
    return s


def _seg_block(qseg_ref, kseg_ref):
    """[BQ, 1] and [1, BK] segment-id slices from the lane/sublane-broadcast
    block refs (or (None, None))."""
    if qseg_ref is None:
        return None, None
    return qseg_ref[...][:, :1], kseg_ref[...][:1, :]


def _contributes(causal, q_start, k_start, block_q, q_seg, kv_seg):
    """Block-skip predicate: fully-above-diagonal causal blocks and blocks
    with no segment overlap contribute nothing to the online softmax (m, l,
    acc unchanged), so their compute is skipped. Segment skipping is what
    makes packed ragged batches cost ~sum(len_i^2) instead of T^2."""
    pred = True
    if causal:
        pred = k_start <= q_start + block_q - 1
    if q_seg is not None:
        overlap = jnp.any(q_seg == kv_seg)
        pred = overlap if pred is True else jnp.logical_and(pred, overlap)
    return pred


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _fwd_kernel(*refs, scale: float, causal: bool, block_q: int,
                block_k: int, limit: Optional[int], want_lse: bool,
                has_segs: bool, dropout_rate: float):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    qseg_ref = next(it) if has_segs else None
    kseg_ref = next(it) if has_segs else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    o_ref = next(it)
    lse_ref = next(it) if want_lse else None
    m_scr, l_scr, acc_scr = next(it), next(it), next(it)

    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_seg, kv_seg = _seg_block(qseg_ref, kseg_ref)

    @pl.when(_contributes(causal, q_start, k_start, block_q, q_seg, kv_seg))
    def _compute():
        # Matmul inputs stay in the storage dtype (bf16 on the training
        # path) so the MXU runs at bf16 rate; accumulation and all softmax
        # state are fp32 via preferred_element_type. Casting q/k/v to fp32
        # here ran the dots at fp32 rate — 4x slower on v5e (round-3 fix).
        q = q_ref[...]                                   # [BQ, D]
        k = k_ref[...]                                   # [BK, D]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK] f32
        s = _block_mask(s, q_start, k_start, causal=causal, limit=limit,
                        q_seg=q_seg, kv_seg=kv_seg)

        m_prev = m_scr[...][:, :1]                       # [BQ, 1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        # l (the softmax denominator) accumulates UNdropped p: dropout
        # applies to normalized probabilities, after the softmax.
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, q_start, k_start,
                                 p.shape, dropout_rate)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        acc_scr[...] = alpha * acc_scr[...] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        m = m_scr[...][:, :1]
        l = l_scr[...][:, :1]
        o_ref[...] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype)
        if lse_ref is not None:
            lse = m + jnp.log(jnp.maximum(l, 1e-30))
            lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _expand_segs(q_seg, kv_seg):
    """[B, Tq] / [B, Tk] int32 -> lane-broadcast [B, Tq, LANES] and
    sublane-broadcast [B, SUBLANES, Tk] (the standard TPU layouts for
    per-row / per-column scalars)."""
    b, tq = q_seg.shape
    tk = kv_seg.shape[1]
    qs = jax.lax.broadcast_in_dim(q_seg, (b, tq, LANES), (0, 1))
    ks = jax.lax.broadcast_in_dim(kv_seg, (b, SUBLANES, tk), (0, 2))
    return qs, ks


def _seg_specs(heads: int, block_q: int, block_k: int, *, q_axis, k_axis):
    """BlockSpecs for the expanded segment-id arrays. Segment ids are per
    BATCH element while the grid's axis 0 is the flattened batch*heads, so
    the index maps divide by `heads`. q_axis/k_axis pick which grid axis
    (1 or 2) indexes q blocks vs k blocks (the dkv kernel swaps them)."""
    def qmap(b, i, j):
        g = (b, i, j)
        return (b // heads, g[q_axis], 0)

    def kmap(b, i, j):
        g = (b, i, j)
        return (b // heads, 0, g[k_axis])

    return (pl.BlockSpec((None, block_q, LANES), qmap),
            pl.BlockSpec((None, SUBLANES, block_k), kmap))


def _fwd(q, k, v, q_seg, kv_seg, seed, scale, causal, kv_len, block_q,
         block_k, interpret, want_lse, dropout_rate, heads):
    """q/k/v: [BH, T, D], T a multiple of the block size (flash_attention
    pads) -> (o [BH, Tq, D], lse [BH, Tq, LANES] f32 | None).

    want_lse=False (inference/eval) skips the lse residual output — it is
    only needed by the backward kernels and its HBM writes can exceed the
    attention output itself at small head dims."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    has_segs = q_seg is not None
    grid = (bh, pl.cdiv(t_q, block_q), pl.cdiv(t_k, block_k))
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, limit=kv_len, want_lse=want_lse,
        has_segs=has_segs, dropout_rate=dropout_rate)
    o_spec = pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0))
    o_shape = jax.ShapeDtypeStruct((bh, t_q, d), q.dtype)
    in_specs = [
        o_spec,
        pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    inputs = [q, k, v]
    if has_segs:
        qs, ks = _expand_segs(q_seg, kv_seg)
        qspec, kspec = _seg_specs(heads, block_q, block_k, q_axis=1,
                                  k_axis=2)
        in_specs += [qspec, kspec]
        inputs += [qs, ks]
    if dropout_rate > 0.0:
        in_specs.append(_smem_spec())
        inputs.append(seed)
    out_specs = [o_spec]
    out_shape = [o_shape]
    if want_lse:
        out_specs.append(
            pl.BlockSpec((None, block_q, LANES), lambda b, i, j: (b, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((bh, t_q, LANES), jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _scratch((block_q, LANES)),
            _scratch((block_q, LANES)),
            _scratch((block_q, d)),
        ],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(*inputs)
    return (out[0], out[1]) if want_lse else (out[0], None)


# --------------------------------------------------------------------------
# Backward: dq kernel (stream K/V per q block), dk/dv kernel (stream Q/dO
# per k block). Standard flash recompute: p = exp(q·kᵀ·scale − lse).
# With dropout, ds_ij = p_ij (keep_ij·dp_ij/(1-r) − delta_i) and dv uses
# g_ij = keep_ij·p_ij/(1-r) — the delta_i = Σ do·o trick still holds
# because o already includes the dropout.
# --------------------------------------------------------------------------

def _dq_kernel(*refs, scale: float, causal: bool, block_q: int,
               block_k: int, limit: Optional[int], has_segs: bool,
               dropout_rate: float):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    do_ref, o_ref, lse_ref = next(it), next(it), next(it)
    qseg_ref = next(it) if has_segs else None
    kseg_ref = next(it) if has_segs else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dq_ref = next(it)
    dq_scr = next(it)

    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q_seg, kv_seg = _seg_block(qseg_ref, kseg_ref)

    @pl.when(_contributes(causal, q_start, k_start, block_q, q_seg, kv_seg))
    def _compute():
        # bf16 matmul inputs + fp32 accumulation (see _fwd_kernel note)
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = jnp.max(lse_ref[...], axis=1, keepdims=True)  # lanes equal
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _block_mask(s, q_start, k_start, causal=causal, limit=limit,
                        q_seg=q_seg, kv_seg=kv_seg)
        p = jnp.exp(s - lse)                                # [BQ, BK] f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BQ, BK]
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, q_start, k_start,
                                 p.shape, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        do_f = do.astype(jnp.float32)
        o = o_ref[...].astype(jnp.float32)
        delta = jnp.sum(do_f * o, axis=1, keepdims=True)    # [BQ, 1]
        ds = p * (dp - delta)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale: float, causal: bool, block_q: int,
                block_k: int, limit: Optional[int], has_segs: bool,
                dropout_rate: float):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    do_ref, o_ref, lse_ref = next(it), next(it), next(it)
    qseg_ref = next(it) if has_segs else None
    kseg_ref = next(it) if has_segs else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dk_ref, dv_ref = next(it), next(it)
    dk_scr, dv_scr = next(it), next(it)

    bh, ki, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    q_start, k_start = qi * block_q, ki * block_k

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_seg, kv_seg = _seg_block(qseg_ref, kseg_ref)

    @pl.when(_contributes(causal, q_start, k_start, block_q, q_seg, kv_seg))
    def _compute():
        # bf16 matmul inputs + fp32 accumulation (see _fwd_kernel note)
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = jnp.max(lse_ref[...], axis=1, keepdims=True)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [BQ, BK]
        s = _block_mask(s, q_start, k_start, causal=causal, limit=limit,
                        q_seg=q_seg, kv_seg=kv_seg)
        p = jnp.exp(s - lse)
        keep = None
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref[0, 0], bh, q_start, k_start,
                                 p.shape, dropout_rate)
            g = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            g = p
        g_lo = g.astype(do.dtype)
        dv_scr[...] += jax.lax.dot_general(
            g_lo, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BQ, BK]
        if keep is not None:
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        do_f = do.astype(jnp.float32)
        o = o_ref[...].astype(jnp.float32)
        delta = jnp.sum(do_f * o, axis=1, keepdims=True)
        ds = p * (dp - delta)
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, o, lse, do, q_seg, kv_seg, seed, scale, causal,
              kv_len, block_q, block_k, interpret, dropout_rate, heads):
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    has_segs = q_seg is not None
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, limit=kv_len, has_segs=has_segs,
                  dropout_rate=dropout_rate)
    seg_inputs = []
    if has_segs:
        seg_inputs = list(_expand_segs(q_seg, kv_seg))
    seed_inputs = [seed] if dropout_rate > 0.0 else []

    q_spec = pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0))
    lse_spec = pl.BlockSpec((None, block_q, LANES), lambda b, i, j: (b, i, 0))
    kj_spec = pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0))
    dq_in_specs = [q_spec, kj_spec, kj_spec, q_spec, q_spec, lse_spec]
    if has_segs:
        qspec, kspec = _seg_specs(heads, block_q, block_k, q_axis=1,
                                  k_axis=2)
        dq_in_specs += [qspec, kspec]
    if dropout_rate > 0.0:
        dq_in_specs.append(_smem_spec())
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(bh, pl.cdiv(t_q, block_q), pl.cdiv(t_k, block_k)),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(q, k, v, do, o, lse, *seg_inputs, *seed_inputs)

    qj_spec = pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0))
    lsej_spec = pl.BlockSpec((None, block_q, LANES),
                             lambda b, i, j: (b, j, 0))
    ki_spec = pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0))
    dkv_in_specs = [qj_spec, ki_spec, ki_spec, qj_spec, qj_spec, lsej_spec]
    if has_segs:
        # dkv grid is (bh, k_blocks, q_blocks): q blocks ride grid axis 2
        qspec, kspec = _seg_specs(heads, block_q, block_k, q_axis=2,
                                  k_axis=1)
        dkv_in_specs += [qspec, kspec]
    if dropout_rate > 0.0:
        dkv_in_specs.append(_smem_spec())
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(bh, pl.cdiv(t_k, block_k), pl.cdiv(t_q, block_q)),
        in_specs=dkv_in_specs,
        out_specs=[ki_spec, ki_spec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(q, k, v, do, o, lse, *seg_inputs, *seed_inputs)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom_vjp wiring ([BH, T, D] core; segment ids stay [B, T] compact and
# are lane/sublane-expanded per pallas_call)
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13))
def _flash_core(q, k, v, q_seg, kv_seg, seed, scale, causal, kv_len,
                block_q, block_k, interpret, dropout_rate, heads):
    o, _ = _fwd(q, k, v, q_seg, kv_seg, seed, scale, causal, kv_len,
                block_q, block_k, interpret, want_lse=False,
                dropout_rate=dropout_rate, heads=heads)
    return o


def _flash_core_fwd(q, k, v, q_seg, kv_seg, seed, scale, causal, kv_len,
                    block_q, block_k, interpret, dropout_rate, heads):
    o, lse = _fwd(q, k, v, q_seg, kv_seg, seed, scale, causal, kv_len,
                  block_q, block_k, interpret, want_lse=True,
                  dropout_rate=dropout_rate, heads=heads)
    return o, (q, k, v, o, lse, q_seg, kv_seg, seed)


def _flash_core_bwd(scale, causal, kv_len, block_q, block_k, interpret,
                    dropout_rate, heads, res, do):
    q, k, v, o, lse, q_seg, kv_seg, seed = res
    dq, dk, dv = _bwd_impl(q, k, v, o, lse, do, q_seg, kv_seg, seed, scale,
                           causal, kv_len, block_q, block_k, interpret,
                           dropout_rate, heads)
    return dq, dk, dv, None, None, None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, mask=None, scale: Optional[float] = None,
                    causal: bool = False, kv_len: Optional[int] = None,
                    segment_ids=None, dropout_rate: float = 0.0,
                    dropout_rng=None,
                    block_q: Optional[int] = DEFAULT_BLOCK_Q,
                    block_k: Optional[int] = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """q: [B, Tq, H, D]; k/v: [B, Tk, H, D] -> [B, Tq, H, D]. Differentiable.

    segment_ids: packed-ragged-batch masking — either a [B, T] int32 array
    (self-attention; ids shared by q and kv) or a (q_seg [B, Tq],
    kv_seg [B, Tk]) pair. Tokens attend only where ids are EQUAL; ids must
    be >= 0 (internal padding uses -1). Blocks with no segment overlap are
    skipped entirely (block-sparse). Every real token must be able to
    attend at least one position (with causal self-attention the diagonal
    guarantees this); a fully-masked row yields finite garbage, not NaN.

    dropout_rate: in-kernel attention dropout (needs dropout_rng when > 0).
    The keep pattern is a deterministic function of (rng, batch*head,
    q_pos, k_pos) — NOT bit-identical to the XLA reference path's
    bernoulli draw, but the same distribution and exactly reproduced in
    the backward kernels.

    mask: only None supported here (use causal/kv_len/segment_ids);
    callers with arbitrary masks must use the reference path —
    kernels/attention.py dispatches accordingly.
    """
    if mask is not None:
        raise ValueError("flash_attention handles causal/kv_len/segment_ids "
                         "only; arbitrary masks use the reference path")
    if dropout_rate >= 1.0:
        raise ValueError("dropout_rate must be < 1.0")
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    q_seg = kv_seg = None
    if segment_ids is not None:
        q_seg, kv_seg = normalize_segment_ids(segment_ids, b, t_q, t_k)

    seed = None
    if dropout_rate > 0.0:
        if dropout_rng is None:
            dropout_rate = 0.0  # eval: dropout is a no-op without an rng
        else:
            seed = jax.random.randint(dropout_rng, (1, 1), 0, 2**31 - 1,
                                      dtype=jnp.int32)

    if block_q is None or block_k is None:
        if interpret:
            # interpret mode (CPU tests): per-block python interpretation
            # cost scales with block area; small blocks keep CI fast and
            # the numerics are block-size-independent
            dq, dk = 128, 128
        else:
            dq, dk = _default_blocks(t_q, t_k)
        block_q = block_q if block_q is not None else dq
        block_k = block_k if block_k is not None else dk

    # Pad sequence dims to block multiples: Pallas clamps a ragged tail
    # block's *start index*, silently overlapping the previous block, so
    # padding + masking via kv_len is the only correct treatment. Autodiff
    # through pad/slice zero-pads the cotangents for the backward kernels.
    # Segment ids pad with -1: real ids are >= 0 so real rows never attend
    # the pad tail, while pad q rows match pad kv columns (keeps their
    # denominators non-degenerate; those rows are sliced off below).
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    pad_q = -t_q % block_q
    pad_k = -t_k % block_k
    if pad_k and kv_len is None and kv_seg is None:
        kv_len = t_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        if q_seg is not None:
            q_seg = jnp.pad(q_seg, ((0, 0), (0, pad_q)), constant_values=-1)
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        if kv_seg is not None:
            kv_seg = jnp.pad(kv_seg, ((0, 0), (0, pad_k)),
                             constant_values=-1)

    def to_bhtd(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(-1, x.shape[1], d)

    o = _flash_core(to_bhtd(q), to_bhtd(k), to_bhtd(v), q_seg, kv_seg, seed,
                    scale, causal, kv_len, block_q, block_k, interpret,
                    dropout_rate, h)
    o = jnp.transpose(o.reshape(b, h, t_q + pad_q, d), (0, 2, 1, 3))
    return o[:, :t_q] if pad_q else o

"""Flash attention in Pallas (TPU).

The Pallas tier is this framework's analog of the reference's hand-fused
CUDA/JIT kernels (operators/fused/, operators/jit/): XLA fuses most things,
but attention's softmax-rescaling loop is the canonical case where a custom
kernel beats the compiler by keeping the [Tq, Tk] score matrix out of HBM.

Algorithm: standard online-softmax flash attention. Grid over
(batch*heads, q blocks); each program streams K/V blocks with a fori_loop
carrying (running max, running denom, accumulator) — O(Tq*D) VMEM instead of
O(Tq*Tk) HBM traffic.

Supports causal masking and right-padding via `kv_len`. Dropout and
arbitrary masks fall back to the XLA reference path in
kernels/attention.py.

On CPU (tests) runs in interpret mode so the kernel's numerics are validated
against reference_attention without TPU hardware.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU builds; interpret mode needs no TPU.
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
                  block_k: int, kv_len: Optional[int], q_offset_blocks: int):
    """One (batch*head, q-block) program: stream K/V, online softmax."""
    q = q_ref[...].astype(jnp.float32) * scale          # [BQ, D]
    bq, d = q.shape
    t_k = k_ref.shape[0]
    qi = pl.program_id(1)
    q_start = (qi + q_offset_blocks) * bq

    num_kb = pl.cdiv(t_k, block_k)

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [BQ, BK]
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        if kv_len is not None:
            s = jnp.where(kpos < kv_len, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # only k-blocks up to the diagonal contribute
        last = jnp.minimum(
            num_kb, (q_start + bq + block_k - 1) // block_k)
    else:
        last = num_kb
    m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, a0))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_bhtd(q, k, v, scale: float, causal: bool, kv_len: Optional[int],
                block_q: int, block_k: int, interpret: bool):
    """q/k/v: [BH, T, D] — core pallas_call wrapper."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    grid = (bh, pl.cdiv(t_q, block_q))

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_k=block_k,
        kv_len=kv_len, q_offset_blocks=0)

    kw = {} if _VMEM is None else {"memory_space": _VMEM}
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((None, t_k, d), lambda b, i: (b, 0, 0), **kw),
            pl.BlockSpec((None, t_k, d), lambda b, i: (b, 0, 0), **kw),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0),
                               **kw),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
        interpret=interpret,
    )(q, k, v)


def flash_attention(q, k, v, mask=None, scale: Optional[float] = None,
                    causal: bool = False, kv_len: Optional[int] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """q: [B, Tq, H, D]; k/v: [B, Tk, H, D] -> [B, Tq, H, D].

    mask: only None supported here (use causal/kv_len); callers with
    arbitrary masks must use the reference path — kernels/attention.py
    dispatches accordingly.
    """
    if mask is not None:
        raise ValueError("flash_attention handles causal/kv_len only; "
                         "arbitrary masks use the reference path")
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def to_bhtd(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(-1, x.shape[1], d)

    o = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), scale, causal,
                    kv_len, block_q, block_k, interpret)
    return jnp.transpose(o.reshape(b, h, t_q, d), (0, 2, 1, 3))

"""Paged decode attention: K/V gathered through per-sequence block tables.

The serving-side sibling of kernels/flash.py. Online inference
(engine/) stores each sequence's KV history as a list of fixed-size
token blocks inside one shared pool ([num_blocks, block_size, Hkv, Dh]
per layer), so admission/eviction never copies KV state and a ragged
batch wastes at most block_size-1 slots per sequence ("Ragged Paged
Attention", arxiv 2604.15464). Decode attention then has to gather K/V
through the block table instead of slicing a dense [B, Tmax] cache.

Two implementations with one contract (mirroring attention.py's
flash/reference split):

- `paged_attention_reference` — pure-XLA gather + dense attention.
  Runs anywhere, is the numerics oracle for tests, and is what the
  dispatcher uses off-TPU.
- a Pallas kernel — grid (B, blocks_per_seq); the block table rides
  scalar prefetch (pltpu.PrefetchScalarGridSpec) so the *index map*
  picks which pool block to DMA into VMEM: the gather IS the block
  fetch, no [B, T, Hkv, Dh] contiguous K/V ever materializes. The kv
  axis is sequential ("arbitrary") with online-softmax scratch, and
  blocks past a sequence's context length are skipped entirely, so a
  ragged batch costs ~sum(ceil(len_i/bs)) block reads, not B*max_len.
  Runs in interpret mode on CPU so tests validate it without TPU
  hardware (same policy as kernels/flash.py).

Layout: q is [B, H, Dh] (one query token per sequence — decode);
pools are [NB, BS, Hkv, Dh]; block_tables [B, MB] int32 pool-block
ids; context_lens [B] int32 valid-token counts. GQA/MQA: Hkv may
divide H; the grouped einsum reads each kv head once.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports without TPU hardware; interpret mode needs no TPU.
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from paddle_tpu.kernels.attention import reference_attention

NEG_INF = -1e9
LANES = 128   # online-softmax m/l scratch is lane-broadcast, as in flash.py
# Mirror of quant.int8_compute's QMAX reciprocal (importing it would pull
# nn.layers into the kernel module). The in-place dequant below must stay
# bit-identical to dequantize_block: x = (q_int8 -> f32) * (scale * RQMAX),
# then cast to the fp pool dtype — that identity is what makes direct int8
# reads produce the same bytes as the promote-then-read path. Multiplying
# by the pre-rounded reciprocal (rather than dividing by 127) keeps eager
# and jitted dequant bit-equal: XLA rewrites constant division into
# reciprocal multiplication, eager mode does not.
_QMAX = 127.0
_RQMAX = float(np.float32(1.0) / np.float32(_QMAX))


@functools.lru_cache(maxsize=1)
def _device_platform() -> str:
    """The default device's platform, resolved once per process.
    jax.devices() takes a lock and rebuilds the device list on every
    call — too heavy for a per-dispatch check on the serve hot path."""
    return jax.devices()[0].platform


def _resolve_dispatch(use_kernel: Optional[bool],
                      interpret: Optional[bool]) -> tuple:
    """Shared kernel/reference/interpret tier selection for the paged
    dispatchers. Explicit caller arguments win; with use_kernel=None the
    PTPU_PAGED_KERNEL env var can force a tier (so the FULL engine path
    can run through the kernel in interpret mode, not just unit tests):

    - "kernel":    Pallas kernel, interpret off-TPU
    - "interpret": Pallas kernel in interpret mode everywhere
    - "reference": XLA reference everywhere
    """
    if use_kernel is None:
        mode = os.environ.get("PTPU_PAGED_KERNEL", "").strip().lower()
        if mode == "reference":
            return False, False
        if mode == "interpret":
            return True, True
        if mode == "kernel":
            use_kernel = True
        elif mode:
            raise ValueError(
                f"PTPU_PAGED_KERNEL={mode!r}: expected "
                "kernel | reference | interpret")
    on_tpu = _device_platform() == "tpu"
    if use_kernel is None:
        use_kernel = on_tpu
    if not use_kernel:
        return False, False
    if interpret is None:
        interpret = not on_tpu
    return True, interpret


def paged_attention_reference(q, k_pool, v_pool, block_tables, context_lens,
                              scale: Optional[float] = None):
    """Oracle path: gather blocks dense, mask past context_len, run
    reference_attention. q: [B, H, D]; pools: [NB, BS, Hkv, D];
    block_tables: [B, MB] int32; context_lens: [B] int32 -> [B, H, D]."""
    b, h, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    k = k_pool[block_tables].reshape(b, mb * bs, hkv, d)
    v = v_pool[block_tables].reshape(b, mb * bs, hkv, d)
    mask = (jnp.arange(mb * bs)[None, :]
            < context_lens[:, None])[:, None, None, :]
    return reference_attention(q[:, None].astype(k.dtype), k, v, mask=mask,
                               scale=scale)[:, 0].astype(q.dtype)


def paged_prefill_attention(q, k_pool, v_pool, block_tables, context_lens,
                            q_positions, scale: Optional[float] = None):
    """Chunked-prefill attention: a CHUNK of queries per sequence
    attends through the block table — over the prefix KV already in the
    pool AND the chunk's own KV (the caller scatters the chunk's k/v
    into the pool first), causally.

    q: [B, C, H, D] chunk queries; q_positions: [B, C] int32 absolute
    position of each query (start offset + within-chunk index — rows of
    a batch may start at different depths, and pad rows sit at
    position 0); pools [NB, BS, Hkv, D]; block_tables [B, MB];
    context_lens [B] int32 = each row's chunk-end position (or 1 for
    pad rows). Returns [B, C, H, D].

    A gathered slot's logical position IS its index in block-table
    order, so causality is `kv_pos <= q_pos` — which also masks the
    scratch-block garbage gathered for padded table entries (their
    kv_pos exceeds every real query position). Masked scores sit at
    NEG_INF and underflow to exact 0 after the softmax's max-shift, so
    widening the gather never perturbs the attended sum — the property
    the engine's exact batching-invariance tests lean on.

    XLA-only for now: chunk prefill is compute-bound (unlike decode,
    whose gather the Pallas kernel exists to keep HBM-shaped), and the
    dense gather is the same oracle path `paged_attention_reference`
    uses. A Pallas ragged-prefill kernel (PAPERS.md "Ragged Paged
    Attention") is the TPU-rig follow-up tracked in ROADMAP.md.
    """
    b, c, h, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    k = k_pool[block_tables].reshape(b, mb * bs, hkv, d)
    v = v_pool[block_tables].reshape(b, mb * bs, hkv, d)
    kv_pos = jnp.arange(mb * bs, dtype=jnp.int32)
    mask = ((kv_pos[None, None, :] <= q_positions[:, :, None])
            & (kv_pos[None, None, :] < context_lens[:, None, None]))
    return reference_attention(q.astype(k.dtype), k, v,
                               mask=mask[:, None], scale=scale
                               ).astype(q.dtype)


def _scratch(shape):
    if _VMEM is None:  # pragma: no cover
        raise RuntimeError(
            "Pallas TPU support unavailable in this jax build; use "
            "paged_attention_reference (use_kernel=False)")
    return _VMEM(shape, jnp.float32)


def _paged_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, block_size: int,
                  groups: int):
    """One (sequence, kv-block) grid cell. q_ref: [H, D]; k/v_ref: the
    pool block the index map selected via the prefetched block table,
    [BS, Hkv, D]. Scratch persists across the sequential kv axis."""
    b, j = pl.program_id(0), pl.program_id(1)
    nblk = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    ctx = cl_ref[b]

    @pl.when(j * block_size < ctx)
    def _compute():
        q = q_ref[...]                                  # [H, D]
        k = k_ref[...]                                  # [BS, Hkv, D]
        v = v_ref[...]
        h, d = q.shape
        hkv = k.shape[1]
        qg = q.reshape(hkv, groups, d)
        kt = jnp.transpose(k, (1, 0, 2))                # [Hkv, BS, D]
        # batched over kv heads: [Hkv, G, D] x [Hkv, BS, D] -> [Hkv, G, BS]
        s = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        s = s.reshape(h, block_size)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (h, block_size), 1)
        s = jnp.where(pos < ctx, s, NEG_INF)

        m_prev = m_scr[...][:, :1]                      # [H, 1]
        l_prev = l_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                          # [H, BS]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pg = p.reshape(hkv, groups, block_size)
        vt = jnp.transpose(v, (1, 0, 2))                # [Hkv, BS, D]
        pv = jax.lax.dot_general(
            pg.astype(v.dtype), vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)         # [Hkv, G, D]
        acc_scr[...] = alpha * acc_scr[...] + pv.reshape(h, d)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nblk - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        o_ref[...] = (acc_scr[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype)


def _paged_kernel_call(q, k_pool, v_pool, block_tables, context_lens, scale,
                       interpret: bool):
    b, h, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block_tables, context_lens
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda b, j, bt, cl: (b, 0, 0)),
            # the paged gather: the index map dereferences the block table
            pl.BlockSpec((None, bs, hkv, d),
                         lambda b, j, bt, cl: (bt[b, j], 0, 0, 0)),
            pl.BlockSpec((None, bs, hkv, d),
                         lambda b, j, bt, cl: (bt[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d), lambda b, j, bt, cl: (b, 0, 0)),
        scratch_shapes=[
            _scratch((h, LANES)),
            _scratch((h, LANES)),
            _scratch((h, d)),
        ],
    )
    kernel = functools.partial(_paged_kernel, scale=scale, block_size=bs,
                               groups=h // hkv)
    compiler_params = None
    if pltpu is not None:
        # jax <= 0.4.x spells it TPUCompilerParams; newer jax CompilerParams
        cls = (getattr(pltpu, "CompilerParams", None)
               or pltpu.TPUCompilerParams)
        compiler_params = cls(dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      q, k_pool, v_pool)


def paged_attention(q, k_pool, v_pool, block_tables, context_lens,
                    scale: Optional[float] = None,
                    use_kernel: Optional[bool] = None,
                    interpret: Optional[bool] = None):
    """Dispatching entry point (the mha() of the paged path).

    use_kernel=None: Pallas kernel on TPU, XLA reference elsewhere —
    the engine and model code call with defaults and get the right tier
    (PTPU_PAGED_KERNEL overrides; see _resolve_dispatch). Tests pass
    use_kernel=True, interpret=True to validate the kernel's numerics
    on CPU.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    use_kernel, interpret = _resolve_dispatch(use_kernel, interpret)
    if not use_kernel:
        return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                         context_lens, scale=scale)
    return _paged_kernel_call(q, k_pool, v_pool, block_tables, context_lens,
                              scale, interpret)


# ---------------------------------------------------------------------------
# Ragged paged attention: ONE launch for a mixed prefill+decode batch.
#
# The serve engine packs every row of a step — decode rows (one query
# token) and prefill chunks (a window of C query tokens) — into a
# single flat query array q: [T, H, D]. Each row occupies a contiguous
# segment aligned to TILE_Q tokens; slack positions inside a row's last
# tile and whole unused tiles are padding. Per-TILE metadata maps the
# packing back to sequences:
#
# - tile_rows [NT] int32: which metadata row each query tile belongs to
#   (pad tiles point at a "null row" whose context_len is 1 and whose
#   block table is all scratch block 0).
# - tile_offs [NT] int32: the tile's token offset WITHIN its row's
#   segment, so a query's absolute position is
#   q_starts[row] + tile_off + (index inside the tile).
# - block_tables [R, MB], context_lens [R], q_starts [R]: per-row pool
#   block tables, chunk-end positions (start + q_len; 1 for the null
#   row), and first-query positions. A decode row is simply q_len=1:
#   q_start = ctx - 1.
#
# Masking is absolute-position causal AND context-bounded
# (kv_pos <= q_pos, kv_pos < ctx — the paged_prefill_attention
# contract), so decode rows, mid-prompt chunks and pad queries all fall
# out of one rule: pad queries attend a finite prefix (never sampled),
# and kv position 0 is always visible, so no softmax row is ever empty.
# ---------------------------------------------------------------------------


def _gather_mixed(pool, q_pool, scales, ids, neg):
    """Dense mixed-tier gather for the reference oracle: fp pool rows
    where the (bias-decoded) table entry is non-negative, per-block
    dequantized int8 rows where it is. ids: [...] raw table entries;
    neg = ids < 0. Dequant is the dequantize_block identity —
    (int8 -> f32) * (scale / QMAX), cast to the fp pool dtype — so a
    direct read returns exactly the bytes a promote would have
    scattered."""
    fp_ids = jnp.where(neg, 0, ids)
    q_ids = jnp.where(neg, -ids - 1, 0)
    dense = pool[fp_ids]                       # [..., BS, Hkv, D]
    deq = (q_pool[q_ids].astype(jnp.float32)
           * (scales[q_ids] * _RQMAX)[..., None, None, None]
           ).astype(pool.dtype)
    return jnp.where(neg[..., None, None, None], deq, dense)


def ragged_paged_attention_reference(q, k_pool, v_pool, block_tables,
                                     context_lens, q_starts, tile_rows,
                                     tile_offs,
                                     scale: Optional[float] = None,
                                     kq_pool=None, vq_pool=None,
                                     k_scales=None, v_scales=None):
    """XLA oracle for the ragged layout: expand tile metadata to
    per-token rows and run the dense gather + masked attention.
    q: [T, H, D] flat-packed; returns [T, H, D].

    Gathers [T, MB*BS, Hkv, D] — heavier than the per-row [B, ...]
    gathers above (every token re-gathers its row's blocks), but it is
    the off-TPU dispatch tier where T stays small (CPU smoke + tests),
    and XLA's masked softmax keeps it exactly batch-invariant.

    With kq_pool/vq_pool (+[NQ] per-block k_scales/v_scales) the table
    entries are bias-encoded: id >= 0 reads the fp pool, id < 0 reads
    int8 slot -id-1 and dequantizes in place."""
    t, h, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    nt = tile_rows.shape[0]
    if t % nt:
        raise ValueError(f"flat length {t} not a multiple of {nt} tiles")
    tq = t // nt
    mb = block_tables.shape[1]
    row_of = jnp.repeat(tile_rows, tq)                       # [T]
    qpos = (jnp.repeat(q_starts[tile_rows] + tile_offs, tq)
            + jnp.tile(jnp.arange(tq, dtype=jnp.int32), nt))  # [T]
    bt = block_tables[row_of]                                # [T, MB]
    if kq_pool is None:
        k = k_pool[bt].reshape(t, mb * bs, hkv, d)
        v = v_pool[bt].reshape(t, mb * bs, hkv, d)
    else:
        neg = bt < 0
        k = _gather_mixed(k_pool, kq_pool, k_scales, bt, neg
                          ).reshape(t, mb * bs, hkv, d)
        v = _gather_mixed(v_pool, vq_pool, v_scales, bt, neg
                          ).reshape(t, mb * bs, hkv, d)
    kv_pos = jnp.arange(mb * bs, dtype=jnp.int32)
    ctx = context_lens[row_of]
    mask = ((kv_pos[None, :] <= qpos[:, None])
            & (kv_pos[None, :] < ctx[:, None]))[:, None, None, :]
    return reference_attention(q[:, None].astype(k.dtype), k, v, mask=mask,
                               scale=scale)[:, 0].astype(q.dtype)


def _ragged_tile_update(q, k, v, q0, ctx, j, m_scr, l_scr, acc_scr, *,
                        scale: float, block_size: int, groups: int):
    """Online-softmax update for one (query-tile, kv-block) cell —
    shared by the fp-only and mixed-precision ragged kernels. q:
    [TQ, H, D]; k/v: [BS, Hkv, D]; scratch rows are flattened TQ*H."""
    tq, h, d = q.shape
    hkv = k.shape[1]
    # batch over kv heads: [Hkv, TQ*G, D] x [Hkv, BS, D]
    qg = q.reshape(tq, hkv, groups, d).transpose(1, 0, 2, 3) \
          .reshape(hkv, tq * groups, d)
    kt = jnp.transpose(k, (1, 0, 2))                # [Hkv, BS, D]
    s = jax.lax.dot_general(
        qg, kt, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale  # [Hkv, TQ*G, BS]
    s = s.reshape(hkv, tq, groups, block_size).transpose(1, 0, 2, 3) \
         .reshape(tq * h, block_size)
    qpos = q0 + jax.lax.broadcasted_iota(
        jnp.int32, (tq, h, block_size), 0).reshape(tq * h, block_size)
    kpos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (tq, h, block_size), 2).reshape(tq * h, block_size)
    s = jnp.where((kpos <= qpos) & (kpos < ctx), s, NEG_INF)

    m_prev = m_scr[...][:, :1]                      # [TQ*H, 1]
    l_prev = l_scr[...][:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)                          # [TQ*H, BS]
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    pg = p.reshape(tq, hkv, groups, block_size).transpose(1, 0, 2, 3) \
          .reshape(hkv, tq * groups, block_size)
    vt = jnp.transpose(v, (1, 0, 2))                # [Hkv, BS, D]
    pv = jax.lax.dot_general(
        pg.astype(v.dtype), vt, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)         # [Hkv, TQ*G, D]
    pv = pv.reshape(hkv, tq, groups, d).transpose(1, 0, 2, 3) \
           .reshape(tq * h, d)
    acc_scr[...] = alpha * acc_scr[...] + pv
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)


def _ragged_kernel(bt_ref, cl_ref, qs_ref, tr_ref, to_ref,
                   q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                   scale: float, block_size: int, tile_q: int, groups: int):
    """One (query-tile, kv-block) grid cell. q_ref: [TQ, H, D] — one
    tile of the flat packing; k/v_ref: the pool block the index map
    selected, [BS, Hkv, D]. Online-softmax scratch is flattened to
    (TQ*H, ·) rows and persists across the sequential kv axis."""
    t, j = pl.program_id(0), pl.program_id(1)
    nblk = pl.num_programs(1)
    row = tr_ref[t]
    ctx = cl_ref[row]
    q0 = qs_ref[row] + to_ref[t]        # absolute pos of the tile's 1st query

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip blocks entirely past the row's context OR entirely in the
    # causal future of the tile's LAST query (position q0 + tile_q - 1)
    @pl.when((j * block_size < ctx) & (j * block_size <= q0 + tile_q - 1))
    def _compute():
        _ragged_tile_update(q_ref[...], k_ref[...], v_ref[...], q0, ctx, j,
                            m_scr, l_scr, acc_scr, scale=scale,
                            block_size=block_size, groups=groups)

    @pl.when(j == nblk - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        o_ref[...] = (acc_scr[...] / jnp.maximum(l, 1e-30)).reshape(
            o_ref.shape).astype(o_ref.dtype)


def _ragged_kernel_mixed(bt_ref, cl_ref, qs_ref, tr_ref, to_ref,
                         ksc_ref, vsc_ref,
                         q_ref, k_ref, v_ref, kq_ref, vq_ref, o_ref,
                         m_scr, l_scr, acc_scr, *,
                         scale: float, block_size: int, tile_q: int,
                         groups: int):
    """Mixed-precision variant: the block table entry is bias-encoded
    (id >= 0 -> fp pool block id; id < 0 -> int8 pool slot -id-1). Both
    pools ride their own BlockSpec — each index map degenerates to slot
    0 for the tier it does NOT serve, so only the selected tier's DMA
    changes block-to-block — and the kernel dequantizes the int8 block
    in registers with the per-block scale from scalar prefetch. The
    dequant is bit-identical to quant.dequantize_block, which is what
    pins direct-read output to the promote path's bytes."""
    t, j = pl.program_id(0), pl.program_id(1)
    nblk = pl.num_programs(1)
    row = tr_ref[t]
    ctx = cl_ref[row]
    q0 = qs_ref[row] + to_ref[t]
    e = bt_ref[row, j]
    is8 = e < 0
    slot = jnp.where(is8, -e - 1, 0)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when((j * block_size < ctx) & (j * block_size <= q0 + tile_q - 1))
    def _compute():
        kf = k_ref[...]                                 # [BS, Hkv, D]
        vf = v_ref[...]
        kd = (kq_ref[...].astype(jnp.float32)
              * (ksc_ref[slot] * _RQMAX)).astype(kf.dtype)
        vd = (vq_ref[...].astype(jnp.float32)
              * (vsc_ref[slot] * _RQMAX)).astype(vf.dtype)
        k = jnp.where(is8, kd, kf)
        v = jnp.where(is8, vd, vf)
        _ragged_tile_update(q_ref[...], k, v, q0, ctx, j,
                            m_scr, l_scr, acc_scr, scale=scale,
                            block_size=block_size, groups=groups)

    @pl.when(j == nblk - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        o_ref[...] = (acc_scr[...] / jnp.maximum(l, 1e-30)).reshape(
            o_ref.shape).astype(o_ref.dtype)


def _ragged_kernel_call(q, k_pool, v_pool, block_tables, context_lens,
                        q_starts, tile_rows, tile_offs, scale,
                        interpret: bool,
                        kq_pool=None, vq_pool=None,
                        k_scales=None, v_scales=None):
    t, h, d = q.shape
    nb, bs, hkv, _ = k_pool.shape
    mb = block_tables.shape[1]
    nt = tile_rows.shape[0]
    if t % nt:
        raise ValueError(f"flat length {t} not a multiple of {nt} tiles")
    tq = t // nt
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    mixed = kq_pool is not None

    def _active(ti, j, cl, qs, tr, to):
        # skip predicate shared by every kv index map: inactive cells
        # re-select block 0, which elides the DMA entirely when the
        # previous cell already holds it (Pallas skips re-fetch on an
        # unchanged block index)
        row = tr[ti]
        return ((j * bs < cl[row])
                & (j * bs <= qs[row] + to[ti] + tq - 1))

    def _kv_block(ti, j, bt, cl, qs, tr, to):
        return (jnp.where(_active(ti, j, cl, qs, tr, to),
                          bt[tr[ti], j], 0), 0, 0, 0)

    def _kv_fp(ti, j, bt, cl, qs, tr, to, ksc, vsc):
        # bias-encoded entry: only non-negative ids live in the fp pool
        e = bt[tr[ti], j]
        act = _active(ti, j, cl, qs, tr, to) & (e >= 0)
        return (jnp.where(act, e, 0), 0, 0, 0)

    def _kv_q(ti, j, bt, cl, qs, tr, to, ksc, vsc):
        # negative ids decode to int8 pool slot -id-1
        e = bt[tr[ti], j]
        act = _active(ti, j, cl, qs, tr, to) & (e < 0)
        return (jnp.where(act, -e - 1, 0), 0, 0, 0)

    if mixed:
        def _q_map(ti, j, bt, cl, qs, tr, to, ksc, vsc):
            return (ti, 0, 0)
        # block_tables, ctx_lens, q_starts, tiles x2, k/v scales
        num_prefetch = 7
        in_specs = [
            pl.BlockSpec((tq, h, d), _q_map),
            pl.BlockSpec((None, bs, hkv, d), _kv_fp),
            pl.BlockSpec((None, bs, hkv, d), _kv_fp),
            pl.BlockSpec((None, bs, hkv, d), _kv_q),
            pl.BlockSpec((None, bs, hkv, d), _kv_q),
        ]
        out_specs = pl.BlockSpec((tq, h, d), _q_map)
        kernel_fn = _ragged_kernel_mixed
    else:
        def _q_map(ti, j, bt, cl, qs, tr, to):
            return (ti, 0, 0)
        num_prefetch = 5  # block_tables, ctx_lens, q_starts, tiles x2
        in_specs = [
            pl.BlockSpec((tq, h, d), _q_map),
            pl.BlockSpec((None, bs, hkv, d), _kv_block),
            pl.BlockSpec((None, bs, hkv, d), _kv_block),
        ]
        out_specs = pl.BlockSpec((tq, h, d), _q_map)
        kernel_fn = _ragged_kernel

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_prefetch,
        grid=(nt, mb),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            _scratch((tq * h, LANES)),
            _scratch((tq * h, LANES)),
            _scratch((tq * h, d)),
        ],
    )
    kernel = functools.partial(kernel_fn, scale=scale, block_size=bs,
                               tile_q=tq, groups=h // hkv)
    compiler_params = None
    if pltpu is not None:
        cls = (getattr(pltpu, "CompilerParams", None)
               or pltpu.TPUCompilerParams)
        compiler_params = cls(dimension_semantics=("parallel", "arbitrary"))
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, h, d), q.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )
    scalars = (block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
               q_starts.astype(jnp.int32), tile_rows.astype(jnp.int32),
               tile_offs.astype(jnp.int32))
    if mixed:
        return call(*scalars, k_scales.astype(jnp.float32),
                    v_scales.astype(jnp.float32),
                    q, k_pool, v_pool, kq_pool, vq_pool)
    return call(*scalars, q, k_pool, v_pool)


def ragged_paged_attention(q, k_pool, v_pool, block_tables, context_lens,
                           q_starts, tile_rows, tile_offs,
                           scale: Optional[float] = None,
                           use_kernel: Optional[bool] = None,
                           interpret: Optional[bool] = None,
                           kq_pool=None, vq_pool=None,
                           k_scales=None, v_scales=None):
    """Mixed prefill+decode attention over the flat ragged packing —
    the engine's single-step entry point. Dispatch tiers mirror
    paged_attention: Pallas kernel on TPU, XLA reference elsewhere,
    PTPU_PAGED_KERNEL / explicit flags override.

    When the engine's compressed tier is live it passes the int8 pools
    (kq_pool/vq_pool [NQ, BS, Hkv, D]) and per-block scales ([NQ] f32),
    and bias-encodes int8-resident blocks into block_tables (id < 0 ->
    slot -id-1): those blocks are read in place — dequantized per block
    inside the gather — instead of being promoted to fp first. The
    signature is shape-stable across fp-only / mixed / all-int8 batches
    so the jit cache stays at one entry (TP004)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    use_kernel, interpret = _resolve_dispatch(use_kernel, interpret)
    if not use_kernel:
        return ragged_paged_attention_reference(
            q, k_pool, v_pool, block_tables, context_lens, q_starts,
            tile_rows, tile_offs, scale=scale,
            kq_pool=kq_pool, vq_pool=vq_pool,
            k_scales=k_scales, v_scales=v_scales)
    return _ragged_kernel_call(q, k_pool, v_pool, block_tables,
                               context_lens, q_starts, tile_rows, tile_offs,
                               scale, interpret,
                               kq_pool=kq_pool, vq_pool=vq_pool,
                               k_scales=k_scales, v_scales=v_scales)


# -- tensor-parallel wrappers (engine tp_size knob, ENGINE.md) ------------
#
# The ragged kernel derives num_heads / num_kv_heads / groups from its
# INPUT shapes, so it runs unmodified on per-shard slices: shard q over
# heads and the pools over kv-heads on the "tp" mesh axis and each chip
# computes attention for its own contiguous head block. With both H and
# Hkv divisible by tp, shard s's q-head block [s·H/tp, (s+1)·H/tp) maps
# exactly onto its kv-head block (the local `head // groups` lookup is
# unchanged: groups = H/Hkv is the same locally), so GQA groups stay
# device-local and NO collective runs inside attention. Block tables /
# context lens / packing metadata are tiny int32 operands — replicated.


def ragged_paged_attention_tp(mesh, q, k_pool, v_pool, block_tables,
                              context_lens, q_starts, tile_rows, tile_offs,
                              scale: Optional[float] = None,
                              use_kernel: Optional[bool] = None,
                              interpret: Optional[bool] = None,
                              kq_pool=None, vq_pool=None,
                              k_scales=None, v_scales=None):
    """`ragged_paged_attention` as an explicit shard_map island over
    the "tp" axis of `mesh` — q [T, H, D] sharded on H, pools sharded
    on Hkv, everything else replicated; output [T, H, D] stays sharded
    on H (the downstream out_proj is row-parallel over the same
    axis). The int8 pools shard on Hkv exactly like the fp pools;
    per-block scales are head-independent scalars, replicated."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.compat import shard_map

    if kq_pool is None:
        def body(q_, kp, vp, bt, cl, qs, tr, to):
            return ragged_paged_attention(q_, kp, vp, bt, cl, qs, tr, to,
                                          scale=scale, use_kernel=use_kernel,
                                          interpret=interpret)

        f = shard_map(body, mesh=mesh,
                      in_specs=(P(None, "tp", None),
                                P(None, None, "tp", None),
                                P(None, None, "tp", None),
                                P(), P(), P(), P(), P()),
                      out_specs=P(None, "tp", None), check_vma=False)
        return f(q, k_pool, v_pool, block_tables, context_lens, q_starts,
                 tile_rows, tile_offs)

    def body(q_, kp, vp, bt, cl, qs, tr, to, kq, vq, ks, vs):
        return ragged_paged_attention(q_, kp, vp, bt, cl, qs, tr, to,
                                      scale=scale, use_kernel=use_kernel,
                                      interpret=interpret,
                                      kq_pool=kq, vq_pool=vq,
                                      k_scales=ks, v_scales=vs)

    f = shard_map(body, mesh=mesh,
                  in_specs=(P(None, "tp", None),
                            P(None, None, "tp", None),
                            P(None, None, "tp", None),
                            P(), P(), P(), P(), P(),
                            P(None, None, "tp", None),
                            P(None, None, "tp", None),
                            P(), P()),
                  out_specs=P(None, "tp", None), check_vma=False)
    return f(q, k_pool, v_pool, block_tables, context_lens, q_starts,
             tile_rows, tile_offs, kq_pool, vq_pool, k_scales, v_scales)


def paged_prefill_attention_tp(mesh, q, k_pool, v_pool, block_tables,
                               context_lens, q_positions,
                               scale: Optional[float] = None):
    """`paged_prefill_attention` sharded the same way: q [B, C, H, D]
    on H, pools on Hkv, int32 metadata replicated, output sharded on
    H."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.compat import shard_map

    def body(q_, kp, vp, bt, cl, qp):
        return paged_prefill_attention(q_, kp, vp, bt, cl, qp, scale=scale)

    f = shard_map(body, mesh=mesh,
                  in_specs=(P(None, None, "tp", None),
                            P(None, None, "tp", None),
                            P(None, None, "tp", None),
                            P(), P(), P()),
                  out_specs=P(None, None, "tp", None), check_vma=False)
    return f(q, k_pool, v_pool, block_tables, context_lens, q_positions)

"""Attention kernels: XLA reference path + Pallas flash attention on TPU.

The reference framework hand-fuses hot patterns in C++/CUDA (operators/fused/,
attention-adjacent fuse passes ir/attention_lstm_fuse_pass.cc); on TPU the
equivalent tier is Pallas kernels (see /opt/skills/guides/pallas_guide.md).

Layout convention: q/k/v are [B, T, H, Dh] (batch, time, heads, head_dim).
`mha` dispatches:
- Pallas flash attention (paddle_tpu.kernels.flash) when running on TPU and
  shapes are tile-friendly;
- an XLA einsum reference path otherwise (CPU tests, odd shapes). Both paths
  share semantics, so tests on the CPU mesh validate the TPU path's contract.

FLAGS_flash_attention=0 forces the reference path (debugging escape hatch,
like the reference's FLAGS_cudnn_deterministic).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.utils.flags import FLAGS

FLAGS.define("flash_attention", True,
             "Use the Pallas flash-attention kernel on TPU when applicable.")

NEG_INF = -1e9


def reference_attention(q, k, v, mask=None, scale: Optional[float] = None,
                        dropout_rng=None, dropout_rate: float = 0.0):
    """Plain XLA attention. q:[B,Tq,H,D] k/v:[B,Tk,Hkv,D] -> [B,Tq,H,D].

    Hkv may divide H (grouped-query / multi-query attention): the grouped
    einsum never materializes k/v repeated to H heads — at decode time
    the k/v cache read IS the bandwidth bill, which is the point of GQA.

    mask: broadcastable to [B, H, Tq, Tk] (with GQA, to
    [B, Hkv, G, Tq, Tk] after a group-dim insert — [B, 1or H, Tq, Tk]
    masks broadcast either way), True = attend.
    """
    d = q.shape[-1]
    h, h_kv = q.shape[2], k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if h != h_kv:
        if h % h_kv:
            raise ValueError(f"q heads {h} not a multiple of kv heads "
                             f"{h_kv}")
        g = h // h_kv
        b, tq = q.shape[:2]
        qg = q.reshape(b, tq, h_kv, g, d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * scale
        logits = logits.astype(jnp.promote_types(logits.dtype, jnp.float32))
        if mask is not None:
            m = mask
            if m.ndim == 4:  # [B, 1|H, Tq, Tk] -> group layout
                if m.shape[1] == h:
                    m = m.reshape(m.shape[0], h_kv, g, *m.shape[2:])
                else:
                    m = m[:, :, None]
            logits = jnp.where(m, logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        if dropout_rate > 0.0 and dropout_rng is not None:
            keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                        probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
        probs = probs.astype(v.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
        return out.reshape(b, tq, h, d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.promote_types(logits.dtype, jnp.float32))
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def would_use_flash(q_shape, k_shape, has_mask: bool = False,
                    dropout_rate: float = 0.0) -> bool:
    """mha's flash-dispatch gate, exported so callers that must AGREE
    with the dispatch (the analytic MFU corrections in
    benchmark/models.py — the flash custom call scores 0 flops in XLA's
    cost analysis) evaluate the same predicate, not a copy.

    The kernel pads ragged sequence lengths to block multiples itself and
    (round 5) handles segment-id masking and attention dropout in-kernel,
    so the gate only excludes: shapes where XLA's dense attention is
    simply faster, head dims the MXU tiles badly, and arbitrary dense
    masks. `dropout_rate` is accepted for signature compatibility but no
    longer gates — dropout>0 does not change the dispatch. Measured on
    v5e (fwd+bwd, bf16, causal): XLA wins 3.6x at T=256; flash wins 1.9x
    at T=1024 and is the only feasible path at 16k+ (the [B,H,Tq,Tk]
    score tensor stops fitting) — so the gate is the kv length crossing
    512."""
    del dropout_rate  # in-kernel dropout: no longer affects dispatch
    return (FLAGS.get("flash_attention") and _on_tpu()
            and not has_mask
            and q_shape[1] >= 64 and k_shape[1] >= 512
            and q_shape[-1] % 32 == 0 and q_shape[-1] <= 256)


def mha(q, k, v, mask=None, scale: Optional[float] = None,
        dropout_rng=None, dropout_rate: float = 0.0, causal: bool = False,
        kv_len: Optional[int] = None, segment_ids=None):
    """Dispatching multi-head attention entry point used by model code.

    `causal`, `kv_len` (static right-padding length) and `segment_ids`
    ([B, T] int32 packed-batch ids, or a (q_seg, kv_seg) pair; tokens
    attend only where ids match) are forwarded to the flash kernel, which
    handles them block-wise — materializing them into a dense `mask` would
    force the XLA reference path. Dropout runs in-kernel on the flash path
    (same distribution as the reference path's bernoulli, different bits).
    An explicit `mask` (arbitrary pattern) always uses the reference path.
    """
    if would_use_flash(q.shape, k.shape, has_mask=mask is not None):
        from paddle_tpu.kernels import flash
        if k.shape[2] != q.shape[2]:
            # GQA prefill/training: the kernel wants equal head counts —
            # repeat kv heads (compute unchanged; the cache still stores
            # only Hkv heads, which is where GQA's decode win lives)
            if q.shape[2] % k.shape[2]:
                raise ValueError(f"q heads {q.shape[2]} not a multiple "
                                 f"of kv heads {k.shape[2]}")
            g = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        return flash.flash_attention(q, k, v, scale=scale, causal=causal,
                                     kv_len=kv_len, segment_ids=segment_ids,
                                     dropout_rate=dropout_rate,
                                     dropout_rng=dropout_rng)
    if segment_ids is not None:
        from paddle_tpu.kernels.flash import normalize_segment_ids
        q_seg, kv_seg = normalize_segment_ids(
            segment_ids, q.shape[0], q.shape[1], k.shape[1])
        smask = (q_seg[:, :, None] == kv_seg[:, None, :])[:, None]
        mask = smask if mask is None else jnp.logical_and(mask, smask)
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        cmask = (jnp.arange(t_k)[None, :] <= jnp.arange(t_q)[:, None]
                 )[None, None]
        mask = cmask if mask is None else jnp.logical_and(mask, cmask)
    if kv_len is not None:
        t_k = k.shape[1]
        pmask = (jnp.arange(t_k) < kv_len)[None, None, None, :]
        mask = pmask if mask is None else jnp.logical_and(mask, pmask)
    return reference_attention(q, k, v, mask=mask, scale=scale,
                               dropout_rng=dropout_rng,
                               dropout_rate=dropout_rate)

from paddle_tpu.kernels import attention, paged_attention

"""Seed-deterministic byte-level tokenizer for the serve front door.

The serving API has always taken raw token id lists — fine for
benchmarks, hostile to clients (ROADMAP "async front door"). This
module closes that gap WITHOUT shipping a vocab artifact: the mapping
is derived entirely from (vocab_size, seed), so every replica built
with the same model dims and init seed tokenizes identically — the
same property the fleet already leans on for weights (same
PRNGKey(seed) init on every replica => byte-identical greedy decode).

Scheme: each UTF-8 byte becomes exactly TWO token ids — the high and
low nibble, each looked up in its own 16-entry alphabet drawn from a
seeded permutation of the model vocab. Fixed width makes the encoding
trivially injective (decode inverts pair by pair), nibble alphabets
keep it usable down to tiny test vocabs (needs vocab >= 16, the
replica default is 61), and the permutation spreads prompt mass over
the vocab so a text prompt exercises the same embedding rows a random
token benchmark does.

This is deliberately NOT a learned tokenizer — it is the smallest
deterministic front door that lets `{"prompt": "some text"}` hit
`/v1/completions` and round-trip through `/v1/tokenize`; a real BPE
vocab can replace the byte mapping behind the same encode/decode
surface later.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


class ByteTokenizer:
    """`ByteTokenizer(vocab, seed).encode(text)` -> token ids (2 per
    UTF-8 byte); `decode(ids)` inverts it. Same (vocab, seed) =>
    identical mapping in every process."""

    def __init__(self, vocab: int, seed: int = 0):
        if vocab < 16:
            raise ValueError(
                f"vocab {vocab} < 16: the byte tokenizer needs 16 "
                "distinct ids per nibble alphabet")
        self.vocab = int(vocab)
        self.seed = int(seed)
        rs = np.random.RandomState(self.seed)
        # two sequential draws from ONE seeded stream: distinct
        # alphabets, still fully determined by (vocab, seed)
        self._hi = [int(t) for t in rs.permutation(self.vocab)[:16]]
        self._lo = [int(t) for t in rs.permutation(self.vocab)[:16]]
        self._hi_inv = {t: i for i, t in enumerate(self._hi)}
        self._lo_inv = {t: i for i, t in enumerate(self._lo)}

    def encode(self, text: str) -> List[int]:
        out: List[int] = []
        for b in text.encode("utf-8"):
            out.append(self._hi[b >> 4])
            out.append(self._lo[b & 0xF])
        return out

    def decode(self, tokens: Sequence[int]) -> str:
        """Invert encode(). Raises ValueError on ids outside the
        alphabets or an odd-length sequence (generated tokens are NOT
        generally decodable — only encode() output round-trips)."""
        if len(tokens) % 2:
            raise ValueError(
                f"token count {len(tokens)} is odd: byte encoding is "
                "2 tokens per byte")
        data = bytearray()
        for i in range(0, len(tokens), 2):
            hi = self._hi_inv.get(int(tokens[i]))
            lo = self._lo_inv.get(int(tokens[i + 1]))
            if hi is None or lo is None:
                raise ValueError(
                    f"token pair ({tokens[i]}, {tokens[i + 1]}) at "
                    f"position {i} is not in the byte alphabets")
            data.append((hi << 4) | lo)
        return data.decode("utf-8")

"""Replica CLI: one serving process = model + engine + front-end.

`python -m paddle_tpu.serve.replica --port 0 ...` boots a CausalLM
(either a fresh PRNGKey(--init-seed) init — every replica started with
the same seed and dims holds IDENTICAL weights, which is how
serve_bench and the tests stand up a homogeneous fleet without a
checkpoint — or `--model-dir` from a save_inference_model() export),
wraps it in a ServeEngine and a ServeFrontend, warms the one compiled
step, and prints a single `serve_listening` JSON line carrying the
bound port (ephemeral with --port 0) for the parent to read back.

SIGTERM drains: in-flight streams finish (bounded by
--drain-deadline-s), then the process exits 75 (PREEMPT_EXIT_CODE) —
the same "safe to reschedule" contract as the training runtime.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="ptpu serve replica")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 binds an ephemeral port (printed in the "
                        "serve_listening line)")
    # model: a saved export, or a fresh deterministic init
    p.add_argument("--model-dir", default=None,
                   help="save_inference_model() directory with serve "
                        "metadata; omitting it builds a fresh model")
    p.add_argument("--vocab", type=int, default=61)
    p.add_argument("--model-dim", type=int, default=16)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--ffn-dim", type=int, default=32)
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--init-seed", type=int, default=0,
                   help="PRNGKey seed for the fresh init: same seed + "
                        "dims = identical weights on every replica")
    # engine
    p.add_argument("--max-batch-size", type=int, default=4)
    p.add_argument("--block-size", type=int, default=4)
    p.add_argument("--num-blocks", type=int, default=64)
    p.add_argument("--max-prefill-tokens", type=int, default=64)
    p.add_argument("--tile-q", type=int, default=8)
    p.add_argument("--no-prefix-cache", action="store_true")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative draft length (0 disables; > 0 "
                        "turns on the n-gram self-drafter)")
    p.add_argument("--host-tier-bytes", type=int, default=0,
                   help="host-RAM KV tier byte budget (0 disables; "
                        "> 0 demotes evicted/preempted blocks to host "
                        "and revives them by DMA — engine/kvtier.py)")
    p.add_argument("--kv-tier-int8", action="store_true",
                   help="store host-tier blocks int8-quantized "
                        "(roughly doubles the tier's effective budget)")
    p.add_argument("--kv-compress-blocks", type=int, default=0,
                   help="device int8 KV compression pool size in blocks "
                        "(0 disables): cold cached-free / idle shared "
                        "prefix blocks are quantized in place on device "
                        "and promoted back to fp on a prefix hit — "
                        "engine/paged_cache.py")
    p.add_argument("--tier-spill-dir", default=None,
                   help="warm-restart directory for the host KV tier: "
                        "the tier spills here when a drain completes "
                        "(and every --tier-spill-interval-s when > 0), "
                        "and a fresh boot warm-starts from the spill — "
                        "restart with the SAME dir to revive warm KV")
    p.add_argument("--tier-spill-interval-s", type=float, default=0.0,
                   help="also spill the host tier periodically (0 = "
                        "drain-time only); lets a SIGKILLed replica "
                        "warm-start from a recent snapshot")
    p.add_argument("--tp-size", type=int, default=1,
                   help="tensor-parallel degree: shard the one compiled "
                        "step over the first N devices (weights + KV "
                        "pools; per-chip HBM ~1/N). On CPU the replica "
                        "forces N virtual devices before jax initializes; "
                        "PTPU_SERVE_ALLREDUCE=fp|int8 picks the decode "
                        "collective wire format")
    p.add_argument("--phase", default="mixed",
                   choices=("prefill", "decode", "mixed"),
                   help="disaggregated-serving phase advertised to the "
                        "router (serve/kvxfer.py): a prefill replica "
                        "demotes every finished request's prefix blocks "
                        "into the host tier so decode replicas can pull "
                        "them over GET /kvblocks/<digest>")
    # fleet membership (serve/router.py POST /register)
    p.add_argument("--router-url", default=None,
                   help="router base url: heartbeat POST /register so "
                        "this replica joins (and re-joins after a "
                        "restart) without being on the router's argv")
    p.add_argument("--register-interval-s", type=float, default=2.0,
                   help="registration heartbeat cadence")
    # front-end / admission / drain
    p.add_argument("--max-queue-depth", type=int, default=64)
    p.add_argument("--drain-deadline-s", type=float, default=30.0)
    p.add_argument("--default-max-new-tokens", type=int, default=32)
    p.add_argument("--default-deadline-ms", type=float, default=None)
    # front-door security (serve/aio.py) + slow-client eviction
    p.add_argument("--tls-cert", default=None,
                   help="PEM certificate chain: serve https on the "
                        "asyncio transport (requires --tls-key)")
    p.add_argument("--tls-key", default=None,
                   help="PEM private key for --tls-cert")
    p.add_argument("--auth-token", default=None,
                   help="require 'Authorization: Bearer <token>' on "
                        "every route except /healthz (401 otherwise)")
    p.add_argument("--write-deadline-s", type=float, default=30.0,
                   help="slow-client eviction: a stream whose client "
                        "stops draining our writes for this long is "
                        "aborted and its engine work cancelled")
    # observability / postmortem
    p.add_argument("--dir-interval-s", type=float, default=0.25,
                   help="refresh cadence for the /kvprefixes "
                        "advertisement, /debug snapshot and scheduler "
                        "gauges")
    p.add_argument("--watchdog-s", type=float, default=0.0,
                   help="flag an engine step stuck longer than this "
                        "and dump a flight-recorder bundle "
                        "(0 disables the watchdog)")
    p.add_argument("--flightrec-out", default=None,
                   help="directory for postmortem flightrec-*.json "
                        "bundles (omit to keep them in memory only, "
                        "readable via /debug/flightrec)")
    p.add_argument("--flightrec-capacity", type=int, default=256,
                   help="events retained in the flight-recorder ring")
    p.add_argument("--enable-chaos", action="store_true",
                   help="mount GET /debug/stall/<s> (wedges the engine "
                        "loop for <s> seconds — bench/test fault "
                        "injection; NEVER enable in production)")
    # SLO objectives (obs/slo.py default_objectives)
    p.add_argument("--slo-ttft-ms", type=float, default=500.0)
    p.add_argument("--slo-tpot-ms", type=float, default=200.0)
    p.add_argument("--slo-queue-wait-ms", type=float, default=1000.0)
    p.add_argument("--slo-target", type=float, default=0.99)
    p.add_argument("--slo-short-window-s", type=float, default=5.0)
    p.add_argument("--slo-long-window-s", type=float, default=60.0)
    p.add_argument("--slo-burn-threshold", type=float, default=1.0)
    p.add_argument("--slo-min-samples", type=int, default=4)
    p.add_argument("--slo-interval-s", type=float, default=0.25)
    return p


def _ensure_device_visibility(tp_size: int) -> None:
    """--tp-size needs tp_size visible devices. On a CPU host that
    means the XLA virtual-device flag, which only takes effect if set
    BEFORE jax initializes — which is why build_frontend defers every
    jax import until after this runs (main() calls it first). A
    no-op when the flag is already present (e.g. under the test
    suite's conftest) or tp_size == 1."""
    if tp_size <= 1:
        return
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={tp_size}").strip()


def build_frontend(a: argparse.Namespace):
    """Everything up to (not including) start(): importable by tests
    that want an in-process replica with CLI-identical wiring."""
    from paddle_tpu.engine.engine import ServeEngine
    from paddle_tpu.obs.metrics import MetricsRegistry
    from paddle_tpu.obs.slo import SLOMonitor, default_objectives
    from paddle_tpu.serve.frontend import ServeFrontend

    registry = MetricsRegistry()    # private: one process, one story
    if a.model_dir:
        engine = ServeEngine.from_saved_model(
            a.model_dir, max_batch_size=a.max_batch_size,
            block_size=a.block_size, num_blocks=a.num_blocks,
            max_prefill_tokens=a.max_prefill_tokens, tile_q=a.tile_q,
            enable_prefix_cache=not a.no_prefix_cache,
            spec_k=a.spec_k, registry=registry,
            host_tier_bytes=a.host_tier_bytes,
            kv_tier_int8=a.kv_tier_int8,
            kv_compress_blocks=a.kv_compress_blocks,
            tier_spill_dir=a.tier_spill_dir, tp_size=a.tp_size,
            demote_finished=(a.phase == "prefill"))
    else:
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models.transformer import CausalLM

        model = CausalLM(vocab=a.vocab, model_dim=a.model_dim,
                         num_heads=a.num_heads, num_layers=a.num_layers,
                         ffn_dim=a.ffn_dim, dropout=0.0, max_len=a.max_len)
        variables = model.init(jax.random.PRNGKey(a.init_seed),
                               jnp.zeros((1, 4), jnp.int32))
        engine = ServeEngine(
            model, variables, max_batch_size=a.max_batch_size,
            block_size=a.block_size, num_blocks=a.num_blocks,
            max_prefill_tokens=a.max_prefill_tokens, tile_q=a.tile_q,
            enable_prefix_cache=not a.no_prefix_cache,
            spec_k=a.spec_k, registry=registry,
            host_tier_bytes=a.host_tier_bytes,
            kv_tier_int8=a.kv_tier_int8,
            kv_compress_blocks=a.kv_compress_blocks,
            tier_spill_dir=a.tier_spill_dir, tp_size=a.tp_size,
            demote_finished=(a.phase == "prefill"))
    slo = SLOMonitor(
        registry,
        objectives=default_objectives(
            ttft_ms=a.slo_ttft_ms, tpot_ms=a.slo_tpot_ms,
            queue_wait_ms=a.slo_queue_wait_ms, target=a.slo_target),
        short_window_s=a.slo_short_window_s,
        long_window_s=a.slo_long_window_s,
        burn_threshold=a.slo_burn_threshold,
        min_samples=a.slo_min_samples)
    return ServeFrontend(
        engine, host=a.host, port=a.port, slo=slo,
        slo_interval_s=a.slo_interval_s,
        max_queue_depth=a.max_queue_depth,
        drain_deadline_s=a.drain_deadline_s,
        default_max_new_tokens=a.default_max_new_tokens,
        default_deadline_ms=a.default_deadline_ms,
        dir_interval_s=a.dir_interval_s,
        watchdog_s=a.watchdog_s,
        flightrec_out=a.flightrec_out,
        flightrec_capacity=a.flightrec_capacity,
        enable_chaos=a.enable_chaos,
        router_url=a.router_url,
        register_interval_s=a.register_interval_s,
        tier_spill_interval_s=a.tier_spill_interval_s,
        phase=a.phase, tokenizer_seed=a.init_seed,
        tls_cert=a.tls_cert, tls_key=a.tls_key,
        auth_token=a.auth_token,
        write_deadline_s=a.write_deadline_s)


def main(argv: Optional[List[str]] = None) -> int:
    a = build_parser().parse_args(argv)
    _ensure_device_visibility(a.tp_size)
    frontend = build_frontend(a)
    frontend.start().install_signals()
    code = frontend.wait()      # blocks until a drain completes
    frontend._teardown()
    return code if code is not None else 0


if __name__ == "__main__":
    sys.exit(main())

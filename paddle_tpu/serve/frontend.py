"""ServeFrontend: the HTTP/SSE serving front-end over ServeEngine.

One process, one port, two planes:

- DATA PLANE — `POST /v1/completions`: JSON body in, server-sent
  events out (one frame per sampled token tagged with its candidate
  `index` + in-candidate `pos`, a final done frame with the finish
  reason + best token list, then `[DONE]`). `n` requests parallel
  sampling — the engine forks n candidates off ONE shared prefill
  (COW prompt blocks) and their frames interleave on the same
  response; `best_of >= n` decodes extra silent candidates that only
  compete in the mean-logprob ranking the done frame reports.
  Streaming falls out of the engine's iteration-level scheduling: the
  engine thread runs `step()` continuously and per-token callbacks fan
  tokens out to per-request queues that handler COROUTINES drain. A
  client that disconnects mid-stream cancels its whole group — the
  engine frees every candidate's KV blocks (shared prefix blocks drop
  one refcount each) and the loss shows up as
  `requests{reason="cancelled"}`.
  `POST /v1/tokenize` maps a raw string to the ids the completions
  route would prefill (serve/tokenizer.py) — `"prompt"` accepts either
  form. `GET /kvblocks/<digest>` serves this replica's host-tier
  entries to peers, and the router's `x-ptpu-kv-source` hint makes a
  request PULL its warm prefix from the advertising peer before it is
  enqueued (serve/kvxfer.py — disaggregated prefill/decode serving).
- CONTROL PLANE — the same telemetry the engine records is what
  admits, sheds, and drains: `/metrics` (Prometheus scrape),
  `/healthz` (pure liveness), `/readyz` (503 until the one compiled
  step is warm, 503 again once a drain begins — the router and k8s
  probes stop routing here), `/slo` (the SLOMonitor's machine-readable
  verdict). Admission control rejects with 503 while an SLO objective
  BURNS (obs/slo.py multi-window burn rate over the live TTFT /
  TPOT / queue-wait histograms) or the wait queue is full — every shed
  is a labeled `ptpu_serve_sheds_total{reason=...}` increment, so
  overload is observable from the same scrape that caused it.

INTROSPECTION + POSTMORTEM (OBSERVABILITY.md §introspection). Each
request carries a fleet trace id (the router's `x-ptpu-trace` header,
minted locally when absent) that tags its tracer spans and rides the
done frame back to the client; `/trace/<id>` serves that request's
span fragment for the router's cross-process stitcher. `/debug`
exposes the engine-loop-refreshed scheduler/KV-pool/tier snapshot
(handler threads never touch the engine), and a FlightRecorder
(obs/flightrec.py) keeps the recent serve/resilience event ring,
dumping a postmortem bundle on watchdog stall (`watchdog_s` arms a
RunSupervisor watchdog around engine steps), SLO burn onset, drain
deadline, or an engine-loop crash — `/debug/flightrec` shows the
latest bundle. `/debug/stall/<s>` (armed only with `enable_chaos`)
wedges the next engine step on purpose: the serve_bench fleet-obs
cell uses it to prove a real stall produces a bundle naming the
stuck request.

THREADING. The engine is single-threaded by design (compiled steps,
host-side allocator bookkeeping). All engine mutation happens on ONE
loop thread. The connection side is an asyncio event loop on ONE
acceptor thread (serve/aio.py): each connection is a coroutine that
only enqueues work (submissions, cancellations) onto thread-safe
queues and parks on its stream's event, woken from the engine thread
via `loop.call_soon_threadsafe`. Thousands of idle SSE streams cost
coroutines, not OS threads — `ptpu_serve_conn_threads` stays flat
while `ptpu_serve_open_connections` climbs. Disconnects come from the
transport (a parked read resolves on peer close); writes are
backpressured per-connection with a slow-client eviction deadline
(`write_deadline_s` → `ptpu_serve_slow_client_evictions_total`), so a
stalled reader frees its KV instead of wedging the fan-out. The
registry and SLO monitor are thread-safe, so scrapes and admission
checks never touch the engine.

FRONT-DOOR SECURITY. `tls_cert`/`tls_key` wrap the listening
transport in stdlib TLS (the url property flips to https), and
`auth_token` requires `Authorization: Bearer <token>` on every route
except `/healthz` (liveness probes stay credential-free) — mismatch
is a 401 before any routing or admission work happens.

PREEMPTIBILITY. SIGTERM (or `begin_drain()`) flips readiness off,
sheds new work with reason="draining", lets every in-flight stream run
to completion bounded by `drain_deadline_s` (stragglers past the
deadline are cancelled and counted in
`ptpu_serve_drain_cancelled_total`), then stops and reports exit code
75 (resilience/errors.py PREEMPT_EXIT_CODE) — same contract as the
training runtime, so a fleet scheduler can tell "drained clean, safe
to reschedule" from "crashed".
"""

from __future__ import annotations

import asyncio
import json
import queue
import signal
import threading
import time
import uuid
from collections import deque
from http.client import HTTPConnection
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from paddle_tpu.engine.engine import ServeEngine
from paddle_tpu.engine.scheduler import Request
from paddle_tpu.obs.flightrec import FlightRecorder
from paddle_tpu.obs.http import json_route, obs_response
from paddle_tpu.obs.slo import SLOMonitor
from paddle_tpu.resilience.errors import PREEMPT_EXIT_CODE
from paddle_tpu.resilience.supervisor import RunSupervisor
from paddle_tpu.serve.aio import AioConnection, AioRequest, \
    AsyncHTTPServer, SlowClientError, make_server_tls_context
from paddle_tpu.serve.kvxfer import KVXferMetrics, encode_tier_blob, \
    pull_prefix
from paddle_tpu.serve.sse import DONE_SENTINEL, sse_event
from paddle_tpu.serve.tokenizer import ByteTokenizer
from paddle_tpu.utils.log import serve_event

_DIR_INTERVAL_S = 0.25   # default /kvprefixes + /debug refresh cadence


class _Stream:
    """Plumbing for one in-flight completion GROUP (1 primary +
    n - 1 forked candidates share one HTTP response): the engine
    thread feeds `q` via `push()`; the handler coroutine drains it.
    Items: ("token", int, cand_index), ("done", reason, tokens, extra)
    where extra is None for n == 1 and {"best_index", "candidates"}
    for a parallel-sampling group, ("error", message).

    The queue stays a thread-safe `queue.Queue` (warmup drains it
    BLOCKING before any event loop exists); `attach()` bridges it to
    the connection coroutine — after that every push also wakes the
    stream's asyncio.Event via `loop.call_soon_threadsafe`, so a
    parked consumer resumes without polling. `gone` is flipped in-loop
    by the transport disconnect watcher."""

    __slots__ = ("params", "q", "req", "streamed", "cand_pos",
                 "loop", "ev", "gone")

    def __init__(self, params: dict):
        self.params = params
        self.q: "queue.Queue" = queue.Queue()
        self.req: Optional[Request] = None
        self.streamed = 0
        self.cand_pos: Dict[int, int] = {}   # candidate -> tokens sent
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.ev: Optional[asyncio.Event] = None
        self.gone = False

    def attach(self, loop: asyncio.AbstractEventLoop,
               ev: asyncio.Event) -> None:
        """Bind the consumer side; call BEFORE submitting to the
        engine so no push can miss the wake-up."""
        self.ev = ev
        self.loop = loop

    def push(self, item: tuple) -> None:
        """Engine-thread producer: enqueue + wake the parked
        coroutine (a no-op wake before attach/after loop teardown)."""
        self.q.put(item)
        loop, ev = self.loop, self.ev
        if loop is not None and ev is not None:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass                    # loop already closed (teardown)


class ServeFrontend:
    """`ServeFrontend(engine).start()` binds the port (`.port` after
    start — port=0 is ephemeral), spawns the engine loop, and serves
    until `stop()` / a drain completes. `slo=None` builds a monitor
    with default objectives over the engine's registry."""

    def __init__(self, engine: ServeEngine, host: str = "127.0.0.1",
                 port: int = 0, slo: Optional[SLOMonitor] = None,
                 slo_interval_s: float = 0.25,
                 max_queue_depth: int = 64,
                 drain_deadline_s: float = 30.0,
                 default_max_new_tokens: int = 64,
                 default_deadline_ms: Optional[float] = None,
                 warmup: bool = True,
                 dir_interval_s: float = _DIR_INTERVAL_S,
                 watchdog_s: float = 0.0,
                 flightrec_out: Optional[str] = None,
                 flightrec_capacity: int = 256,
                 enable_chaos: bool = False,
                 router_url: Optional[str] = None,
                 register_interval_s: float = 2.0,
                 tier_spill_interval_s: float = 0.0,
                 phase: str = "mixed",
                 tokenizer_seed: int = 0,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 auth_token: Optional[str] = None,
                 write_deadline_s: float = 30.0,
                 sock_sndbuf: int = 0,
                 write_buffer_limit: int = 0):
        self.engine = engine
        self.host = host
        self.port = port
        # front-door security: TLS on the listening transport + bearer
        # auth (everything except /healthz) — both optional, both
        # enforced before any routing happens
        if bool(tls_cert) != bool(tls_key):
            raise ValueError("tls_cert and tls_key must be set together")
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.auth_token = auth_token
        # slow-client eviction: a stream whose peer can't drain a write
        # within this deadline is cancelled (KV freed) and its
        # transport aborted. sock_sndbuf/write_buffer_limit shrink the
        # server-side buffering so tests can trip it with tiny streams.
        self.write_deadline_s = write_deadline_s
        self.sock_sndbuf = sock_sndbuf
        self.write_buffer_limit = write_buffer_limit
        self.obs = engine.obs
        self.slo = slo if slo is not None else SLOMonitor(engine.obs)
        self.slo_interval_s = slo_interval_s
        self.max_queue_depth = max_queue_depth
        self.drain_deadline_s = drain_deadline_s
        self.default_max_new_tokens = default_max_new_tokens
        self.default_deadline_ms = default_deadline_ms
        self.dir_interval_s = dir_interval_s
        self._warmup = warmup
        self._enable_chaos = enable_chaos
        self.exit_code: Optional[int] = None
        # dynamic membership (RESILIENCE.md §fleet): a router url turns
        # on the registration heartbeat — POST /register {"url": ...}
        # every register_interval_s, so the replica joins the fleet
        # without being on the router's argv, and a RESTARTED replica
        # (new process, same port) re-admits itself within one beat.
        self.router_url = router_url.rstrip("/") if router_url else None
        self.register_interval_s = register_interval_s
        # disaggregated serving (serve/kvxfer.py): the phase rides the
        # registration heartbeat and the /kvprefixes advertisement so
        # the router can specialize routing (prefill-heavy traffic to
        # prefill replicas, the decode continuation to decode ones)
        if phase not in ("prefill", "decode", "mixed"):
            raise ValueError(f"phase {phase!r}: want prefill|decode|mixed")
        self.phase = phase
        self._kvx = KVXferMetrics(engine.obs)
        # byte-level front door: string prompts + /v1/tokenize. Needs
        # vocab >= 16; a tiny test vocab just disables string prompts.
        try:
            self.tokenizer: Optional[ByteTokenizer] = ByteTokenizer(
                engine.model.vocab, seed=tokenizer_seed)
        except ValueError:
            self.tokenizer = None
        # warm restarts: > 0 spills the host KV tier to the engine's
        # tier_spill_dir every interval ON TOP of the drain-time spill,
        # so even a SIGKILLed replica warm-starts from a recent
        # snapshot (the spill replaces atomically; a torn write is
        # never visible)
        self.tier_spill_interval_s = tier_spill_interval_s
        self._spill_next = 0.0               # engine-loop thread only
        self._register_thread: Optional[threading.Thread] = None
        self._stop_register = threading.Event()

        self._server: Optional[AsyncHTTPServer] = None
        self._engine_thread: Optional[threading.Thread] = None
        self._work = threading.Event()       # engine loop wake-up
        self._stopped = threading.Event()    # engine loop exited
        self._submit: "deque[_Stream]" = deque()
        self._cancel: "deque[_Stream]" = deque()
        self._lock = threading.Lock()
        self._active: Dict[int, _Stream] = {}    # guarded-by: self._lock
        self._open_streams = 0               # guarded-by: self._lock
        # fleet prefix directory advertisement (/kvprefixes): the
        # engine loop snapshots {len, digest, tier} rows from the
        # prefix index + device int8 compressed pool + host tier
        # (tier in device|device_int8|host, hottest first) every
        # _DIR_INTERVAL_S; handler threads serve the snapshot (never
        # touch the engine)
        self._directory: List[dict] = []     # guarded-by: self._lock
        self._dir_next = 0.0                 # engine-loop thread only
        # /debug snapshot: refreshed on the engine loop at the same
        # cadence as the directory; handler threads serve the copy
        self._debug_snapshot: dict = {}      # guarded-by: self._lock
        self._stall_s = 0.0                  # guarded-by: self._lock
        self._draining = False
        self._drain_started = 0.0
        self._drain_dumped = False           # engine-loop thread only
        self._burn_prev = False              # engine-loop thread only
        self._stop_requested = False
        self._warm = False

        # postmortem plane: the flight recorder taps the process event
        # streams (ring of recent serve/resilience records) and, when
        # watchdog_s > 0, a RunSupervisor watchdog wraps engine steps
        # so a wedged step dumps a bundle while the stall is live
        self.flightrec = FlightRecorder(
            capacity=flightrec_capacity,
            snapshot_fn=self._flight_snapshot,
            out_dir=flightrec_out,
            registry=engine.obs)
        self._sup: Optional[RunSupervisor] = None
        if watchdog_s > 0:
            self._sup = RunSupervisor(
                watchdog_timeout_s=watchdog_s, on_hang=self._on_hang)

        m = self.obs
        self._m_sheds = m.counter(
            "ptpu_serve_sheds_total",
            "Admission rejections (503) by cause",
            labelnames=("reason",))
        self._m_drain_cancelled = m.counter(
            "ptpu_serve_drain_cancelled_total",
            "In-flight streams cancelled at the drain deadline")
        self._m_draining = m.gauge(
            "ptpu_serve_draining", "1 while a drain is in progress")
        self._m_ready = m.gauge(
            "ptpu_serve_ready",
            "1 when /readyz reports ready (warm and not draining)")
        self._m_ready.set(0.0)
        # the asyncio scaling claim, as a gauge pair: connections climb
        # with load, OS threads stay flat (engine loop + acceptor +
        # a constant) — serve_bench's soak cell asserts exactly this
        self._m_open_conns = m.gauge(
            "ptpu_serve_open_connections",
            "Live front-door connections (idle SSE streams park here "
            "as coroutines, not threads)")
        self._m_conn_threads = m.gauge(
            "ptpu_serve_conn_threads",
            "OS threads in the process at the last connection event "
            "(flat vs open_connections under the asyncio front door)")
        self._m_evictions = m.counter(
            "ptpu_serve_slow_client_evictions_total",
            "Streams cancelled at the per-connection write deadline "
            "(stalled readers; their KV blocks are freed)")
        self._m_token_write = m.histogram(
            "ptpu_serve_token_write_seconds",
            "Per-token SSE write+drain latency")

    # -- readiness --------------------------------------------------------
    def readiness(self):
        """The /readyz truth: a replica is routable iff its one
        compiled step is warm (ptpu_engine_compiles >= 1 — explicit
        warmup() or real traffic both warm it) AND it is not
        draining."""
        if not (self._warm or self.engine._m_compiles.value >= 1.0):
            return False, "engine cold (compiled step not warm)"
        if self._draining:
            return False, "draining"
        return True, ""

    def _set_ready_gauge(self) -> None:
        self._m_ready.set(1.0 if self.readiness()[0] else 0.0)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "ServeFrontend":
        if self._server is not None:
            return self
        # The engine loop must be LIVE before warmup: the engine is
        # single-threaded, so the warmup request has to ride the loop
        # like any other submission (stepping from this thread would
        # race it once real traffic lands) — and under tensor-parallel
        # serving the warmup compile IS the sharded step executable,
        # so it must be built through the same path /readyz vouches
        # for. Starting the loop first makes warmup() take its
        # engine-loop branch instead of the direct-generate fallback.
        self._engine_thread = threading.Thread(
            target=self._engine_loop, daemon=True, name="ptpu-serve-engine")
        self._engine_thread.start()
        if self._warmup:
            self.warmup()
        self.slo.start(self.slo_interval_s)
        self.flightrec.install()
        if self._sup is not None:
            self._sup.start_watchdog()
        tls_ctx = None
        if self.tls_cert and self.tls_key:
            tls_ctx = make_server_tls_context(self.tls_cert, self.tls_key)
        # ONE acceptor thread owns the event loop; every connection is
        # a coroutine (serve/aio.py) — HTTP/1.0 close-delimited, no
        # chunking, byte-compatible with the threaded front it replaces
        self._server = AsyncHTTPServer(
            self.host, self.port, self._a_dispatch,
            name="ptpu-serve-http", tls_context=tls_ctx,
            on_open=self._conn_opened, on_close=self._conn_closed,
            write_deadline_s=self.write_deadline_s,
            sock_sndbuf=self.sock_sndbuf,
            write_buffer_limit=self.write_buffer_limit)
        self._server.start()
        self.port = self._server.port
        serve_event("serve_listening", host=self.host, port=self.port,
                    url=self.url)
        if self.router_url:
            self._register_thread = threading.Thread(
                target=self._register_loop, daemon=True,
                name="ptpu-serve-register")
            self._register_thread.start()
        return self

    def _register_once(self) -> bool:
        """One POST /register heartbeat to the router; False when the
        router is unreachable (normal during rolling restarts — the
        next beat retries)."""
        parts = urlsplit(self.router_url)
        try:
            conn = HTTPConnection(parts.hostname, parts.port or 80,
                                  timeout=5.0)
            try:
                conn.request(
                    "POST", "/register",
                    body=json.dumps({"url": self.url,
                                     "phase": self.phase}).encode(),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                return resp.status == 200
            finally:
                conn.close()
        except OSError:
            return False

    def _register_loop(self) -> None:
        registered = False
        while not self._stop_register.is_set():
            ok = self._register_once()
            if ok and not registered:
                serve_event("serve_registered", router=self.router_url,
                            url=self.url)
            registered = ok
            self._stop_register.wait(self.register_interval_s)

    @property
    def url(self) -> str:
        scheme = "https" if self.tls_cert else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def warmup(self) -> None:
        """Run one tiny request through the engine so the single
        compiled step is built BEFORE /readyz flips — a router never
        sees a replica that would compile on its first real request.
        The engine is single-threaded: once the loop thread is live,
        the warmup request must ride it like any other submission
        (stepping from this thread would race the loop)."""
        if self._warm:
            return
        vocab = self.engine.model.vocab
        if self._engine_thread is not None and self._engine_thread.is_alive():
            stream = _Stream({
                "prompt": [vocab - 1] * 2, "max_new_tokens": 2,
                "temperature": 0.0, "top_k": 0, "seed": 0,
                "eos_id": None, "deadline_ms": None})
            self._submit.append(stream)
            self._work.set()
            while True:
                item = stream.q.get(timeout=120)
                if item[0] in ("done", "error"):
                    break
        else:
            self.engine.generate([[vocab - 1] * 2], max_new_tokens=2)
        self.engine.reset_stats()
        # reset_stats zeroes gauges in place; restore the compile gauge
        # from the jit cache — the compiled step really is warm, and
        # /readyz gates on exactly this series
        self.engine._m_compiles.set(self.engine._step_fn._cache_size())
        self._warm = True
        self._set_ready_gauge()

    def install_signals(self) -> "ServeFrontend":
        """SIGTERM/SIGINT -> drain (main thread only: CLI entry)."""
        def _on_signal(signum, frame):
            serve_event("serve_sigterm", signal=int(signum))
            self.begin_drain()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
        return self

    def begin_drain(self) -> None:
        """Stop admitting, finish what's in flight (bounded), exit 75.
        Idempotent; safe from any thread (including a signal
        handler — it only flips flags and an Event)."""
        if self._draining:
            return
        self._draining = True
        self._drain_started = time.monotonic()
        self._m_draining.set(1.0)
        self._set_ready_gauge()
        self._stop_requested = True
        self._work.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        """Block until the engine loop exits (drain complete or
        stop()); returns the exit code (75 for a drain)."""
        self._stopped.wait(timeout)
        return self.exit_code

    def stop(self) -> None:
        """Immediate non-drain shutdown (tests): cancels in-flight work
        and tears the server down without the preempt exit code."""
        self._stop_requested = True
        self._work.set()
        self._stopped.wait(timeout=10)
        self._teardown()

    def _teardown(self) -> None:
        self._stop_register.set()
        if self._register_thread is not None:
            self._register_thread.join(timeout=5)
            self._register_thread = None
        self.slo.stop()
        self.flightrec.uninstall()
        if self._sup is not None:
            self._sup.stop_watchdog()
        if self._server is not None:
            self._server.stop()
            self._server = None

    # -- engine loop ------------------------------------------------------
    def _engine_loop(self) -> None:
        eng = self.engine
        try:
            while True:
                self._drain_control_queues()
                progressed = False
                if eng.scheduler.has_work():
                    progressed = self._step_once()
                    self._flush_finished()
                now = time.monotonic()
                if now >= self._dir_next:
                    self._dir_next = now + self.dir_interval_s
                    snapshot = eng.kv_prefix_directory()
                    debug = eng.debug_state()
                    with self._lock:
                        self._directory = snapshot
                        self._debug_snapshot = debug
                    self._check_slo_burn()
                if (self.tier_spill_interval_s > 0
                        and now >= self._spill_next):
                    self._spill_next = now + self.tier_spill_interval_s
                    self._spill_tier("interval")
                if self._draining:
                    if self._drain_finished():
                        break
                elif self._stop_requested:
                    self._abort_active("shutdown")
                    break
                if not progressed:
                    self._work.wait(0.02)
                    self._work.clear()
        except Exception as e:
            # an engine-loop crash is exactly what the flight recorder
            # exists for: freeze the event ring + engine state before
            # the thread dies, then re-raise so the failure stays loud
            self.flightrec.dump("engine_exception", error=repr(e))
            serve_event("serve_engine_crash", error=repr(e))
            raise
        finally:
            # spill the host tier LAST, with no traffic left to mutate
            # it: the successor process warm-starts from exactly the
            # state the drain left behind
            self._spill_tier("drain")
            if self._draining:
                self.exit_code = PREEMPT_EXIT_CODE
                serve_event("serve_drained",
                            drain_s=round(time.monotonic()
                                          - self._drain_started, 3),
                            exit_code=self.exit_code)
            self._stopped.set()

    def _spill_tier(self, cause: str) -> None:
        """Spill the host KV tier to the engine's tier_spill_dir
        (engine-loop thread only — the tier's lock makes the read
        consistent, the rename makes the write atomic). No-op without
        a tier or a dir; a failed spill is an event, never a crash."""
        eng = self.engine
        if eng.host_tier is None or not eng.tier_spill_dir:
            return
        try:
            blocks = eng.host_tier.spill(eng.tier_spill_dir)
        except OSError as e:
            serve_event("tier_spill_failed", cause=cause, error=repr(e))
            return
        if blocks or cause == "drain":
            serve_event("tier_spill", cause=cause, blocks=blocks,
                        dir=eng.tier_spill_dir)

    def _step_once(self) -> bool:
        """One engine step, under the hung-step watchdog when armed.
        An armed chaos stall (POST /debug/stall/<s>) sleeps INSIDE the
        watched window, so the watchdog observes it exactly like a real
        wedged step and fires the postmortem hook mid-stall."""
        with self._lock:
            stall, self._stall_s = self._stall_s, 0.0
        if self._sup is None:
            if stall:
                time.sleep(stall)
            return self.engine.step()
        with self._sup.watch_step(self.engine.steps):
            if stall:
                time.sleep(stall)
            return self.engine.step()

    def _check_slo_burn(self) -> None:
        """Dump one flight-recorder bundle per burn EPISODE (edge
        trigger): the moment an objective starts burning is when the
        ring still holds the traffic that caused it."""
        burning = self.slo.burning_objectives()
        if burning and not self._burn_prev:
            self.flightrec.dump("slo_burn", objectives=burning)
        self._burn_prev = bool(burning)

    def _on_hang(self, step: int, elapsed: float) -> None:
        """RunSupervisor watchdog callback — runs on the WATCHDOG
        thread while the engine thread is wedged; the snapshot is
        best-effort by design (obs/flightrec.py)."""
        self.flightrec.dump("watchdog_hang", step=step,
                            elapsed_s=round(elapsed, 3))

    def _flight_snapshot(self) -> dict:
        state = self.engine.debug_state()
        with self._lock:
            state["open_streams"] = self._open_streams
            state["active_req_ids"] = sorted(self._active)
        state["draining"] = self._draining
        return state

    def _drain_control_queues(self) -> None:
        """Apply handler-thread intents on the engine thread: new
        submissions, then cancellations (a disconnect may target a
        request submitted moments ago)."""
        while self._submit:
            stream = self._submit.popleft()
            p = stream.params
            n_stream = p.get("n", 1)        # candidates the client sees

            def _fork_cb(i, s=stream, n_stream=n_stream):
                # candidates in [n, best_of) decode silently: they only
                # compete in the best-of ranking, never reach the wire
                if i >= n_stream:
                    return None
                return lambda tok, s=s, i=i: s.push(("token", tok, i))

            try:
                req = self.engine.add_request(
                    p["prompt"], max_new_tokens=p["max_new_tokens"],
                    temperature=p["temperature"], top_k=p["top_k"],
                    seed=p["seed"], eos_id=p["eos_id"],
                    deadline_ms=p["deadline_ms"],
                    n=p.get("best_of", 1),
                    fork_callback=_fork_cb,
                    callback=lambda tok, s=stream: s.push(("token", tok, 0)))
                stream.req = req
                self.engine.tracer.set_trace_id(
                    req.req_id, p.get("trace_id"))
                with self._lock:
                    self._active[req.req_id] = stream
            except Exception as e:       # bad prompt: surface as 400
                stream.push(("error", str(e)))
        while self._cancel:
            stream = self._cancel.popleft()
            if stream.req is not None:
                # a disconnect tears down the WHOLE group: every
                # candidate's block refs drop, shared prompt refcounts
                # return to baseline
                self.engine.cancel_group(stream.req)
                with self._lock:
                    self._active.pop(stream.req.req_id, None)

    @staticmethod
    def _group_done(req: Request) -> bool:
        """A stream's done frame goes out when its WHOLE group is
        terminal: the primary plus every fork. Before the fork happens
        (mid-prefill) only a cancellation is terminal — any other
        finish implies the prefill completed, which forks first."""
        if not req.finish_reason:
            return False
        if req.n_candidates == 1:
            return True
        if len(req.forks) < req.n_candidates - 1:
            return req.finish_reason == "cancelled"
        return all(f.finish_reason for f in req.forks)

    @staticmethod
    def _rank_group(req: Request) -> "tuple[int, list]":
        """best-of-n ranking: mean per-token log-probability under each
        candidate's own sampling distribution (sum would just prefer
        short outputs). Ties break to the LOWEST candidate index, so
        n == best_of degenerates deterministically to candidate 0's
        behavior under greedy (all candidates identical)."""
        cands = sorted([req] + req.forks, key=lambda r: r.cand_index)
        infos = [{"index": r.cand_index,
                  "tokens": ServeEngine._generated_of(r),
                  "reason": r.finish_reason,
                  "logprob": round(
                      r.logprob_sum / max(1, len(r.generated)), 6)}
                 for r in cands]
        best = max(infos, key=lambda c: c["logprob"])
        return best["index"], infos

    def _flush_finished(self) -> None:
        """Push done frames for request GROUPS the last step finished
        (for n > 1 the frame waits until every candidate is done)."""
        with self._lock:
            done = [(rid, s) for rid, s in self._active.items()
                    if s.req is not None and self._group_done(s.req)]
            for rid, _ in done:
                del self._active[rid]
        for rid, s in done:
            if s.req.n_candidates == 1:
                s.push(("done", s.req.finish_reason,
                        ServeEngine._generated_of(s.req), None))
            else:
                best_idx, cands = self._rank_group(s.req)
                best = cands[best_idx]
                n_stream = s.params.get("n", 1)
                s.push(("done", best["reason"], best["tokens"],
                        {"best_index": best_idx,
                         # silent best_of-only candidates stay
                         # server-side; the wire sees n candidates
                         "candidates": cands[:n_stream]}))

    def _drain_finished(self) -> bool:
        """True once every in-flight stream completed (or the deadline
        cancelled it) and no handler is still writing."""
        deadline_hit = (time.monotonic() - self._drain_started
                        > self.drain_deadline_s)
        if deadline_hit:
            if not self._drain_dumped:
                # dump BEFORE aborting so the snapshot still names the
                # streams the deadline is about to cancel
                self._drain_dumped = True
                with self._lock:
                    stuck = sorted(self._active)
                self.flightrec.dump("drain_deadline", stuck_req_ids=stuck)
            self._abort_active("drain_deadline", count_drain=True)
        with self._lock:
            # read both under the lock: a handler that already popped its
            # stream from _active but hasn't finished its final write yet
            # is only visible through _open_streams.
            engine_idle = not self._active
            streams_open = self._open_streams > 0
        return (engine_idle and not self.engine.scheduler.has_work()
                and (not streams_open or deadline_hit))

    def _abort_active(self, reason: str, count_drain: bool = False) -> None:
        with self._lock:
            aborted = list(self._active.values())
            self._active.clear()
        for s in aborted:
            if s.req is not None:
                self.engine.cancel_group(s.req)
                if count_drain:
                    self._m_drain_cancelled.inc()
            s.push(("done", "cancelled", [], None))

    def _directory_payload(self) -> dict:
        """The /kvprefixes body: this replica's warm-prefix
        advertisement for the router's fleet prefix directory, plus its
        serving phase (argv-seeded replicas never POST /register, so
        the phase has to ride the scrape). `direct_int8` advertises the
        mixed-step direct-read capability: with it the router prices
        this replica's device_int8 rows like device-fp rows (no promote
        round-trip on a hit); older replicas never send the field and
        keep the old ordering."""
        with self._lock:
            return {"prefixes": list(self._directory),
                    "phase": self.phase,
                    "direct_int8": bool(getattr(self.engine,
                                                "kv_direct_int8", False))}

    def _debug_payload(self) -> dict:
        """The /debug body: the engine-loop-refreshed scheduler/KV
        snapshot plus front-end stream state — everything a handler
        thread can serve without touching the engine."""
        with self._lock:
            return {
                "engine": dict(self._debug_snapshot),
                "open_streams": self._open_streams,
                "active_req_ids": sorted(self._active),
                "draining": self._draining,
                "warm": self._warm,
                "dir_interval_s": self.dir_interval_s,
                "watchdog_s": (self._sup.watchdog_timeout_s
                               if self._sup is not None else 0.0),
            }

    def _kvblocks_route(self, path: str):
        """GET /kvblocks/<digest> -> one host-tier entry in the kvxfer
        wire envelope (serve/kvxfer.py), or 404 when this replica does
        not hold it. Served straight off the handler thread: the tier
        is thread-safe and the engine loop is never involved, so a
        peer's pull can never stall this replica's own decoding."""
        digest = path[len("/kvblocks/"):].strip("/")
        tier = self.engine.host_tier
        blob = (encode_tier_blob(tier, digest)
                if tier is not None and digest else None)
        if blob is None:
            return (404, "application/json",
                    b'{"error": "unknown kv block"}\n')
        return 200, "application/octet-stream", blob

    def _trace_route(self, path: str):
        """GET /trace/<id> -> this replica's span fragment for one
        fleet trace id (404 when the id never landed here — the router
        probes every replica and keeps the ones that answer)."""
        tid = path[len("/trace/"):].strip("/")
        frag = self.engine.tracer.trace_fragment(tid)
        if not tid or frag is None:
            return 404, "application/json", b'{"error": "unknown trace"}\n'
        return (200, "application/json",
                json.dumps(frag).encode() + b"\n")

    def _stall_route(self, path: str):
        """GET /debug/stall/<seconds> (chaos builds only): arm a
        deliberate sleep inside the next WATCHED engine step — the
        fleet-obs bench cell's way of inducing a real stall."""
        if not self._enable_chaos:
            return (403, "application/json",
                    b'{"error": "chaos routes disabled"}\n')
        tail = path[len("/debug/stall"):].strip("/")
        try:
            seconds = float(tail) if tail else 1.0
        except ValueError:
            return 400, "application/json", b'{"error": "bad seconds"}\n'
        seconds = max(0.0, min(seconds, 30.0))
        with self._lock:
            self._stall_s = seconds
        self._work.set()
        return (200, "application/json",
                json.dumps({"stall_s": seconds}).encode() + b"\n")

    # -- connection events (acceptor-loop thread) -------------------------
    def _conn_opened(self) -> None:
        self._m_open_conns.inc()
        self._m_conn_threads.set(float(threading.active_count()))

    def _conn_closed(self) -> None:
        self._m_open_conns.dec()
        self._m_conn_threads.set(float(threading.active_count()))

    # -- HTTP handlers (coroutines on the serve/aio.py loop) --------------
    async def _a_dispatch(self, req: AioRequest,
                          conn: AioConnection) -> None:
        if self.auth_token and req.path.split("?")[0] != "/healthz":
            # /healthz stays credential-free: a liveness probe must
            # never fail for a config (secret-rotation) reason
            if req.header("authorization", "") \
                    != f"Bearer {self.auth_token}":
                await conn.send(401, "application/json",
                                b'{"error": "unauthorized"}\n',
                                {"WWW-Authenticate": "Bearer"})
                return
        if req.method == "GET":
            await self._a_get(req, conn)
        elif req.method == "POST":
            await self._a_post(req, conn)
        else:
            await conn.send(405, "text/plain", b"method not allowed\n")

    async def _a_get(self, req: AioRequest, conn: AioConnection) -> None:
        self._set_ready_gauge()     # traffic may have warmed the engine
        resp = obs_response(
            req.path, self.obs, readiness=self.readiness,
            routes={"/slo": json_route(self.slo.verdict),
                    "/kvprefixes": json_route(self._directory_payload),
                    "/debug": json_route(self._debug_payload),
                    "/debug/flightrec": json_route(
                        self.flightrec.debug_payload)},
            prefix_routes={"/trace/": self._trace_route,
                           "/debug/stall": self._stall_route,
                           "/kvblocks/": self._kvblocks_route})
        if resp is None:
            resp = (404, "text/plain", b"not found\n")
        await conn.send(*resp)

    async def _a_shed(self, conn: AioConnection, reason: str) -> None:
        self._m_sheds.labels(reason=reason).inc()
        serve_event("serve_shed", reason=reason,
                    queue_depth=self.engine.scheduler.queue_depth)
        body = json.dumps({"error": "overloaded", "reason": reason,
                           "retry_after_s": 1.0}).encode() + b"\n"
        await conn.send(503, "application/json", body,
                        {"Retry-After": "1"})

    def _admission_shed_reason(self) -> Optional[str]:
        """Why a new request must bounce, or None to admit. Order
        matters: a draining replica sheds everything; a full queue is
        backpressure regardless of SLO state; then the SLO verdict."""
        if self._draining or self._stop_requested:
            return "draining"
        if self.engine.scheduler.queue_depth >= self.max_queue_depth:
            return "queue_full"
        burning = self.slo.burning_objectives()
        if burning:
            return f"slo_{burning[0]}"
        return None

    def _parse_completion(self, req: AioRequest
                          ) -> Tuple[Optional[dict], Optional[bytes]]:
        """(params, None), or (None, body) for a 400 response."""
        try:
            body = json.loads(req.body or b"{}")
            prompt = body["prompt"]
            if isinstance(prompt, str):
                if self.tokenizer is None:
                    raise ValueError(
                        "string prompts need the byte tokenizer "
                        "(model vocab < 16)")
                prompt = self.tokenizer.encode(prompt)
            elif (not isinstance(prompt, list)
                    or not all(isinstance(t, int) for t in prompt)):
                raise ValueError(
                    "prompt must be a list of token ids or a string")
            n = int(body.get("n", 1))
            best_of = int(body.get("best_of", n))
            if n < 1:
                raise ValueError(f"n {n} < 1")
            if best_of < n:
                raise ValueError(
                    f"best_of {best_of} < n {n}: the ranked pool must "
                    "contain every returned candidate")
            return {
                "prompt": prompt,
                "max_new_tokens": int(body.get(
                    "max_new_tokens", self.default_max_new_tokens)),
                "temperature": float(body.get("temperature", 0.0)),
                "top_k": int(body.get("top_k", 0)),
                "seed": int(body.get("seed", 0)),
                "eos_id": body.get("eos_id"),
                "deadline_ms": body.get("deadline_ms",
                                        self.default_deadline_ms),
                "stream": bool(body.get("stream", True)),
                "n": n,
                "best_of": best_of,
                # fleet trace id: the router propagates its minted id
                # via x-ptpu-trace; a direct client gets one minted
                # here, so every stream is traceable either way
                "trace_id": (req.header("x-ptpu-trace")
                             or uuid.uuid4().hex[:16]),
            }, None
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            return None, json.dumps({"error": str(e)}).encode() + b"\n"

    async def _a_tokenize(self, req: AioRequest,
                          conn: AioConnection) -> None:
        """POST /v1/tokenize: {"text": "..."} (or "prompt") -> the
        token ids /v1/completions would prefill for that string.
        Engine-free — the mapping is pure (vocab, seed)."""
        try:
            body = json.loads(req.body or b"{}")
            text = body.get("text", body.get("prompt"))
            if not isinstance(text, str):
                raise ValueError('want {"text": "<string>"}')
            if self.tokenizer is None:
                raise ValueError(
                    "no tokenizer: model vocab < 16")
            tokens = self.tokenizer.encode(text)
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            await conn.send(400, "application/json",
                            json.dumps({"error": str(e)}).encode() + b"\n")
            return
        payload = {"tokens": tokens, "count": len(tokens),
                   "vocab": self.tokenizer.vocab,
                   "seed": self.tokenizer.seed}
        await conn.send(200, "application/json",
                        json.dumps(payload).encode() + b"\n")

    def _maybe_pull_kv(self, req: AioRequest, prompt: List[int]) -> None:
        """Honor the router's transfer hint (x-ptpu-kv-source): pull
        the warm prefix from the named peer into OUR host tier before
        the request is enqueued, so admission's revival walk finds the
        blocks as if they were local. Blocking HTTP — the async
        handler runs it in the loop's executor; a failed pull just
        means the request re-prefills."""
        source = req.header("x-ptpu-kv-source")
        tier = self.engine.host_tier
        if not source or tier is None or source.rstrip("/") == self.url:
            return
        max_len = None
        raw_len = req.header("x-ptpu-kv-len")
        if raw_len is not None:
            try:
                max_len = int(raw_len)
            except ValueError:
                max_len = None
        pull_prefix(tier, source.rstrip("/"), prompt,
                    self.engine.cache.block_size, metrics=self._kvx,
                    max_len=max_len)

    async def _a_post(self, req: AioRequest, conn: AioConnection) -> None:
        path = req.path.split("?")[0]
        if path == "/v1/tokenize":
            await self._a_tokenize(req, conn)
            return
        if path != "/v1/completions":
            await conn.send(404, "text/plain", b"not found\n")
            return
        params, err = self._parse_completion(req)
        if params is None:
            await conn.send(400, "application/json", err)
            return
        reason = self._admission_shed_reason()
        if reason is not None:
            await self._a_shed(conn, reason)
            return
        if req.header("x-ptpu-kv-source"):
            # blocking peer pull: off the loop, into the executor
            await asyncio.get_running_loop().run_in_executor(
                None, self._maybe_pull_kv, req, params["prompt"])
        stream = _Stream(params)
        # bind the wake-up bridge BEFORE the engine can see the stream
        stream.attach(asyncio.get_running_loop(), asyncio.Event())
        with self._lock:
            self._open_streams += 1
        try:
            self._submit.append(stream)
            self._work.set()
            if params["stream"]:
                await self._a_stream_response(conn, stream)
            else:
                await self._a_aggregate_response(conn, stream)
        finally:
            with self._lock:
                self._open_streams -= 1

    def _stream_timeout(self, params: dict) -> float:
        """Worst-case seconds to wait for the next queue item before
        declaring the engine wedged."""
        if params["deadline_ms"] is not None:
            return max(params["deadline_ms"] / 1e3 * 4, 30.0)
        return 300.0

    @staticmethod
    async def _a_next_item(stream: _Stream,
                           deadline: float) -> Optional[tuple]:
        """Next queue item, or None at the absolute loop-time
        deadline, or ("gone",) when the disconnect watcher fired. The
        clear-check-wait order makes the wake-up race-free: a push
        landing between the empty get and the wait re-sets the event
        AFTER the clear, so the wait returns immediately."""
        loop = asyncio.get_running_loop()
        while True:
            stream.ev.clear()
            try:
                return stream.q.get_nowait()
            except queue.Empty:
                pass
            if stream.gone:
                return ("gone",)
            remaining = deadline - loop.time()
            if remaining <= 0:
                return None
            try:
                await asyncio.wait_for(stream.ev.wait(), remaining)
            except asyncio.TimeoutError:
                pass

    async def _a_stream_response(self, conn: AioConnection,
                                 stream: _Stream) -> None:
        # the transport tells us about a hang-up the moment it
        # happens — an SSE client sends nothing after its request, so
        # a completed read (EOF or RST) means it is gone, even while
        # the stream is parked between tokens
        def _gone() -> None:
            stream.gone = True
            stream.ev.set()
        conn.watch_disconnect(_gone)
        deadline = (asyncio.get_running_loop().time()
                    + self._stream_timeout(stream.params))
        try:
            await conn.start_sse()
            while True:
                item = await self._a_next_item(stream, deadline)
                if item is None or item[0] == "gone":
                    # engine wedged past the deadline, or client left
                    self._request_cancel(stream)
                    return
                if item[0] == "token":
                    _, tok, cand = item
                    pos = stream.cand_pos.get(cand, 0)
                    # `index` tags the CANDIDATE (parallel sampling);
                    # `pos` is the token's position within that
                    # candidate's stream
                    t0 = time.perf_counter()
                    await conn.write(sse_event(
                        {"token": tok, "index": cand, "pos": pos}))
                    self._m_token_write.observe(time.perf_counter() - t0)
                    stream.cand_pos[cand] = pos + 1
                    stream.streamed += 1
                elif item[0] == "done":
                    _, reason, tokens, extra = item
                    frame = {"done": True, "reason": reason,
                             "tokens": tokens,
                             "req_id": stream.req.req_id
                             if stream.req else None,
                             "trace_id": stream.params.get("trace_id")}
                    if extra is not None:
                        frame.update(extra)
                    await conn.write(sse_event(frame)
                                     + sse_event(DONE_SENTINEL))
                    return
                else:                              # ("error", msg)
                    await conn.write(sse_event(
                        {"error": item[1], "done": True,
                         "reason": "error"}) + sse_event(DONE_SENTINEL))
                    return
        except SlowClientError:
            # the peer stopped draining: its transport is already
            # aborted — evict the stream so its KV frees NOW
            self._m_evictions.inc()
            serve_event("serve_slow_client_evicted",
                        req_id=stream.req.req_id if stream.req else None,
                        streamed=stream.streamed,
                        deadline_s=self.write_deadline_s)
            self._request_cancel(stream)
        except (ConnectionError, OSError):
            # client went away mid-stream: free its KV now
            self._request_cancel(stream)
        finally:
            conn.cancel_watch()

    async def _a_aggregate_response(self, conn: AioConnection,
                                    stream: _Stream) -> None:
        tokens: List[int] = []
        timeout = self._stream_timeout(stream.params)
        loop = asyncio.get_running_loop()
        while True:
            item = await self._a_next_item(stream, loop.time() + timeout)
            if item is None:
                self._request_cancel(stream)
                await conn.send(504, "application/json",
                                b'{"error": "timed out"}\n')
                return
            if item[0] == "token":
                if item[2] == 0:        # aggregate body reports best /
                    tokens.append(item[1])   # candidate list, not a mix
            elif item[0] == "done":
                _, reason, full, extra = item
                payload = {
                    "tokens": full or tokens, "reason": reason,
                    "req_id": stream.req.req_id if stream.req else None,
                    "trace_id": stream.params.get("trace_id"),
                }
                if extra is not None:
                    payload.update(extra)
                body = json.dumps(payload).encode() + b"\n"
                await conn.send(200, "application/json", body)
                return
            elif item[0] == "error":
                await conn.send(400, "application/json",
                                json.dumps({"error": item[1]}).encode()
                                + b"\n")
                return

    def _request_cancel(self, stream: _Stream) -> None:
        self._cancel.append(stream)
        self._work.set()

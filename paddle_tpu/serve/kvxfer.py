"""Fleet KV block transfer: ship host-tier blocks between replicas.

The fleet prefix directory (serve/router.py /kvprefixes) can route a
REQUEST to warm KV, but until now the blocks themselves were pinned to
the replica that computed them — a request forced onto a different
replica (phase routing, failover, load) re-prefilled from scratch.
This module moves the blocks instead:

- SERVE. Every replica with a host tier exposes its content-keyed
  entries on `GET /kvblocks/<digest>` (serve/frontend.py). The body is
  one tier entry in the same npz encoding the tier's disk spill uses
  (engine/kvtier.py): a length-prefixed JSON manifest carrying the
  exact token key, layer/slot layout, dtypes and a crc32 over the npz
  bytes, then the npz itself. Blobs go out STILL ENCODED — fp entries
  stay bit-exact, int8 entries keep their original scales — so
  revival on the puller dequantizes identically to the source.
- PULL. When the router's plan finds the longest warm prefix on a
  replica OTHER than the routed target, it attaches transfer hints
  (`x-ptpu-kv-source`, `x-ptpu-kv-len`) instead of re-routing. The
  target's HTTP handler thread pulls every full-block prefix it is
  missing BEFORE enqueueing the request (`pull_prefix`), inserting the
  raw blobs into its own HostKVTier. Admission then revives them over
  the existing staged-DMA path (PagedKVCache.alloc_sequence): the one
  compiled step never recompiles and the output is byte-identical to
  a local-warm hit.
- NEVER A WRONG ANSWER. Every blob is crc-checked AND its decoded
  token key is required to be an exact prefix of the incoming prompt
  (a digest collision or stale advertisement can only cost a pull,
  never poison the tier). Any failure — connect refused, black-holed
  socket, torn body, crc mismatch (resilience/chaos.py can inject all
  of these) — abandons the transfer, counts
  `ptpu_kvxfer_fallbacks_total`, and the request simply re-prefills.

Counters `ptpu_kvxfer_{blocks,bytes,pulls,fallbacks}_total` and the
`ptpu_kvxfer_pull_ms` histogram live on the engine registry, so the
transfer plane shows up in the same scrape as the tier it feeds
(OBSERVABILITY.md "Metric inventory").
"""

from __future__ import annotations

import io
import json
import struct
import time
import zlib
from http.client import HTTPConnection
from typing import List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

import numpy as np

from paddle_tpu.engine.kvtier import HostKVTier, prefix_digest
from paddle_tpu.obs.metrics import MetricsRegistry
from paddle_tpu.resilience import chaos
from paddle_tpu.utils.log import serve_event

# wire envelope: 4-byte big-endian manifest length, manifest JSON,
# then the npz bytes the manifest's crc32 covers
_HDR = struct.Struct(">I")
_WIRE_VERSION = 1

DEFAULT_TIMEOUT_S = 5.0


class KVXferError(ValueError):
    """A blob failed to decode/verify (torn wire, crc mismatch, key or
    mode mismatch). Always caught inside pull_prefix — a transfer
    failure degrades to re-prefill, never surfaces to the client."""


class KVXferMetrics:
    """The transfer plane's series, registered on the engine registry
    (same story as the tier's own counters). All traffic counters —
    zeroed by the post-warmup reset like every other serve series."""

    def __init__(self, registry: MetricsRegistry):
        self.blocks = registry.counter(
            "ptpu_kvxfer_blocks_total",
            "Host-tier blocks pulled from a peer replica's /kvblocks")
        self.bytes = registry.counter(
            "ptpu_kvxfer_bytes_total",
            "Wire bytes of pulled KV blobs (envelope included)")
        self.pulls = registry.counter(
            "ptpu_kvxfer_pulls_total",
            "Transfer attempts (one per hinted request that was "
            "missing at least one block)")
        self.fallbacks = registry.counter(
            "ptpu_kvxfer_fallbacks_total",
            "Transfers abandoned to plain re-prefill (connect/stream "
            "failure, crc or key mismatch)")
        self.pull_ms = registry.histogram(
            "ptpu_kvxfer_pull_ms",
            "Wall latency of one pull_prefix transfer (all blocks)")


# -- wire encode/decode ------------------------------------------------------

def encode_entry(key: tuple, blobs: list, nbytes: int,
                 int8: bool) -> bytes:
    """Serialize one raw tier entry (as HostKVTier.entry_by_digest
    hands it over) into the wire envelope. Slot naming matches the
    disk spill's per-entry layout (`l{layer}_p{part}`)."""
    arrays = {}
    slots: List[str] = []
    dtypes: List[str] = []
    for j, blob in enumerate(blobs):
        if int8:
            kq, ks, vq, vs, dtype = blob
            parts = (kq, ks, vq, vs)
            dtypes.append(np.dtype(dtype).name)
        else:
            parts = blob
        for p, arr in enumerate(parts):
            slot = f"l{j}_p{p}"
            arrays[slot] = np.asarray(arr)
            slots.append(slot)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    npz = buf.getvalue()
    manifest = json.dumps({
        "version": _WIRE_VERSION, "int8": bool(int8),
        "crc32": zlib.crc32(npz),
        "key": [int(t) for t in key], "layers": len(blobs),
        "nbytes": int(nbytes), "slots": slots, "dtypes": dtypes,
    }).encode()
    return _HDR.pack(len(manifest)) + manifest + npz


def decode_entry(payload: bytes, int8: bool
                 ) -> Tuple[tuple, list, int]:
    """Parse + verify one wire envelope back into (key, blobs,
    nbytes), raising KVXferError on ANY defect. Mirrors the spill
    loader exactly: int8 scales come back as python floats and dtypes
    as np.dtype, so dequantization is bit-identical to the source
    tier's own revival."""
    try:
        if len(payload) < _HDR.size:
            raise KVXferError("short envelope")
        (mlen,) = _HDR.unpack(payload[:_HDR.size])
        manifest_raw = payload[_HDR.size:_HDR.size + mlen]
        npz = payload[_HDR.size + mlen:]
        if len(manifest_raw) != mlen:
            raise KVXferError("torn manifest")
        manifest = json.loads(manifest_raw)
        if manifest.get("version") != _WIRE_VERSION:
            raise KVXferError(f"wire version {manifest.get('version')}")
        if bool(manifest.get("int8")) != bool(int8):
            raise KVXferError("int8 mode mismatch")
        if zlib.crc32(npz) != manifest.get("crc32"):
            raise KVXferError("crc mismatch")
        arrays = np.load(io.BytesIO(npz))
        key = tuple(int(t) for t in manifest["key"])
        blobs = []
        slots = iter(manifest["slots"])
        for j in range(int(manifest["layers"])):
            if int8:
                kq, ks, vq, vs = (arrays[next(slots)] for _ in range(4))
                blobs.append((kq, float(ks), vq, float(vs),
                              np.dtype(manifest["dtypes"][j])))
            else:
                blobs.append((arrays[next(slots)], arrays[next(slots)]))
        return key, blobs, int(manifest["nbytes"])
    except KVXferError:
        raise
    except (KeyError, ValueError, TypeError, OSError, struct.error,
            zlib.error, StopIteration, json.JSONDecodeError) as e:
        raise KVXferError(f"{type(e).__name__}: {e}") from e


def encode_tier_blob(tier: HostKVTier, digest: str) -> Optional[bytes]:
    """The /kvblocks/<digest> body for one advertised entry, or None
    when this tier doesn't hold it (the route 404s). Thread-safe:
    blob payloads are immutable, serialization runs outside the tier
    lock — HTTP handler threads serve this directly."""
    ent = tier.entry_by_digest(digest)
    if ent is None:
        return None
    key, blobs, nbytes = ent
    return encode_entry(key, blobs, nbytes, tier.int8)


# -- pull client -------------------------------------------------------------

def pull_prefix(tier: HostKVTier, source_url: str,
                tokens: Sequence[int], block_size: int,
                metrics: Optional[KVXferMetrics] = None,
                max_len: Optional[int] = None,
                timeout: float = DEFAULT_TIMEOUT_S) -> int:
    """Pull every full-block prefix of `tokens` that `source_url`
    holds and this tier is missing, shortest first (the revival walk
    in alloc_sequence is contiguous from the device match on). Runs on
    the serve front-end's HANDLER thread, before the request is
    enqueued — the engine loop never blocks on the network. Returns
    blocks inserted; NEVER raises — any failure counts a fallback and
    leaves the tier exactly as it was, so the caller just re-prefills.

    `max_len` (the router's x-ptpu-kv-len hint) caps how far past the
    prompt head to probe; without it the loop stops at the source's
    first 404."""
    bs = max(1, int(block_size))
    limit = len(tokens)
    if max_len is not None:
        limit = min(limit, int(max_len))
    wanted = [tuple(tokens[:end]) for end in range(bs, limit + 1, bs)]
    wanted = [k for k in wanted if not tier.contains(k)]
    if not wanted:
        return 0
    if metrics is not None:
        metrics.pulls.inc()
    t0 = time.monotonic()
    inserted = 0
    parts = urlsplit(source_url)
    try:
        for key in wanted:
            digest = prefix_digest(key)
            # one connection per block: the serve front-end speaks
            # HTTP/1.0 (close-delimited SSE), so sockets don't survive
            # across responses
            conn = HTTPConnection(parts.hostname, parts.port or 80,
                                  timeout=timeout)
            try:
                conn.request("GET", f"/kvblocks/{digest}")
                resp = conn.getresponse()
                body = resp.read()
                status = resp.status
            finally:
                conn.close()
            if status == 404:
                break           # source holds nothing longer: done
            if status != 200:
                raise KVXferError(f"source answered {status}")
            body = chaos.maybe_corrupt_kvxfer(body)
            got_key, blobs, nbytes = decode_entry(body, tier.int8)
            if got_key != key:
                # digest collision or a raced advertisement: the blob
                # is NOT the content we asked for — skip it, keep the
                # tier clean, and stop probing this source
                raise KVXferError("key mismatch (digest collision)")
            if tier.insert_encoded(got_key, blobs, nbytes):
                inserted += 1
                if metrics is not None:
                    metrics.blocks.inc()
                    metrics.bytes.inc(len(body))
    except (OSError, KVXferError) as e:
        if metrics is not None:
            metrics.fallbacks.inc()
        serve_event("kvxfer_fallback", source=source_url,
                    pulled=inserted, error=f"{type(e).__name__}: {e}")
    if metrics is not None:
        metrics.pull_ms.observe((time.monotonic() - t0) * 1e3)
    if inserted:
        serve_event("kvxfer_pull", source=source_url, blocks=inserted,
                    prefix_tokens=inserted * bs,
                    ms=round((time.monotonic() - t0) * 1e3, 3))
    return inserted

"""HTTP/SSE serving front-end + multi-replica router (SERVING over the
engine subsystem — the ROADMAP's "millions of users" story).

- `sse` — server-sent-events framing + a stdlib streaming client
  (what the router proxy, serve_bench and the tests consume with);
- `frontend` — `ServeFrontend`: one HTTP port per replica serving
  `POST /v1/completions` (SSE token streaming, client-disconnect
  cancellation that frees KV blocks, per-request deadlines feeding the
  scheduler's preemption choice), admission control shedding on SLO
  burn (obs/slo.py), and the observability surface (`/metrics`,
  `/healthz`, `/readyz`, `/slo`) on the same port; SIGTERM drains
  in-flight streams to a bounded deadline and exits 75
  (resilience/errors.py PREEMPT_EXIT_CODE) so replicas are
  preemptible;
- `router` — `Router`: spreads traffic across N replicas with
  prefix-hash sticky routing (the shared-system-prompt hit rate
  survives scale-out), ranking fallbacks by each replica's scraped
  `ptpu_kv_hit_rate` / `ptpu_sched_queue_depth` gauges;
- `replica` — CLI entry point (`python -m paddle_tpu.serve.replica`)
  booting a model + engine + front-end in one process.
"""

from paddle_tpu.serve.frontend import ServeFrontend
from paddle_tpu.serve.router import Router
from paddle_tpu.serve.sse import (iter_sse, sse_event, stream_completion)

__all__ = ["ServeFrontend", "Router", "sse_event", "iter_sse",
           "stream_completion"]

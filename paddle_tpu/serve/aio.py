"""Asyncio connection layer shared by the serve front-end and router.

The engine was designed for this split from day one: ALL engine
mutation happens on ONE engine-loop thread, and connection handlers
only touch thread-safe queues. So the connection side is free to be an
event loop instead of a thread per connection — thousands of idle SSE
streams become coroutines parked on queues, and the process holds a
CONSTANT number of OS threads no matter how many clients are attached
(`ptpu_serve_conn_threads` vs `ptpu_serve_open_connections` is the
scaling claim, and serve_bench's `soak` cell measures it).

One daemon "acceptor" thread owns a private event loop and an
`asyncio.start_server`. Each accepted connection runs `_client()`:
parse ONE request (HTTP/1.0 style — SSE bodies are close-delimited, no
chunking, `Connection: close`), invoke the async handler, close. That
is byte-compatible with the stdlib `http.client` front the tests and
the SSE client (serve/sse.py) already speak.

What the loop buys over ThreadingHTTPServer:

- DISCONNECTS come from the transport: a parked `reader.read()`
  coroutine resolves the moment the peer closes, replacing the old
  per-stream `select` + `MSG_PEEK` poll.
- BACKPRESSURE is per-connection: every write awaits
  `writer.drain()` under a deadline (`write_deadline_s`); a client
  that stops reading trips `SlowClientError`, the transport is
  aborted, and the caller evicts the stream (frees its KV) instead of
  wedging a handler thread on a full socket buffer.
- TLS is one `ssl.SSLContext` on the listening transport
  (`make_server_tls_context`), no extra moving parts.

Blocking sub-paths that async handlers still need (KV prefix pulls,
replica probes) go through `loop.run_in_executor` — the default
executor is a small bounded pool, so the thread count stays flat.
"""

from __future__ import annotations

import asyncio
import json
import socket
import ssl
import threading
from http.client import responses as _STATUS_TEXT
from typing import Awaitable, Callable, Dict, Optional, Tuple

from paddle_tpu.serve.sse import DONE_SENTINEL

_MAX_BODY_BYTES = 16 * 1024 * 1024    # absurdly-large-body guard
_REQUEST_TIMEOUT_S = 30.0             # header+body must arrive by then


class SlowClientError(Exception):
    """The peer failed to drain our writes within the write deadline:
    a stalled reader. The transport has already been aborted when this
    raises — the caller's job is to cancel the stream's engine work."""


class AioRequest:
    """One parsed request: method, path, lower-cased header dict, and
    the (possibly empty) body bytes — already fully read, so handlers
    never touch the socket for input."""

    __slots__ = ("method", "path", "headers", "body")

    def __init__(self, method: str, path: str, headers: Dict[str, str],
                 body: bytes):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body

    def header(self, name: str, default: Optional[str] = None
               ) -> Optional[str]:
        return self.headers.get(name.lower(), default)


class AioConnection:
    """The write half handed to handlers: deadline-bounded writes,
    response helpers, and the transport-level disconnect watch."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 write_deadline_s: float = 30.0):
        self.reader = reader
        self.writer = writer
        self.write_deadline_s = write_deadline_s
        self._watch_task: Optional[asyncio.Task] = None

    async def write(self, data: bytes) -> None:
        """Write + drain under the slow-client deadline. On deadline
        the transport is ABORTED (RST, not a lingering FIN) before
        SlowClientError raises, so the stalled peer can never pin
        kernel buffers for a closed stream."""
        self.writer.write(data)
        try:
            await asyncio.wait_for(self.writer.drain(),
                                   self.write_deadline_s)
        except asyncio.TimeoutError:
            self.abort()
            raise SlowClientError(
                f"client failed to drain within "
                f"{self.write_deadline_s:.1f}s") from None

    async def send(self, status: int, ctype: str, body: bytes,
                   extra_headers: Optional[dict] = None) -> None:
        """One complete close-delimited response."""
        text = _STATUS_TEXT.get(status, "")
        head = [f"HTTP/1.0 {status} {text}",
                f"Content-Type: {ctype}",
                f"Content-Length: {len(body)}"]
        for k, v in (extra_headers or {}).items():
            head.append(f"{k}: {v}")
        head.append("Connection: close")
        await self.write("\r\n".join(head).encode("latin-1")
                         + b"\r\n\r\n" + body)

    async def start_sse(self) -> None:
        """Response head for a close-delimited SSE body (no
        Content-Length: the stream length is unknown by design)."""
        await self.write(b"HTTP/1.0 200 OK\r\n"
                         b"Content-Type: text/event-stream\r\n"
                         b"Cache-Control: no-cache\r\n"
                         b"Connection: close\r\n\r\n")

    def watch_disconnect(self, on_gone: Callable[[], None]) -> None:
        """Park a coroutine on the read half: an SSE client sends
        nothing after its request, so ANY read completion (EOF or RST)
        means it hung up — the transport tells us the moment it
        happens, between tokens included. Replaces the old per-stream
        MSG_PEEK poll."""
        async def _watch():
            try:
                while True:
                    data = await self.reader.read(4096)
                    if not data:
                        break
            except (ConnectionError, OSError):
                pass
            on_gone()
        self._watch_task = asyncio.get_running_loop().create_task(_watch())

    def cancel_watch(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None

    def abort(self) -> None:
        """Hard-drop the transport (no FIN handshake, no draining)."""
        transport = self.writer.transport
        if transport is not None:
            transport.abort()

    async def close(self) -> None:
        self.cancel_watch()
        try:
            if self.writer.can_write_eof():
                self.writer.write_eof()
        except (ConnectionError, OSError, RuntimeError):
            pass
        try:
            self.writer.close()
            await asyncio.wait_for(self.writer.wait_closed(), 5.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass


def make_server_tls_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    """Server-side TLS for the listening transport (stdlib ssl only)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


async def read_http_request(reader: asyncio.StreamReader
                            ) -> Optional[AioRequest]:
    """Parse one request off the stream; None on immediate EOF (the
    peer connected and left), ValueError on a malformed request."""
    line = await reader.readline()
    if not line:
        return None
    parts = line.decode("latin-1", "replace").split()
    if len(parts) < 2:
        raise ValueError("malformed request line")
    method, path = parts[0], parts[1]
    headers: Dict[str, str] = {}
    while True:
        raw = await reader.readline()
        if raw in (b"\r\n", b"\n", b""):
            break
        key, _, val = raw.decode("latin-1", "replace").partition(":")
        headers[key.strip().lower()] = val.strip()
    try:
        length = int(headers.get("content-length", "0") or "0")
    except ValueError:
        raise ValueError("malformed Content-Length")
    if not 0 <= length <= _MAX_BODY_BYTES:
        raise ValueError(f"body length {length} out of bounds")
    body = await reader.readexactly(length) if length else b""
    return AioRequest(method, path, headers, body)


# handler signature: receives the parsed request and the connection
Handler = Callable[[AioRequest, AioConnection], Awaitable[None]]


class AsyncHTTPServer:
    """`asyncio.start_server` on a private loop owned by ONE daemon
    acceptor thread. `start()` returns once the port is bound (read it
    back from `.port` — port=0 is ephemeral); `stop()` tears the loop
    down from any thread. `on_open`/`on_close` fire in-loop around
    each connection (the open-connections gauge). `sock_sndbuf` /
    `write_buffer_limit` shrink the server-side buffering so tests can
    trip the slow-client deadline with small streams."""

    def __init__(self, host: str, port: int, handler: Handler,
                 name: str = "ptpu-aio",
                 tls_context: Optional[ssl.SSLContext] = None,
                 on_open: Optional[Callable[[], None]] = None,
                 on_close: Optional[Callable[[], None]] = None,
                 write_deadline_s: float = 30.0,
                 sock_sndbuf: int = 0,
                 write_buffer_limit: int = 0,
                 request_timeout_s: float = _REQUEST_TIMEOUT_S):
        self.host = host
        self.port = port
        self.handler = handler
        self.name = name
        self.tls_context = tls_context
        self.on_open = on_open
        self.on_close = on_close
        self.write_deadline_s = write_deadline_s
        self.sock_sndbuf = sock_sndbuf
        self.write_buffer_limit = write_buffer_limit
        self.request_timeout_s = request_timeout_s
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._boot_error: Optional[BaseException] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "AsyncHTTPServer":
        if self._thread is not None:
            return self
        self.loop = asyncio.new_event_loop()
        bound = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(bound,), daemon=True, name=self.name)
        self._thread.start()
        bound.wait(timeout=30)
        if self._boot_error is not None:
            raise self._boot_error
        return self

    def _run(self, bound: threading.Event) -> None:
        loop = self.loop
        asyncio.set_event_loop(loop)
        try:
            server = loop.run_until_complete(asyncio.start_server(
                self._client, self.host, self.port, ssl=self.tls_context))
            self._server = server
            self.port = server.sockets[0].getsockname()[1]
        except OSError as e:
            self._boot_error = e
            bound.set()
            loop.close()
            return
        bound.set()
        try:
            loop.run_forever()
        finally:
            server.close()
            loop.run_until_complete(server.wait_closed())
            pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
            for t in pending:
                t.cancel()
            if pending:
                loop.run_until_complete(asyncio.gather(
                    *pending, return_exceptions=True))
            loop.close()

    def stop(self) -> None:
        """Safe from any thread; idempotent."""
        loop, self.loop = self.loop, None
        thread, self._thread = self._thread, None
        if loop is not None and thread is not None:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass
            thread.join(timeout=10)

    def call_soon_threadsafe(self, fn: Callable[[], None]) -> bool:
        """Bridge for non-loop threads (the engine loop's token
        callbacks); False once the loop is gone."""
        loop = self.loop
        if loop is None:
            return False
        try:
            loop.call_soon_threadsafe(fn)
            return True
        except RuntimeError:
            return False

    # -- per-connection ---------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if self.sock_sndbuf and sock is not None:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                            self.sock_sndbuf)
        if self.write_buffer_limit:
            writer.transport.set_write_buffer_limits(
                high=self.write_buffer_limit)
        conn = AioConnection(reader, writer,
                             write_deadline_s=self.write_deadline_s)
        if self.on_open is not None:
            self.on_open()
        try:
            try:
                req = await asyncio.wait_for(read_http_request(reader),
                                             self.request_timeout_s)
            except (ValueError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError):
                await conn.send(400, "application/json",
                                b'{"error": "bad request"}\n')
                return
            if req is None:
                return
            await self.handler(req, conn)
        except SlowClientError:
            pass        # transport already aborted; stream was evicted
        except (ConnectionError, OSError):
            pass        # peer vanished mid-response
        finally:
            await conn.close()
            if self.on_close is not None:
                self.on_close()


async def aiter_sse(reader: asyncio.StreamReader,
                    timeout_s: Optional[float] = None):
    """Async twin of sse.iter_sse: yield each frame's data payload —
    INCLUDING the `[DONE]` sentinel, then stop; EOF mid-frame yields
    the partial frame (the consumer sees the truncation). `timeout_s`
    bounds each line read (asyncio.TimeoutError on a stalled peer —
    the async stand-in for a socket read timeout)."""
    data_lines = []
    while True:
        if timeout_s is not None:
            raw = await asyncio.wait_for(reader.readline(), timeout_s)
        else:
            raw = await reader.readline()
        if not raw:                       # EOF mid-stream: truncated
            if data_lines:
                yield "\n".join(data_lines)
            return
        line = raw.decode("utf-8", "replace").rstrip("\r\n")
        if line.startswith("data:"):
            data_lines.append(line[5:].lstrip(" "))
            continue
        if line == "" and data_lines:     # blank line: dispatch frame
            payload = "\n".join(data_lines)
            data_lines = []
            yield payload
            if payload == DONE_SENTINEL:
                return


async def aio_http_request(host: str, port: int, method: str, path: str,
                           body: Optional[bytes] = None,
                           headers: Optional[dict] = None,
                           connect_timeout_s: float = 5.0
                           ) -> Tuple[int, Dict[str, str],
                                      asyncio.StreamReader,
                                      asyncio.StreamWriter]:
    """Async upstream request (the router's relay half): connect,
    send, parse the status line + headers, hand back the live reader
    so the caller can stream the close-delimited body (aiter_sse for
    SSE, read() for JSON). The caller owns closing the writer."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), connect_timeout_s)
    try:
        payload = body or b""
        head = [f"{method} {path} HTTP/1.0",
                f"Host: {host}:{port}",
                f"Content-Length: {len(payload)}"]
        for k, v in (headers or {}).items():
            head.append(f"{k}: {v}")
        head.append("Connection: close")
        writer.write("\r\n".join(head).encode("latin-1")
                     + b"\r\n\r\n" + payload)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(),
                                             connect_timeout_s)
        parts = status_line.decode("latin-1", "replace").split(None, 2)
        if len(parts) < 2 or not parts[1].isdigit():
            raise OSError(f"malformed status line {status_line!r}")
        status = int(parts[1])
        resp_headers: Dict[str, str] = {}
        while True:
            raw = await asyncio.wait_for(reader.readline(),
                                         connect_timeout_s)
            if raw in (b"\r\n", b"\n", b""):
                break
            key, _, val = raw.decode("latin-1", "replace").partition(":")
            resp_headers[key.strip().lower()] = val.strip()
        return status, resp_headers, reader, writer
    except BaseException:
        writer.transport.abort()
        raise


async def aio_read_body(reader: asyncio.StreamReader,
                        headers: Dict[str, str],
                        timeout_s: float = 30.0) -> bytes:
    """Read a non-SSE response body: Content-Length when present,
    close-delimited otherwise."""
    raw_len = headers.get("content-length")
    if raw_len is not None:
        try:
            return await asyncio.wait_for(
                reader.readexactly(int(raw_len)), timeout_s)
        except (ValueError, asyncio.IncompleteReadError):
            return b""
    return await asyncio.wait_for(reader.read(_MAX_BODY_BYTES), timeout_s)


def close_writer_abruptly(writer: asyncio.StreamWriter) -> None:
    """Drop an upstream connection without awaiting the close
    handshake (hedging loser, failover teardown)."""
    try:
        writer.transport.abort()
    except (ConnectionError, OSError, RuntimeError):
        pass


def json_body(obj) -> bytes:
    return json.dumps(obj).encode() + b"\n"

"""Server-sent-events framing + a stdlib streaming HTTP client.

SSE is the transport of the serving front-end (frontend.py): one
`data:` frame per sampled token, a final `{"done": true, ...}` frame
carrying the finish reason and full token list, then the `[DONE]`
sentinel. A client that received `[DONE]` saw an UNTRUNCATED stream —
that is the invariant the SIGTERM drain test and serve_bench's router
scenario assert (zero streams cut off mid-generation).

The client half rides http.client (no third-party deps): it keeps the
socket exposed so a test can CLOSE it mid-stream — exactly how a
browser cancels — and the front-end turns the resulting write failure
into `engine.cancel()`.
"""

from __future__ import annotations

import json
import ssl
from http.client import HTTPConnection, HTTPSConnection
from typing import Iterator, Optional, Tuple
from urllib.parse import urlsplit

DONE_SENTINEL = "[DONE]"


def sse_event(data) -> bytes:
    """One SSE frame. Dicts are JSON-encoded; strings pass through
    (the `[DONE]` sentinel)."""
    payload = data if isinstance(data, str) else json.dumps(data)
    return f"data: {payload}\n\n".encode()


def iter_sse(fp) -> Iterator[str]:
    """Yield the data payload of each SSE frame from a readable byte
    stream — INCLUDING the `[DONE]` sentinel, then stop (so a consumer
    can tell a clean end from an EOF truncation). Multi-line data
    frames are joined per the SSE spec; comment/field lines are
    ignored."""
    data_lines = []
    while True:
        raw = fp.readline()
        if not raw:                       # EOF mid-stream: truncated
            if data_lines:
                yield "\n".join(data_lines)
            return
        line = raw.decode("utf-8", "replace").rstrip("\r\n")
        if line.startswith("data:"):
            data_lines.append(line[5:].lstrip(" "))
            continue
        if line == "" and data_lines:     # blank line: dispatch frame
            payload = "\n".join(data_lines)
            data_lines = []
            yield payload
            if payload == DONE_SENTINEL:
                return


class SSEStream:
    """A live streaming completion: iterate `events()` for decoded
    frames; `close()` mid-iteration drops the socket (client
    cancellation). `done` flips only when `[DONE]` arrived — a stream
    that ends without it was truncated."""

    def __init__(self, conn: HTTPConnection, resp):
        self._conn = conn
        self.resp = resp
        self.status = resp.status
        self.done = False
        self.events_seen = 0

    def events(self) -> Iterator[dict]:
        for payload in iter_sse(self.resp):
            if payload == DONE_SENTINEL:
                self.done = True
                break
            self.events_seen += 1
            yield json.loads(payload)
        self.close()

    def __iter__(self) -> Iterator[dict]:
        return self.events()

    def close(self) -> None:
        # close the RESPONSE too: it holds its own reference to the
        # socket (makefile), so closing only the connection would leave
        # the fd open and the server would never see the disconnect
        for obj in (self.resp, self._conn):
            try:
                obj.close()
            except Exception:
                pass


def _connect(url: str, timeout: float) -> Tuple[HTTPConnection, str]:
    parts = urlsplit(url)
    if parts.scheme == "https":
        # serve fronts run self-signed certs (make_server_tls_context):
        # encrypt the hop, skip hostname/CA verification — this client
        # talks to replicas it just started, not the open internet
        conn: HTTPConnection = HTTPSConnection(
            parts.hostname, parts.port or 443, timeout=timeout,
            context=ssl._create_unverified_context())
    else:
        conn = HTTPConnection(parts.hostname, parts.port or 80,
                              timeout=timeout)
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    return conn, path


def stream_completion(base_url: str, payload: dict,
                      timeout: float = 120.0,
                      headers: Optional[dict] = None) -> SSEStream:
    """POST `payload` to `{base_url}/v1/completions` and return the
    live SSE stream (status != 200 means shed/error — read
    `.resp.read()` for the body). Extra `headers` merge over the
    defaults — how a client pins its own `x-ptpu-trace` id."""
    conn, _ = _connect(base_url, timeout)
    body = json.dumps(payload).encode()
    hdrs = {"Content-Type": "application/json",
            "Accept": "text/event-stream"}
    if headers:
        hdrs.update(headers)
    conn.request("POST", "/v1/completions", body=body, headers=hdrs)
    return SSEStream(conn, conn.getresponse())


def collect_stream(base_url: str, payload: dict,
                   timeout: float = 120.0,
                   headers: Optional[dict] = None) -> dict:
    """Drive one streaming completion to the end; returns
    {status, tokens, done (saw [DONE]), final (the done frame or
    None), trace_id (from the done frame — the handle for the fleet's
    /trace/<id>), shed_body (on non-200)}."""
    s = stream_completion(base_url, payload, timeout=timeout,
                          headers=headers)
    if s.status != 200:
        body = s.resp.read().decode("utf-8", "replace")
        s.close()
        return {"status": s.status, "tokens": [], "done": False,
                "final": None, "trace_id": None, "shed_body": body}
    tokens, final = [], None
    for ev in s.events():
        if "token" in ev:
            tokens.append(ev["token"])
        if ev.get("done"):
            final = ev
    return {"status": 200, "tokens": tokens, "done": s.done,
            "final": final,
            "trace_id": (final or {}).get("trace_id"),
            "shed_body": None}


def http_get(url: str, timeout: float = 10.0) -> Tuple[int, str]:
    """Tiny GET helper (scrapes, probes): (status, body)."""
    conn, path = _connect(url, timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def parse_prometheus_values(text: str) -> dict:
    """Flat {series: value} view of a Prometheus text exposition —
    labelled series key as `name{a="x"}` verbatim, unlabelled as
    `name`. What the router's scrape loop and serve_bench's verdicts
    read replicas' gauges/counters with."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            out[key] = float(val)
        except ValueError:
            continue
    return out

"""Router: one front door over N serve replicas.

Scale-out story (ROADMAP "serve millions"): each replica is a
ServeFrontend process with its own engine, KV pool and telemetry; the
router is a thin streaming proxy that decides WHICH replica sees a
request and otherwise copies bytes. Three decisions, all driven by the
replicas' own scraped telemetry — the router holds no model state:

- STICKY PREFIX ROUTING. Prefix caching only pays when requests that
  share a prompt prefix land on the SAME replica (each engine's block
  pool is private). The primary replica is a stable hash — crc32, not
  Python's per-process-salted `hash()` — of the first `prefix_len`
  prompt tokens, modulo the READY set: every request with the same
  system prompt hashes to the same replica while the fleet is stable,
  so the fleet-wide hit rate tracks the single-replica hit rate
  instead of decaying ~1/N (serve_bench's router scenario measures
  exactly this). When a replica dies, the hash re-maps over the
  survivors only — no request is sticky to a corpse.
- FLEET PREFIX DIRECTORY. The hash is a degenerate directory (it
  predicts where a prefix SHOULD be warm); the real one is scraped:
  each replica advertises its warm prefixes on /kvprefixes as
  (length, crc32 digest, tier) rows — "device" for prefix-index
  blocks still in the pool, "host" for blocks demoted to the RAM tier
  (engine/kvtier.py). plan_route checks the incoming prompt against
  the directory and prefers the READY replica holding the LONGEST
  matching prefix at the HOTTEST tier (device beats host beats
  nothing), falling back to the hash primary when no replica has it.
  After a restart, rebalance, or failover the directory finds warm KV
  wherever it actually lives instead of where the hash says it should.
  A digest collision can only misroute (the receiving replica
  re-matches on exact tokens before reusing anything) — a perf risk,
  never a correctness one.
- TELEMETRY-RANKED FALLBACK. When the primary is not routable (failed
  /readyz: cold or draining; scrape failure; or it sheds 503), the
  request falls back to the remaining ready replicas ranked by their
  scraped `ptpu_kv_hit_rate` (desc — a warm cache serves a prefix
  cheapest) then `ptpu_sched_queue_depth` (asc — shortest line). The
  scrape loop refreshes each replica's gauges every
  `scrape_interval_s` on a daemon thread.
- DRAIN, SAME CONTRACT AS REPLICAS. SIGTERM stops admission (503
  reason="draining"), lets in-flight proxied streams finish to a
  bounded deadline, and exits PREEMPT_EXIT_CODE (75) — a router is as
  preemptible as the replicas behind it.

FLEET FAULT TOLERANCE (RESILIENCE.md §fleet). The router is where a
replica failure is turned back into a successful client request:

- DYNAMIC MEMBERSHIP. The argv replica list is only the bootstrap
  seed: replicas heartbeat `POST /register {"url": ...}` and are
  admitted once a health probe passes. Every replica carries a
  circuit breaker (closed -> open -> half-open): `breaker_fails`
  consecutive scrape/connect failures open it — the replica is
  evicted from routing — and after `breaker_open_s` ONE half-open
  probe per scrape tick decides rejoin vs re-open. A re-register from
  an evicted replica forces the probe immediately, so a warm restart
  is routable within one scrape interval.
- RETRY BUDGET. Failover re-attempts draw from a RetryBudget token
  bucket (resilience/retry.py) deposited by successful traffic: when
  the whole fleet degrades, the bucket drains and requests shed 503
  reason="retry_budget" instead of amplifying the overload into a
  retry storm.
- HEDGED REQUESTS. A request whose first response byte hasn't arrived
  after ~`hedge_ttft_mult` x the scraped TTFT p95 fires ONE hedge to
  the next-ranked replica; first response wins, the loser's connection
  is closed so its engine cancels and its KV blocks free. Hedges spend
  the same retry budget (no hedge storms either).
- FAILOVER WITH STREAM RESUME. The relay is frame-level (SSE), not
  byte-level: when a replica dies mid-stream the router re-sends the
  request to the next candidate and SKIPS the frames the client
  already has — decode is greedy and every replica holds identical
  weights, so the replayed frames are identical and the client sees
  one untruncated stream ending in `[DONE]`.

DISAGGREGATED SERVING (serve/kvxfer.py). With `kv_transfer` on, a
directory hit on a replica OTHER than the routed target no longer
re-routes the request — the router attaches transfer hints
(`x-ptpu-kv-source`: the advertising replica's url, `x-ptpu-kv-len`:
the matched prefix length) and the target PULLS the warm blocks into
its own host tier before admission. Replicas also advertise a serving
PHASE (`prefill` | `decode` | `mixed`, via /register and /kvprefixes):
when the fleet has a ready replica of the wanted phase, requests are
classified by prompt-vs-decode weight (prompt len >=
`phase_prefill_ratio` x max_new_tokens -> prefill-heavy) and sharded
over the matching replicas first — a prefill replica computes and
demotes the prefix, the decode replica pulls it and streams. A failed
pull costs nothing here: the target just re-prefills.

The relay is unbuffered per frame, so the `[DONE]` untruncated-stream
invariant survives the extra hop, and a client disconnect propagates:
the router's write fails, it drops the replica connection, the
replica's write fails, the engine cancels and frees KV blocks.

FLEET OBSERVABILITY (OBSERVABILITY.md §fleet). The router is also the
fleet's one observability front door:

- every proxied request gets a TRACE ID (minted here, or the client's
  own `x-ptpu-trace` passed through) injected on the replica hop; the
  router records its own route/relay spans under the same id, and
  `GET /trace/<id>` fetches each replica's span fragment and stitches
  router + replica rows into ONE Chrome trace with per-process pids —
  TTFT decomposes hop by hop;
- `GET /metrics/fleet` scrapes every replica's exposition and serves
  the federated merge (obs/fleetmetrics.py): counters sum exactly,
  log-bucketed histograms merge bucket-by-bucket (identical layout by
  construction), gauges re-label per replica;
- `GET /debug` is the replica table as the router sees it — ready
  state, breaker state, scraped gauges, prefix-directory size, and
  scrape staleness (also exported as
  `ptpu_router_scrape_age_seconds{replica}`, so routing-on-stale-data
  is visible on the scrape plane too). Scrapes run on per-replica
  threads with their own `scrape_timeout_s`, so one wedged replica
  cannot stall the loop past its interval — its staleness gauge just
  keeps growing while the rest of the fleet stays fresh.

ASYNC FRONT DOOR (serve/aio.py). The router's connection layer is the
same asyncio server the replicas use: every client stream is a
coroutine on one acceptor-thread event loop, client disconnects come
from the transport (the relay's write fails immediately, not at the
next frame), and client writes are backpressured per-connection with a
slow-client deadline — a stalled reader is aborted instead of pinning
a relay. Upstream replica hops are plain asyncio connections
(aio_http_request); the hedge race that used to burn two threads per
hedged request is two coroutines on the same loop. Blocking sub-paths
(scrape probes on /register, the /metrics/fleet and /trace fan-outs)
run on the default executor — the router's thread count is constant
in the number of attached clients, exactly like the replicas'.

FLEET ADMISSION (opt-in: `fleet_admission`). The scrape loop already
reads each replica's exposition; with admission on it also lifts the
replica's own SLO burn-rate verdicts (`ptpu_slo_burning{objective=…}`
gauges, obs/slo.py) into the routing table. A request whose planned
primary is burning its error budget sheds HERE — 503 + Retry-After at
the router, `ptpu_router_fleet_sheds_total{reason="primary_burn"}` —
before the burning replica spends admission work on it, and is
deliberately NOT spilled onto the healthy remainder (pushing a hot
shard's traffic onto its neighbours is how one burning replica
torches the fleet). When EVERY candidate is burning the request sheds
reason="fleet_burn". Burn state is exported per replica as
`ptpu_router_replica_burning` whether or not admission is enforcing.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import re
import signal
import threading
import time
import uuid
import zlib
from http.client import HTTPConnection
from http.client import responses as _STATUS_TEXT
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from paddle_tpu.obs.fleetmetrics import federate
from paddle_tpu.obs.http import CONTENT_TYPE, json_route, obs_response
from paddle_tpu.obs.metrics import MetricsRegistry
from paddle_tpu.obs.tracing import RequestTracer, stitch_fragments
from paddle_tpu.resilience.errors import PREEMPT_EXIT_CODE
from paddle_tpu.resilience.retry import RetryBudget
from paddle_tpu.serve.aio import (AioConnection, AioRequest,
                                  AsyncHTTPServer, SlowClientError,
                                  aio_http_request, aio_read_body,
                                  aiter_sse, close_writer_abruptly)
from paddle_tpu.serve.sse import (DONE_SENTINEL, parse_prometheus_values,
                                  sse_event)
from paddle_tpu.utils.log import serve_event


def prefix_shard(prompt: Sequence[int], n: int, prefix_len: int = 32) -> int:
    """Stable shard index for a prompt: crc32 over the first
    `prefix_len` token ids (little-endian u32 each) mod n. Identical
    prefixes -> identical replica, across processes and runs."""
    head = list(prompt[:prefix_len])
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little") for t in head)
    return zlib.crc32(raw) % max(n, 1)


def prefix_digest(tokens: Sequence[int]) -> str:
    """8-hex-digit digest of a token prefix: crc32 over the ids as
    little-endian u32. MUST match engine/kvtier.py's prefix_digest
    (the replica side of the /kvprefixes advertisement) — duplicated
    here so a standalone router never imports the engine stack;
    tests/test_kvtier.py pins the two functions equal."""
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little")
                   for t in tokens)
    return format(zlib.crc32(raw), "08x")


# directory tier ranking: a device-resident fp prefix serves with zero
# copies, a device-int8 one needs only an on-device dequantize promotion
# (no DMA), a host-tier one needs a DMA revival, anything else
# re-prefills. A replica advertising the direct_int8 capability on
# /kvprefixes reads int8 blocks in place — no promote at all — so
# _directory_best re-prices ITS device_int8 rows up to the device rank;
# the table itself keeps the legacy ordering for older replicas.
_TIER_RANK = {"device": 2, "device_int8": 1, "host": 0}

# breaker state as a gauge level (ptpu_router_breaker_state)
_BREAKER_LEVEL = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

# advertised serving phase as a gauge level (ptpu_router_replica_phase)
_PHASE_LEVEL = {"mixed": 0.0, "prefill": 1.0, "decode": 2.0}

_LE_RE = re.compile(r'le="([^"]+)"')

# a replica's own SLO burn verdict in its exposition (obs/slo.py):
# ptpu_slo_burning{objective="queue_wait"} 1.0 while the short window
# burns error budget faster than the alert threshold
_SLO_BURN_RE = re.compile(r'^ptpu_slo_burning\{objective="([^"]+)"\}$')


def _bucket_quantile(vals: dict, family: str, q: float) -> float:
    """histogram_quantile over a flat scrape dict (same walk as
    serve_bench's verdicts): smallest bucket bound covering the q-rank,
    NaN when the family has no samples."""
    per_le: Dict[float, float] = {}
    prefix = family + "_bucket{"
    for key, v in vals.items():
        if not key.startswith(prefix):
            continue
        m = _LE_RE.search(key)
        if not m:
            continue
        le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
        per_le[le] = per_le.get(le, 0.0) + v
    if not per_le:
        return float("nan")
    bounds = sorted(per_le)
    total = per_le[bounds[-1]]
    if total <= 0:
        return float("nan")
    rank = q * total
    for le in bounds:
        if per_le[le] >= rank:
            return le
    return float("inf")


class ReplicaState:
    """What the scrape loop knows about one replica right now."""

    __slots__ = ("url", "host", "port", "ready", "reason", "hit_rate",
                 "queue_depth", "last_scrape", "prefixes", "fails",
                 "breaker", "open_until", "ttft_p95_ms", "registered",
                 "scraping", "phase", "burning", "direct_int8")

    def __init__(self, url: str):
        parts = urlsplit(url)
        self.url = url.rstrip("/")
        self.host = parts.hostname
        self.port = parts.port or 80
        self.ready = False
        self.reason = "never scraped"
        self.hit_rate = 0.0
        self.queue_depth = 0.0
        self.last_scrape = 0.0
        # fleet prefix directory rows: {(len, digest): tier}
        self.prefixes: Dict[Tuple[int, str], str] = {}
        # circuit breaker: consecutive scrape/connect failures ->
        # closed -> open (evicted) -> half_open (one probe) -> closed
        self.fails = 0
        self.breaker = "closed"
        self.open_until = 0.0
        self.ttft_p95_ms = 0.0
        self.registered = False     # joined via POST /register
        self.scraping = False       # a scrape thread is on it right now
        # disaggregated serving phase (prefill|decode|mixed): from the
        # /register heartbeat or the /kvprefixes advertisement
        self.phase = "mixed"
        # SLO objectives the replica itself reports as burning
        # (ptpu_slo_burning gauges at 1.0) — fleet admission's input
        self.burning: Tuple[str, ...] = ()
        # mixed-step direct-read capability from /kvprefixes: int8
        # prefix rows on this replica serve without a promote
        self.direct_int8 = False


class _RelayState:
    """Per-request relay progress shared across failover attempts:
    whether the client already has status+headers, and how many data
    frames it has received (replayed frames up to `sent` are skipped
    on a resumed stream)."""

    __slots__ = ("started", "sent")

    def __init__(self):
        self.started = False
        self.sent = 0


class _Upstream:
    """One open replica response: parsed status + lower-cased headers
    plus the live reader for the close-delimited body. close() aborts
    the transport (no FIN handshake) — dropping a replica stream this
    way is what makes its engine cancel and free KV blocks."""

    __slots__ = ("status", "headers", "reader", "writer")

    def __init__(self, status: int, headers: Dict[str, str],
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.status = status
        self.headers = headers
        self.reader = reader
        self.writer = writer

    def getheader(self, name: str, default: Optional[str] = None
                  ) -> Optional[str]:
        return self.headers.get(name.lower(), default)

    def close(self) -> None:
        close_writer_abruptly(self.writer)


class Router:
    """`Router(["http://h:p1", "http://h:p2"]).start()` binds `.port`
    and proxies `/v1/completions`; `/metrics`, `/healthz`, `/readyz`
    describe the router itself (ready iff >=1 replica is ready). The
    url list is the bootstrap seed — replicas may also join live via
    `POST /register`."""

    def __init__(self, replica_urls: Sequence[str],
                 host: str = "127.0.0.1", port: int = 0,
                 prefix_len: int = 32,
                 scrape_interval_s: float = 0.5,
                 drain_deadline_s: float = 30.0,
                 connect_timeout_s: float = 10.0,
                 enable_directory: bool = True,
                 scrape_timeout_s: float = 2.0,
                 breaker_fails: int = 3,
                 breaker_open_s: float = 2.0,
                 retry_budget_ratio: float = 0.2,
                 retry_budget_burst: float = 16.0,
                 enable_hedge: bool = True,
                 hedge_ttft_mult: float = 3.0,
                 hedge_min_s: float = 0.05,
                 hedge_max_s: float = 2.0,
                 kv_transfer: bool = False,
                 phase_prefill_ratio: float = 2.0,
                 fleet_admission: bool = False):
        self.replicas = [ReplicaState(u) for u in replica_urls]
        self.host = host
        self.port = port
        self.prefix_len = prefix_len
        # False reverts routing to pure hash stickiness (A/B baseline)
        self.enable_directory = enable_directory
        self.scrape_interval_s = scrape_interval_s
        self.drain_deadline_s = drain_deadline_s
        self.connect_timeout_s = connect_timeout_s
        self.scrape_timeout_s = scrape_timeout_s
        self.breaker_fails = max(1, int(breaker_fails))
        self.breaker_open_s = breaker_open_s
        self.enable_hedge = enable_hedge
        self.hedge_ttft_mult = hedge_ttft_mult
        self.hedge_min_s = hedge_min_s
        self.hedge_max_s = hedge_max_s
        # kv_transfer flips a directory hit from RE-ROUTING (promote
        # the advertising replica) to TRANSFER HINTS (keep the routed
        # target, tell it where to pull the warm blocks from). Opt-in:
        # re-routing is still the right default for a homogeneous
        # fleet with no transfer plane.
        self.kv_transfer = kv_transfer
        # prompt_len >= ratio * max_new_tokens classifies a request as
        # prefill-heavy when phase-specialized replicas exist
        self.phase_prefill_ratio = phase_prefill_ratio
        # opt-in: shed at the router when the planned replica reports
        # ptpu_slo_burning (see the FLEET ADMISSION docstring section)
        self.fleet_admission = fleet_admission
        self.exit_code: Optional[int] = None

        self.obs = MetricsRegistry()    # the router's OWN process story
        self.retry_budget = RetryBudget(ratio=retry_budget_ratio,
                                        burst=retry_budget_burst,
                                        registry=self.obs)
        self._m_routed = self.obs.counter(
            "ptpu_router_requests_total",
            "Requests proxied, by replica and route kind",
            labelnames=("replica", "kind"))  # kind=primary|directory|fallback
        self._m_sheds = self.obs.counter(
            "ptpu_router_sheds_total",
            "Requests the router itself bounced (503)",
            labelnames=("reason",))  # reason=draining|no_replica|retry_budget
        self._m_replica_ready = self.obs.gauge(
            "ptpu_router_replica_ready", "1 when the replica passes /readyz",
            labelnames=("replica",))
        self._m_replica_hit = self.obs.gauge(
            "ptpu_router_replica_hit_rate",
            "Replica's scraped ptpu_kv_hit_rate", labelnames=("replica",))
        self._m_replica_depth = self.obs.gauge(
            "ptpu_router_replica_queue_depth",
            "Replica's scraped ptpu_sched_queue_depth",
            labelnames=("replica",))
        self._m_inflight = self.obs.gauge(
            "ptpu_router_inflight", "Streams currently being proxied")
        self._m_draining = self.obs.gauge(
            "ptpu_router_draining", "1 while the router drains")
        self._m_dir_hits = self.obs.counter(
            "ptpu_router_directory_hits_total",
            "Requests routed to a replica the prefix directory "
            "identified as holding a warm matching prefix")
        self._m_replica_prefixes = self.obs.gauge(
            "ptpu_router_replica_prefixes",
            "Warm prefixes the replica advertises on /kvprefixes",
            labelnames=("replica",))
        self._m_scrape_age = self.obs.gauge(
            "ptpu_router_scrape_age_seconds",
            "Seconds since the replica's gauges were last scraped "
            "successfully (-1 = never); routing decisions are only as "
            "fresh as this", labelnames=("replica",))
        self._m_retries = self.obs.counter(
            "ptpu_router_retries_total",
            "Failover re-attempts, by what failed on the previous try",
            labelnames=("kind",))       # kind=connect|shed|stream
        self._m_hedges = self.obs.counter(
            "ptpu_router_hedges_total",
            "Hedged requests fired against a slow first replica",
            labelnames=("outcome",))    # outcome=won|lost|denied
        self._m_breaker = self.obs.gauge(
            "ptpu_router_breaker_state",
            "Replica circuit breaker: 0 closed, 1 half-open, 2 open "
            "(evicted from routing)", labelnames=("replica",))
        self._m_membership = self.obs.counter(
            "ptpu_router_membership_events_total",
            "Dynamic-membership transitions",
            labelnames=("event",))      # event=register|evict|rejoin
        self._m_replica_ttft = self.obs.gauge(
            "ptpu_router_replica_ttft_p95_ms",
            "Replica's scraped TTFT p95 (bucket upper bound) — the "
            "base of the hedge delay", labelnames=("replica",))
        self._m_kvx_hints = self.obs.counter(
            "ptpu_router_kvxfer_hints_total",
            "Requests served with a KV transfer hint attached (the "
            "target was told to pull the warm prefix from a peer)")
        self._m_phase_routed = self.obs.counter(
            "ptpu_router_phase_routed_total",
            "Requests sharded over phase-matching replicas",
            labelnames=("phase",))      # phase=prefill|decode
        self._m_replica_phase = self.obs.gauge(
            "ptpu_router_replica_phase",
            "Replica's advertised serving phase: 0 mixed, 1 prefill, "
            "2 decode", labelnames=("replica",))
        self._m_replica_burning = self.obs.gauge(
            "ptpu_router_replica_burning",
            "1 when the replica's own exposition reports any "
            "ptpu_slo_burning objective alight", labelnames=("replica",))
        self._m_fleet_sheds = self.obs.counter(
            "ptpu_router_fleet_sheds_total",
            "Requests shed at the router by fleet admission before a "
            "burning replica saw them",
            labelnames=("reason",))     # reason=primary_burn|fleet_burn

        # router-side spans under the fleet trace id: one synthetic
        # request id per proxied POST, stitched with the replica's
        # engine spans by /trace/<id>
        self.tracer = RequestTracer(keep_last=512, process_name="router")
        self._trace_seq = itertools.count(1)

        self._server: Optional[AsyncHTTPServer] = None
        self._scrape_thread: Optional[threading.Thread] = None
        self._stop_scrape = threading.Event()
        # One lock covers the router's mutable shared state: the in-flight
        # count AND every ReplicaState field the scrape loop and handler
        # threads both touch (including membership appends). Network I/O
        # never happens under it.
        self._lock = threading.Lock()
        self._inflight = 0          # guarded-by: self._lock
        self._draining = False      # guarded-by: self._lock
        self._drained = threading.Event()

    # -- membership / circuit breaker -------------------------------------
    def _note_failure(self, r: ReplicaState, reason: str) -> None:
        """One scrape/connect/stream failure on `r`: demote from
        routing and advance the breaker — `breaker_fails` consecutive
        failures open it (eviction), a failed half-open probe re-opens
        it."""
        evicted = False
        with self._lock:
            r.ready = False
            r.reason = reason
            r.fails += 1
            if r.breaker == "closed" and r.fails >= self.breaker_fails:
                r.breaker = "open"
                r.open_until = time.monotonic() + self.breaker_open_s
                evicted = True
            elif r.breaker == "half_open":
                r.breaker = "open"
                r.open_until = time.monotonic() + self.breaker_open_s
            state, fails = r.breaker, r.fails
        self._m_replica_ready.labels(replica=r.url).set(0.0)
        self._m_breaker.labels(replica=r.url).set(_BREAKER_LEVEL[state])
        if evicted:
            self._m_membership.labels(event="evict").inc()
            serve_event("router_evict", replica=r.url, fails=fails,
                        reason=reason)

    def register_replica(self, url: str,
                         phase: Optional[str] = None) -> ReplicaState:
        """Admit (or re-admit) a replica by base url: the programmatic
        half of POST /register. New url -> appended to the table and
        probed; evicted url -> breaker forced half-open and probed NOW,
        so a restarted replica is routable without waiting out
        `breaker_open_s`. `phase` (when the heartbeat carries one)
        updates the replica's advertised serving phase."""
        url = url.rstrip("/")
        with self._lock:
            r = next((x for x in self.replicas if x.url == url), None)
            is_new = r is None
            if is_new:
                r = ReplicaState(url)
                r.registered = True
                self.replicas.append(r)
            elif r.breaker == "open":
                r.breaker = "half_open"
                r.open_until = 0.0
            if phase in _PHASE_LEVEL:
                r.phase = phase
            ready = r.ready
            phase_pub = r.phase
        self._m_replica_phase.labels(replica=r.url).set(
            _PHASE_LEVEL[phase_pub])
        if is_new:
            self._m_membership.labels(event="register").inc()
            serve_event("router_register", replica=url, phase=phase_pub,
                        replicas=len(self.replicas))
        if not ready:
            # probe on the caller's thread (never under the lock): a
            # passing probe flips it ready/rejoined immediately
            self._scrape_once(r)
        return r

    async def _a_register(self, req: AioRequest,
                          conn: AioConnection) -> None:
        try:
            body = json.loads(req.body or b"{}")
            url = str(body.get("url") or "")
            phase = body.get("phase")
        except (ValueError, json.JSONDecodeError):
            url, phase = "", None
        if not url.startswith("http"):
            payload = json.dumps({"ok": False,
                                  "error": "body must be {'url': "
                                           "'http://host:port'}"})
            await conn.send(400, "application/json",
                            payload.encode() + b"\n")
            return
        # register_replica probes the new member over blocking HTTP:
        # off the loop, onto the (bounded) default executor
        r = await asyncio.get_running_loop().run_in_executor(
            None, self.register_replica, url, phase)
        with self._lock:
            known = len(self.replicas)
            ready = r.ready
        await conn.send(200, "application/json", json.dumps(
            {"ok": True, "ready": ready, "replicas": known}).encode()
            + b"\n")

    # -- scrape loop ------------------------------------------------------
    def _scrape_once(self, r: ReplicaState) -> None:
        # HTTP happens into locals; ReplicaState fields are published in
        # one locked write so handler threads (plan_route, _proxy's
        # connect-failure demotion) never see a half-updated replica.
        ready = False
        reason = ""
        vals = {}
        prefixes: Dict[Tuple[int, str], str] = {}
        phase: Optional[str] = None
        direct_int8 = False
        try:
            conn = HTTPConnection(r.host, r.port,
                                  timeout=self.scrape_timeout_s)
            try:
                conn.request("GET", "/readyz")
                resp = conn.getresponse()
                body = resp.read().decode("utf-8", "replace").strip()
                ready = resp.status == 200
                reason = "" if ready else body
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                text = resp.read().decode("utf-8", "replace")
                # fleet prefix directory: tolerate replicas without the
                # endpoint (404 / bad JSON -> empty advertisement, the
                # scrape itself still counts as healthy)
                conn.request("GET", "/kvprefixes")
                presp = conn.getresponse()
                pbody = presp.read()
                if presp.status == 200:
                    try:
                        payload = json.loads(pbody)
                        # phase rides the same advertisement: argv-
                        # seeded replicas never POST /register
                        if payload.get("phase") in _PHASE_LEVEL:
                            phase = payload["phase"]
                        # capability field (absent on older replicas)
                        direct_int8 = bool(payload.get("direct_int8",
                                                       False))
                        for row in payload.get("prefixes", []):
                            prefixes[(int(row["len"]),
                                      str(row["digest"]))] = \
                                str(row.get("tier", "device"))
                    except (ValueError, KeyError, TypeError):
                        prefixes = {}
                        direct_int8 = False
            finally:
                conn.close()
            vals = parse_prometheus_values(text)
        except OSError as e:
            self._note_failure(r, f"scrape failed: {e}")
            with self._lock:
                last_scrape = r.last_scrape
            age = (time.monotonic() - last_scrape) if last_scrape else -1.0
            self._m_scrape_age.labels(replica=r.url).set(age)
            return
        ttft = _bucket_quantile(vals, "ptpu_serve_ttft_ms", 0.95)
        # the replica's own SLO burn verdicts, straight from its
        # exposition — fleet admission sheds on these (when enabled)
        burning = tuple(sorted(
            m.group(1) for key, val in vals.items()
            for m in (_SLO_BURN_RE.match(key),) if m and val >= 1.0))
        with self._lock:
            rejoined = r.breaker != "closed"
            r.breaker = "closed"
            r.fails = 0
            r.open_until = 0.0
            r.ready = ready
            r.reason = reason
            r.prefixes = prefixes
            r.burning = burning
            r.direct_int8 = direct_int8
            if phase is not None:
                r.phase = phase
            phase_pub = r.phase
            if vals:
                r.hit_rate = vals.get("ptpu_kv_hit_rate", 0.0)
                r.queue_depth = vals.get("ptpu_sched_queue_depth", 0.0)
                if ttft == ttft and ttft != float("inf"):   # not NaN/Inf
                    r.ttft_p95_ms = ttft
                r.last_scrape = time.monotonic()
            hit_rate, queue_depth = r.hit_rate, r.queue_depth
            last_scrape, ttft_pub = r.last_scrape, r.ttft_p95_ms
        if rejoined:
            self._m_membership.labels(event="rejoin").inc()
            serve_event("router_rejoin", replica=r.url, ready=ready)
        self._m_replica_ready.labels(replica=r.url).set(1.0 if ready else 0.0)
        self._m_breaker.labels(replica=r.url).set(0.0)
        self._m_replica_hit.labels(replica=r.url).set(hit_rate)
        self._m_replica_depth.labels(replica=r.url).set(queue_depth)
        self._m_replica_prefixes.labels(replica=r.url).set(
            float(len(prefixes)))
        self._m_replica_ttft.labels(replica=r.url).set(ttft_pub)
        self._m_replica_phase.labels(replica=r.url).set(
            _PHASE_LEVEL[phase_pub])
        self._m_replica_burning.labels(replica=r.url).set(
            1.0 if burning else 0.0)
        # staleness: keeps GROWING while scrapes fail, so alerting can
        # tell "replica down" from "replica briefly slow"
        age = (time.monotonic() - last_scrape) if last_scrape else -1.0
        self._m_scrape_age.labels(replica=r.url).set(age)

    def _scrape_guard(self, r: ReplicaState) -> None:
        try:
            self._scrape_once(r)
        finally:
            with self._lock:
                r.scraping = False

    def scrape_now(self, wait_s: Optional[float] = None) -> None:
        """One pass over every replica, each on its own thread with its
        own `scrape_timeout_s` — a wedged /metrics handler delays ONLY
        its replica (whose in-flight flag also stops pileup across
        ticks); the rest of the fleet stays fresh. Joins up to `wait_s`
        (default: one scrape timeout + slack) so startup and tests see
        a synchronous pass."""
        with self._lock:
            reps = list(self.replicas)
        now = time.monotonic()
        threads: List[threading.Thread] = []
        for r in reps:
            with self._lock:
                if r.scraping:          # previous scrape still stuck on it
                    skip, half_open = True, False
                elif r.breaker == "open" and now < r.open_until:
                    skip, half_open = True, False   # evicted: wait out open_s
                else:
                    skip = False
                    half_open = r.breaker == "open"
                    if half_open:
                        r.breaker = "half_open"     # one probe
                    r.scraping = True
                last_scrape = r.last_scrape
            if skip:
                age = (now - last_scrape) if last_scrape else -1.0
                self._m_scrape_age.labels(replica=r.url).set(age)
                continue
            if half_open:
                self._m_breaker.labels(replica=r.url).set(
                    _BREAKER_LEVEL["half_open"])
            t = threading.Thread(target=self._scrape_guard, args=(r,),
                                 daemon=True, name="ptpu-router-scrape-one")
            t.start()
            threads.append(t)
        deadline = time.monotonic() + (
            wait_s if wait_s is not None else self.scrape_timeout_s + 0.5)
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def _scrape_loop(self) -> None:
        # wait_s=0: the periodic tick never waits on the scrape
        # threads, so the cadence stays `scrape_interval_s` even while
        # one replica's scrape is timing out — a black-holed member
        # must not slow down how fast a DEAD member is detected. The
        # per-replica `scraping` flag stops pileup on the slow one.
        while not self._stop_scrape.wait(self.scrape_interval_s):
            self.scrape_now(wait_s=0.0)

    # -- routing policy ---------------------------------------------------
    def _directory_best(self, prompt: Sequence[int], snapshot: dict
                        ) -> Tuple[Optional[ReplicaState], int]:
        """The ready replica advertising the LONGEST prefix of `prompt`
        at the HOTTEST tier plus that matched length, or (None, 0) when
        the fleet directory has no match. Digests are memoized per
        length: one crc32 per distinct advertised prefix length, not
        per (replica, row). A direct_int8-capable replica's device_int8
        rows rank AT the device rung — its mixed step reads them in
        place, no promote — while replicas without the capability keep
        the legacy device > device_int8 > host ordering."""
        best: Optional[ReplicaState] = None
        best_score = (-1, -1)
        memo: Dict[int, str] = {}
        for r, (ready, _, _, prefixes, _, _, direct) in snapshot.items():
            if not ready:
                continue
            for (ln, dg), tier in prefixes.items():
                rank = (_TIER_RANK["device"]
                        if direct and tier == "device_int8"
                        else _TIER_RANK.get(tier, -1))
                score = (ln, rank)
                if ln > len(prompt) or score <= best_score:
                    continue
                if ln not in memo:
                    memo[ln] = prefix_digest(prompt[:ln])
                if memo[ln] == dg:
                    best, best_score = r, score
        return best, max(0, best_score[0])

    def _classify_phase(self, prompt: Sequence[int],
                        max_new_tokens: Optional[int]) -> str:
        """Which phase specialization serves this request best:
        "prefill" when the prompt dominates the work (prompt len >=
        phase_prefill_ratio x expected decode tokens), else "decode"."""
        max_new = max(1, int(max_new_tokens)
                      if max_new_tokens is not None else 64)
        if len(prompt) >= self.phase_prefill_ratio * max_new:
            return "prefill"
        return "decode"

    def _plan(self, prompt: Sequence[int],
              max_new_tokens: Optional[int] = None
              ) -> Tuple[List[ReplicaState], Optional[ReplicaState],
                         Optional[ReplicaState], int, Optional[str]]:
        """(candidates in try-order, directory pick or None, sticky,
        matched directory prefix length, phase specialization applied
        or None). The hash primary maps over the READY set (in table
        order), so a dead replica's shard re-maps over survivors;
        `sticky` is the hash over the FULL member table — the label
        reference point, so stickiness verdicts don't shift when
        readiness flaps. Ready fallbacks rank best-first (highest
        scraped hit rate, shortest queue); routable-but-not-ready
        replicas trail as a last ditch (the scrape may be stale);
        breaker-open replicas are not tried at all.

        PHASE. When the fleet has a ready replica whose advertised
        phase exactly matches the request's classification, the hash
        shards over the MATCHING set first and the rest of the ready
        fleet trails — a mixed fleet (no specialists) routes exactly as
        before.

        DIRECTORY. When the fleet prefix directory knows a ready
        replica holding a warm prefix of this prompt: without
        kv_transfer that replica is promoted to the front (warm KV
        beats where the hash says the prefix should live); with
        kv_transfer the ORDER STANDS and the caller attaches transfer
        hints instead — the routed target pulls the blocks from
        dir_pick (serve/kvxfer.py)."""
        with self._lock:    # one consistent snapshot to rank against
            stats = {r: (r.ready, r.hit_rate, r.queue_depth,
                         dict(r.prefixes), r.breaker, r.phase,
                         r.direct_int8)
                     for r in self.replicas}
        members = list(stats.keys())
        if not members:
            return [], None, None, 0, None
        sticky = members[prefix_shard(prompt, len(members),
                                      self.prefix_len)]
        routable = [r for r in members if stats[r][4] != "open"]
        ready = [r for r in routable if stats[r][0]]
        want: Optional[str] = None
        if ready:
            pool = ready
            wanted = self._classify_phase(prompt, max_new_tokens)
            matching = [r for r in ready if stats[r][5] == wanted]
            if matching and len(matching) < len(ready):
                # phase specialists exist: shard over them first
                pool = matching
                want = wanted
            primary = pool[prefix_shard(prompt, len(pool),
                                        self.prefix_len)]
            fallbacks = sorted(
                (r for r in pool if r is not primary),
                key=lambda r: (-stats[r][1], stats[r][2]))
            order = [primary] + fallbacks
            order += sorted(
                (r for r in ready if r not in pool),
                key=lambda r: (-stats[r][1], stats[r][2]))
            in_order = set(map(id, order))
            order += [r for r in routable if id(r) not in in_order]
        else:
            # none ready: try the routable set anyway (scrapes may be
            # stale) — but NEVER a breaker-open replica; a fully open
            # fleet sheds until a half-open probe rejoins someone
            order = routable
        dir_pick, dir_len = ((self._directory_best(prompt, stats))
                             if self.enable_directory else (None, 0))
        if (dir_pick is not None and not self.kv_transfer
                and dir_pick is not order[0]):
            if dir_pick in order:
                order.remove(dir_pick)
            order.insert(0, dir_pick)
        return order, dir_pick, sticky, dir_len, want

    def plan_route(self, prompt: Sequence[int]) -> List[ReplicaState]:
        """Candidate replicas in try-order (see _plan)."""
        return self._plan(prompt)[0]

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Router":
        if self._server is not None:
            return self
        self.scrape_now()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, daemon=True, name="ptpu-router-scrape")
        self._scrape_thread.start()
        self._server = AsyncHTTPServer(
            self.host, self.port, self._a_dispatch,
            name="ptpu-router-http").start()
        self.port = self._server.port
        serve_event("router_listening", host=self.host, port=self.port,
                    replicas=[r.url for r in self.replicas])
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def install_signals(self) -> "Router":
        def _on_signal(signum, frame):
            serve_event("router_sigterm", signal=int(signum))
            threading.Thread(target=self.begin_drain, daemon=True).start()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
        return self

    def begin_drain(self) -> None:
        """Stop admitting; wait for in-flight proxied streams to finish
        (bounded by drain_deadline_s); record exit code 75."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._m_draining.set(1.0)
        deadline = time.monotonic() + self.drain_deadline_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.05)
        self.exit_code = PREEMPT_EXIT_CODE
        serve_event("router_drained", exit_code=self.exit_code,
                    inflight_at_exit=self._inflight)
        self._drained.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        self._drained.wait(timeout)
        return self.exit_code

    def stop(self) -> None:
        self._stop_scrape.set()
        if self._server is not None:
            server, self._server = self._server, None
            server.stop()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None

    # -- HTTP -------------------------------------------------------------
    def readiness(self) -> Tuple[bool, str]:
        with self._lock:
            if self._draining:
                return False, "draining"
            if any(r.ready for r in self.replicas):
                return True, ""
        return False, "no ready replicas"

    def _fetch(self, r: ReplicaState, path: str,
               timeout: Optional[float] = None) -> Optional[str]:
        """GET `path` from a replica, body text on 200 else None. Runs
        on handler threads with NO router lock held (network under the
        lock is forbidden — see self._lock's comment). `timeout`
        defaults to the proxy connect timeout; aggregation routes pass
        `scrape_timeout_s` so one hung replica delays, not stalls,
        the merge."""
        try:
            conn = HTTPConnection(
                r.host, r.port,
                timeout=self.connect_timeout_s if timeout is None
                else timeout)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return None
                return body.decode("utf-8", "replace")
            finally:
                conn.close()
        except OSError:
            return None

    def _fleet_route(self):
        """/metrics/fleet: scrape every replica NOW and serve the
        federated exposition. Unreachable replicas are simply absent
        from the merge (their staleness still shows on the router's
        own ptpu_router_scrape_age_seconds)."""
        expositions: Dict[str, str] = {}
        for r in self.replicas:
            text = self._fetch(r, "/metrics", timeout=self.scrape_timeout_s)
            if text is not None:
                expositions[r.url] = text
        return 200, CONTENT_TYPE, federate(expositions).encode()

    def _trace_route(self, path: str):
        """/trace/<id>: merge the router's own span fragment for the
        trace id with every replica's into one Chrome trace — each
        process gets its own pid row, timestamps are epoch-anchored
        (now_us) so no shifting is needed."""
        tid = path[len("/trace/"):].strip("/")
        fragments: List[Tuple[str, dict]] = []
        own = self.tracer.trace_fragment(tid) if tid else None
        if own is not None:
            fragments.append(("router", own))
        for r in self.replicas:
            text = (self._fetch(r, "/trace/" + tid,
                                timeout=self.scrape_timeout_s)
                    if tid else None)
            if text is None:
                continue
            try:
                frag = json.loads(text)
            except ValueError:
                continue
            fragments.append((f"replica {r.url}", frag))
        if not fragments:
            return (404, "application/json",
                    json.dumps({"error": "unknown trace",
                                "trace_id": tid}).encode() + b"\n")
        merged = stitch_fragments(fragments, trace_id=tid)
        return (200, "application/json",
                json.dumps(merged).encode() + b"\n")

    def _debug_payload(self) -> dict:
        """/debug: the replica table as routing sees it right now."""
        now = time.monotonic()
        with self._lock:
            replicas = [{
                "url": r.url,
                "ready": r.ready,
                "reason": r.reason,
                "hit_rate": r.hit_rate,
                "queue_depth": r.queue_depth,
                "scrape_age_s": (round(now - r.last_scrape, 3)
                                 if r.last_scrape else None),
                "prefixes": len(r.prefixes),
                "breaker": r.breaker,
                "fails": r.fails,
                "registered": r.registered,
                "ttft_p95_ms": r.ttft_p95_ms,
                "phase": r.phase,
                "burning": list(r.burning),
            } for r in self.replicas]
            inflight = self._inflight
            draining = self._draining
        return {"replicas": replicas, "inflight": inflight,
                "draining": draining,
                "scrape_interval_s": self.scrape_interval_s,
                "directory_enabled": self.enable_directory,
                "retry_budget_tokens": self.retry_budget.tokens(),
                "hedge_enabled": self.enable_hedge,
                "kv_transfer": self.kv_transfer,
                "fleet_admission": self.fleet_admission}

    def _get_response(self, path: str) -> Tuple[int, str, bytes]:
        """Resolve a GET path to (status, ctype, body). Runs on an
        executor thread: /metrics/fleet and /trace/<id> fan blocking
        GETs over the whole fleet and must never park the loop."""
        resp = obs_response(
            path, self.obs, readiness=self.readiness,
            routes={"/metrics/fleet": self._fleet_route,
                    "/debug": json_route(self._debug_payload)},
            prefix_routes={"/trace/": self._trace_route})
        if resp is None:
            resp = (404, "text/plain", b"not found\n")
        return resp

    async def _a_get(self, req: AioRequest, conn: AioConnection) -> None:
        resp = await asyncio.get_running_loop().run_in_executor(
            None, self._get_response, req.path)
        await conn.send(*resp)

    async def _a_shed(self, conn: AioConnection, reason: str) -> None:
        self._m_sheds.labels(reason=reason).inc()
        body = json.dumps({"error": "overloaded", "reason": reason,
                           "retry_after_s": 1.0}).encode() + b"\n"
        try:
            await conn.send(503, "application/json", body,
                            {"Retry-After": "1"})
        except (SlowClientError, ConnectionError, OSError):
            pass

    async def _a_fleet_shed(self, conn: AioConnection,
                            reason: str) -> None:
        """Fleet admission's bounce: same 503 + Retry-After contract
        as _a_shed but counted on its own series — "the fleet is
        protecting itself" is a different signal from "the router has
        nowhere to route"."""
        self._m_fleet_sheds.labels(reason=reason).inc()
        serve_event("router_fleet_shed", reason=reason)
        body = json.dumps({"error": "overloaded", "reason": reason,
                           "retry_after_s": 1.0}).encode() + b"\n"
        try:
            await conn.send(503, "application/json", body,
                            {"Retry-After": "1"})
        except (SlowClientError, ConnectionError, OSError):
            pass

    def _fleet_admission_reason(
            self, candidates: List[ReplicaState]) -> Optional[str]:
        """None admits. "primary_burn" when the planned primary's own
        SLO monitor says it is burning error budget — the request is
        shed, deliberately NOT spilled onto the healthy remainder
        (pushing a hot shard's traffic onto its neighbours is how one
        burning replica torches the fleet). "fleet_burn" when every
        candidate is burning."""
        if not self.fleet_admission or not candidates:
            return None
        with self._lock:
            burning = [bool(r.burning) for r in candidates]
        if all(burning):
            return "fleet_burn"
        if burning[0]:
            return "primary_burn"
        return None

    async def _a_dispatch(self, req: AioRequest,
                          conn: AioConnection) -> None:
        if req.method == "GET":
            await self._a_get(req, conn)
        elif req.method == "POST":
            await self._a_post(req, conn)
        else:
            await conn.send(405, "text/plain", b"method not allowed\n")

    async def _a_post(self, req: AioRequest, conn: AioConnection) -> None:
        path = req.path.split("?")[0]
        if path == "/register":
            await self._a_register(req, conn)
            return
        if path != "/v1/completions":
            await self._a_get(req, conn)    # reuse the 404 path
            return
        if self._draining:
            await self._a_shed(conn, "draining")
            return
        max_new: Optional[int] = None
        raw = req.body or b"{}"
        try:
            body = json.loads(raw or b"{}")
            prompt = body.get("prompt") or []
            if isinstance(prompt, str):
                # string prompts tokenize REPLICA-side; route on the
                # utf-8 bytes — stable across processes, and identical
                # strings still shard sticky (the directory simply
                # won't match until token-level requests warmed it)
                prompt = list(prompt.encode("utf-8"))
            mn = body.get("max_new_tokens")
            if mn is not None:
                max_new = int(mn)
        except (ValueError, TypeError, json.JSONDecodeError):
            raw, prompt = b"{}", []
        # fleet trace id: honor the client's, else mint one; the same
        # id tags the router's route/relay spans AND rides the replica
        # hop as x-ptpu-trace, so /trace/<id> can stitch both processes
        tid = req.header("x-ptpu-trace") or uuid.uuid4().hex[:16]
        rid = next(self._trace_seq)
        self.tracer.set_trace_id(rid, tid)
        self.tracer.span_begin(rid, "route")
        candidates, dir_pick, sticky, dir_len, want = self._plan(
            prompt, max_new)
        if not candidates:
            self.tracer.on_finish(rid, "shed")
            await self._a_shed(conn, "no_replica")
            return
        fleet_reason = self._fleet_admission_reason(candidates)
        if fleet_reason is not None:
            self.tracer.on_finish(rid, "shed")
            await self._a_fleet_shed(conn, fleet_reason)
            return
        if want is not None:
            self._m_phase_routed.labels(phase=want).inc()
        self._track_inflight(+1)
        try:
            await self._a_proxy(conn, raw, prompt, candidates, dir_pick,
                                sticky, dir_len=dir_len, tid=tid, rid=rid)
        finally:
            self._track_inflight(-1)

    def _track_inflight(self, delta: int) -> None:
        """Count and gauge move together under the lock: the old code
        re-read `self._inflight` outside it, so two crossing requests
        could publish stale values out of order and leave the gauge
        permanently off. The gauge's own child lock is leaf-level (it
        never takes router locks), so nesting it here cannot deadlock."""
        with self._lock:
            self._inflight += delta
            self._m_inflight.set(float(self._inflight))

    # -- proxy data path --------------------------------------------------
    async def _a_connect_stream(self, r: ReplicaState, raw: bytes,
                                headers: dict):
        """POST the completion to one replica.
        ("ok", _Upstream) | ("shed", body) | ("error",)."""
        try:
            status, rheaders, reader, writer = await aio_http_request(
                r.host, r.port, "POST", "/v1/completions", body=raw,
                headers=headers, connect_timeout_s=self.connect_timeout_s)
        except (OSError, asyncio.TimeoutError) as e:
            self._note_failure(r, f"connect failed: {e}")
            return ("error",)
        up = _Upstream(status, rheaders, reader, writer)
        if status == 503:           # replica shed: caller tries the next
            try:
                body = await aio_read_body(
                    reader, rheaders, timeout_s=self.connect_timeout_s)
            except asyncio.TimeoutError:
                body = b""
            up.close()
            return ("shed", body)
        return ("ok", up)

    def _hedge_delay_s(self, r: ReplicaState) -> float:
        """How long to give `r`'s first response byte before hedging:
        hedge_ttft_mult x its scraped TTFT p95 (fleet max when `r` has
        no samples yet), clamped to [hedge_min_s, hedge_max_s]. An
        unmeasured fleet waits the full hedge_max_s — no speculative
        traffic before there is evidence of what slow means."""
        with self._lock:
            p95 = r.ttft_p95_ms or max(
                (x.ttft_p95_ms for x in self.replicas if x.ready),
                default=0.0)
        if p95 <= 0:
            return self.hedge_max_s
        return min(max(self.hedge_ttft_mult * p95 / 1000.0,
                       self.hedge_min_s), self.hedge_max_s)

    async def _a_open_stream(self, r: ReplicaState, raw: bytes,
                             headers: dict,
                             hedge_pool: Optional[List[ReplicaState]],
                             rid: Optional[int]):
        """Open the stream on `r`; with a non-empty `hedge_pool`, race
        ONE hedge to its head after the TTFT-derived delay — first
        response wins, the loser's connection is aborted (the engine
        behind it cancels and frees KV). The hedge spends a retry-
        budget token when it fires; an empty bucket silently skips it.
        The race that used to burn two threads per hedged request is
        two coroutines on the serving loop.
        Returns ("ok", replica, _Upstream) | ("shed", body) |
        ("error",)."""
        if not hedge_pool:
            res = await self._a_connect_stream(r, raw, headers)
            return res if res[0] != "ok" else ("ok", r, res[1])
        delay = self._hedge_delay_s(r)
        decided = asyncio.Event()
        fired = False
        hedge_target = hedge_pool[0]

        async def attempt(rep: ReplicaState, tag: str, wait_s: float):
            nonlocal fired
            if wait_s > 0.0:
                try:
                    await asyncio.wait_for(decided.wait(), wait_s)
                    return (tag, rep, None)     # first answered in time
                except asyncio.TimeoutError:
                    pass
            if tag == "hedge":
                if not self.retry_budget.try_spend("router_hedge"):
                    self._m_hedges.labels(outcome="denied").inc()
                    return (tag, rep, ("error",))
                fired = True
                if rid is not None:
                    self.tracer.mark(rid, "hedge_fired", replica=rep.url)
            return (tag, rep,
                    await self._a_connect_stream(rep, raw, headers))

        loop = asyncio.get_running_loop()
        tasks = {loop.create_task(attempt(r, "first", 0.0)),
                 loop.create_task(attempt(hedge_target, "hedge", delay))}
        chosen = None
        first_failure = None
        endline = loop.time() + self.connect_timeout_s + delay + 1.0
        while tasks and chosen is None:
            timeout = endline - loop.time()
            if timeout <= 0:
                break
            done, tasks = await asyncio.wait(
                tasks, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                tag, rep, res = t.result()
                if res is None:
                    continue            # hedge stood down: first decided
                if res[0] == "ok":
                    if chosen is None:
                        chosen = (tag, rep, res)
                    else:               # both landed: drop the loser
                        res[1].close()
                elif tag == "first":
                    first_failure = res
                    if not fired:
                        # the primary failed before any hedge went out:
                        # stand the hedge down and fail over normally
                        decided.set()
                        await asyncio.gather(*tasks,
                                             return_exceptions=True)
                        return first_failure
                # a failed hedge: keep waiting for the primary
        decided.set()
        if chosen is None:
            for t in tasks:
                t.cancel()
            return first_failure if first_failure is not None else ("error",)
        tag, rep, res = chosen
        if tag == "hedge":
            self._m_hedges.labels(outcome="won").inc()
        elif fired:
            self._m_hedges.labels(outcome="lost").inc()
        if tasks:
            # the loser is still connecting: reap its socket when it
            # resolves so the engine behind it cancels
            async def _reap(pending):
                done, late = await asyncio.wait(
                    pending, timeout=self.connect_timeout_s + 5.0)
                for t in late:
                    t.cancel()
                for t in done:
                    _, _, lres = t.result()
                    if lres is not None and lres[0] == "ok":
                        lres[1].close()
            loop.create_task(_reap(set(tasks)))
        return ("ok", rep, res[1])

    @staticmethod
    async def _a_client_write(conn: AioConnection, data: bytes) -> bool:
        """True when the client took the bytes; False when it hung up
        or stalled past the write deadline (its transport is already
        aborted by then)."""
        try:
            await conn.write(data)
            return True
        except (SlowClientError, ConnectionError, OSError):
            return False

    async def _a_relay_sse(self, conn: AioConnection, up: _Upstream,
                           state: _RelayState) -> str:
        """Frame-level relay: forward SSE frames as they arrive,
        skipping the first `state.sent` data frames (a resumed stream
        replays from the start — greedy decode on identical weights
        makes the replay identical). Returns "done" ([DONE] relayed /
        non-stream response fully copied), "client_gone" (our write
        failed or the client stalled past the write deadline), or
        "truncated" (upstream died first — the caller fails over)."""
        ctype = up.getheader("content-type", "") or ""
        if up.status != 200 or "text/event-stream" not in ctype:
            if state.started:
                return "truncated"  # can't splice a non-stream mid-stream
            await self._a_relay(conn, up)
            return "done"
        if not state.started:
            head = ("HTTP/1.0 200 OK\r\n"
                    f"Content-Type: {ctype}\r\n"
                    "Connection: close\r\n\r\n").encode("latin-1")
            if not await self._a_client_write(conn, head):
                return "client_gone"
            state.started = True
        n = 0
        try:
            async for payload in aiter_sse(
                    up.reader, timeout_s=self.connect_timeout_s):
                if payload == DONE_SENTINEL:
                    if not await self._a_client_write(
                            conn, sse_event(payload)):
                        return "client_gone"
                    return "done"
                n += 1
                if n <= state.sent:
                    continue        # the client already has this frame
                if not await self._a_client_write(
                        conn, sse_event(payload)):
                    return "client_gone"
                state.sent = n
        except (OSError, asyncio.TimeoutError):
            pass                    # reset / stall from upstream
        return "truncated"          # EOF without [DONE]

    async def _a_proxy(self, conn: AioConnection, raw: bytes,
                       prompt: Sequence[int],
                       candidates: List[ReplicaState],
                       dir_pick: Optional[ReplicaState] = None,
                       sticky: Optional[ReplicaState] = None, *,
                       dir_len: int = 0,
                       tid: Optional[str] = None,
                       rid: Optional[int] = None) -> None:
        """Drive one request to a `[DONE]`-terminated stream across as
        many replicas as the retry budget allows: connect failures and
        replica 503s fail over BEFORE the first byte; a mid-stream
        death fails over WITH RESUME (state.sent frames are skipped on
        the replay); the first attempt may hedge. Every re-attempt
        after the first costs a budget token — an empty bucket sheds
        503 reason="retry_budget" rather than storming a degraded
        fleet. The served replica's route kind: "primary" when it is
        the full-table hash pick (the directory agreeing with the hash
        stays "primary" so stickiness verdicts survive), "directory"
        when the fleet prefix directory OVERRODE the hash, "fallback"
        otherwise."""
        headers = {"Content-Type": "application/json"}
        if tid:
            headers["x-ptpu-trace"] = tid
        state = _RelayState()
        pending = list(candidates)
        last_shed: Optional[bytes] = None
        attempt = 0
        retry_kind = "connect"
        while pending:
            r = pending.pop(0)
            attempt += 1
            if attempt > 1:
                if not self.retry_budget.try_spend("router"):
                    if rid is not None:
                        self.tracer.on_finish(rid, "budget_exhausted")
                    if not state.started:
                        await self._a_shed(conn, "retry_budget")
                    return
                self._m_retries.labels(kind=retry_kind).inc()
                if rid is not None:
                    self.tracer.mark(rid, "failover", replica=r.url,
                                     kind=retry_kind)
            hedge_pool = (pending if attempt == 1 and self.enable_hedge
                          and pending and not state.started else None)
            # kv_transfer: when the warm prefix lives on a replica we
            # are NOT about to try, tell this attempt's target where to
            # pull it from (per-attempt copy: a later attempt may BE
            # dir_pick and must not be told to pull from itself)
            hinted = (self.kv_transfer and dir_pick is not None
                      and dir_len > 0 and r is not dir_pick)
            attempt_headers = headers
            if hinted:
                attempt_headers = dict(headers)
                attempt_headers["x-ptpu-kv-source"] = dir_pick.url
                attempt_headers["x-ptpu-kv-len"] = str(dir_len)
            res = await self._a_open_stream(r, raw, attempt_headers,
                                            hedge_pool, rid)
            if res[0] == "shed":
                last_shed = res[1]
                retry_kind = "shed"
                if rid is not None:
                    self.tracer.mark(rid, "replica_shed", replica=r.url)
                continue
            if res[0] == "error":
                retry_kind = "connect"
                if rid is not None:
                    self.tracer.mark(rid, "connect_failed", replica=r.url)
                continue
            _, r_used, up = res
            if r_used is not r:
                # the hedge won: it came out of pending; the slow
                # primary goes to the back as a last-resort retry
                if r_used in pending:
                    pending.remove(r_used)
                pending.append(r)
            if r_used is sticky:
                kind = "primary"
            elif dir_pick is not None and r_used is dir_pick:
                kind = "directory"
            else:
                kind = "fallback"
            if dir_pick is not None and r_used is dir_pick:
                self._m_dir_hits.inc()
            if hinted and r_used is not dir_pick:
                # the served replica was told where to pull warm KV —
                # the directory paid off WITHOUT re-routing
                self._m_dir_hits.inc()
                self._m_kvx_hints.inc()
            self._m_routed.labels(replica=r_used.url, kind=kind).inc()
            if rid is not None:
                self.tracer.mark(rid, "routed", replica=r_used.url,
                                 kind=kind)
                self.tracer.span_begin(rid, "relay")
            outcome = await self._a_relay_sse(conn, up, state)
            up.close()
            if outcome == "done":
                if rid is not None:
                    self.tracer.on_finish(rid, "relayed")
                return
            if outcome == "client_gone":
                if rid is not None:
                    self.tracer.on_finish(rid, "client_gone")
                return
            # upstream died mid-stream: breaker takes note, the next
            # candidate resumes past the frames the client already has
            self._note_failure(r_used, "stream truncated")
            retry_kind = "stream"
            if rid is not None:
                self.tracer.mark(rid, "stream_truncated",
                                 replica=r_used.url, frames=state.sent)
        if rid is not None:
            self.tracer.on_finish(rid, "shed")
        if state.started:
            return      # partial stream, nothing left to resume from
        if last_shed is not None:       # every replica shed: relay it
            try:
                await conn.send(503, "application/json", last_shed)
            except (SlowClientError, ConnectionError, OSError):
                pass
            return
        await self._a_shed(conn, "no_replica")

    async def _a_relay(self, conn: AioConnection, up: _Upstream) -> None:
        """Copy status + content-type + body bytes to the client,
        unbuffered per read so bytes stream as they arrive. A client
        write failure aborts the replica connection (via the caller's
        up.close()), which cancels the request engine-side. The
        non-SSE path (errors, future non-stream responses); SSE goes
        through _a_relay_sse for failover-with-resume."""
        ctype = up.getheader("content-type", "application/octet-stream")
        head = (f"HTTP/1.0 {up.status} {_STATUS_TEXT.get(up.status, '')}"
                f"\r\nContent-Type: {ctype}\r\n"
                "Connection: close\r\n\r\n").encode("latin-1")
        if not await self._a_client_write(conn, head):
            return
        try:
            while True:
                chunk = await asyncio.wait_for(
                    up.reader.read(8192), self.connect_timeout_s)
                if not chunk:
                    break
                if not await self._a_client_write(conn, chunk):
                    return
        except (OSError, asyncio.TimeoutError):
            pass


def main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.serve.router --replica URL --replica URL`
    (or no --replica at all: replicas join via POST /register)"""
    import argparse

    p = argparse.ArgumentParser(description="ptpu serve router")
    p.add_argument("--replica", action="append", default=[],
                   help="replica base url (repeatable; optional — "
                        "replicas can also POST /register themselves)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--prefix-len", type=int, default=32)
    p.add_argument("--scrape-interval-s", type=float, default=0.5)
    p.add_argument("--scrape-timeout-s", type=float, default=2.0,
                   help="per-replica scrape socket timeout: a wedged "
                        "replica delays only itself, never the loop")
    p.add_argument("--drain-deadline-s", type=float, default=30.0)
    p.add_argument("--no-prefix-directory", action="store_true",
                   help="route on hash stickiness only; ignore the "
                        "scraped /kvprefixes fleet directory")
    p.add_argument("--breaker-fails", type=int, default=3,
                   help="consecutive scrape/connect failures that open "
                        "a replica's circuit breaker (evict)")
    p.add_argument("--breaker-open-s", type=float, default=2.0,
                   help="how long an open breaker waits before its "
                        "half-open probe")
    p.add_argument("--retry-budget-ratio", type=float, default=0.2,
                   help="retry tokens deposited per successful request")
    p.add_argument("--retry-budget-burst", type=float, default=16.0,
                   help="retry-budget bucket size (cold-start allowance)")
    p.add_argument("--no-hedge", action="store_true",
                   help="disable hedged requests")
    p.add_argument("--hedge-ttft-mult", type=float, default=3.0,
                   help="hedge after this multiple of the scraped "
                        "TTFT p95")
    p.add_argument("--hedge-min-s", type=float, default=0.05)
    p.add_argument("--hedge-max-s", type=float, default=2.0)
    p.add_argument("--kv-transfer", action="store_true",
                   help="attach KV transfer hints on directory hits "
                        "instead of re-routing (disaggregated serving)")
    p.add_argument("--phase-prefill-ratio", type=float, default=2.0,
                   help="prompt len >= ratio * max_new_tokens routes "
                        "to prefill-phase replicas when any exist")
    p.add_argument("--fleet-admission", action="store_true",
                   help="shed (503 + Retry-After) at the router when "
                        "the planned replica reports ptpu_slo_burning")
    a = p.parse_args(argv)
    router = Router(a.replica, host=a.host, port=a.port,
                    prefix_len=a.prefix_len,
                    scrape_interval_s=a.scrape_interval_s,
                    scrape_timeout_s=a.scrape_timeout_s,
                    drain_deadline_s=a.drain_deadline_s,
                    enable_directory=not a.no_prefix_directory,
                    breaker_fails=a.breaker_fails,
                    breaker_open_s=a.breaker_open_s,
                    retry_budget_ratio=a.retry_budget_ratio,
                    retry_budget_burst=a.retry_budget_burst,
                    enable_hedge=not a.no_hedge,
                    hedge_ttft_mult=a.hedge_ttft_mult,
                    hedge_min_s=a.hedge_min_s,
                    hedge_max_s=a.hedge_max_s,
                    kv_transfer=a.kv_transfer,
                    phase_prefill_ratio=a.phase_prefill_ratio,
                    fleet_admission=a.fleet_admission)
    router.start().install_signals()
    code = router.wait()
    router.stop()
    return code if code is not None else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())

"""Router: one front door over N serve replicas.

Scale-out story (ROADMAP "serve millions"): each replica is a
ServeFrontend process with its own engine, KV pool and telemetry; the
router is a thin streaming proxy that decides WHICH replica sees a
request and otherwise copies bytes. Three decisions, all driven by the
replicas' own scraped telemetry — the router holds no model state:

- STICKY PREFIX ROUTING. Prefix caching only pays when requests that
  share a prompt prefix land on the SAME replica (each engine's block
  pool is private). The primary replica is a stable hash — crc32, not
  Python's per-process-salted `hash()` — of the first `prefix_len`
  prompt tokens, modulo the READY set: every request with the same
  system prompt hashes to the same replica while the fleet is stable,
  so the fleet-wide hit rate tracks the single-replica hit rate
  instead of decaying ~1/N (serve_bench's router scenario measures
  exactly this). When a replica dies, the hash re-maps over the
  survivors only — no request is sticky to a corpse.
- FLEET PREFIX DIRECTORY. The hash is a degenerate directory (it
  predicts where a prefix SHOULD be warm); the real one is scraped:
  each replica advertises its warm prefixes on /kvprefixes as
  (length, crc32 digest, tier) rows — "device" for prefix-index
  blocks still in the pool, "host" for blocks demoted to the RAM tier
  (engine/kvtier.py). plan_route checks the incoming prompt against
  the directory and prefers the READY replica holding the LONGEST
  matching prefix at the HOTTEST tier (device beats host beats
  nothing), falling back to the hash primary when no replica has it.
  After a restart, rebalance, or failover the directory finds warm KV
  wherever it actually lives instead of where the hash says it should.
  A digest collision can only misroute (the receiving replica
  re-matches on exact tokens before reusing anything) — a perf risk,
  never a correctness one.
- TELEMETRY-RANKED FALLBACK. When the primary is not routable (failed
  /readyz: cold or draining; scrape failure; or it sheds 503), the
  request falls back to the remaining ready replicas ranked by their
  scraped `ptpu_kv_hit_rate` (desc — a warm cache serves a prefix
  cheapest) then `ptpu_sched_queue_depth` (asc — shortest line). The
  scrape loop refreshes each replica's gauges every
  `scrape_interval_s` on a daemon thread.
- DRAIN, SAME CONTRACT AS REPLICAS. SIGTERM stops admission (503
  reason="draining"), lets in-flight proxied streams finish to a
  bounded deadline, and exits PREEMPT_EXIT_CODE (75) — a router is as
  preemptible as the replicas behind it.

FLEET FAULT TOLERANCE (RESILIENCE.md §fleet). The router is where a
replica failure is turned back into a successful client request:

- DYNAMIC MEMBERSHIP. The argv replica list is only the bootstrap
  seed: replicas heartbeat `POST /register {"url": ...}` and are
  admitted once a health probe passes. Every replica carries a
  circuit breaker (closed -> open -> half-open): `breaker_fails`
  consecutive scrape/connect failures open it — the replica is
  evicted from routing — and after `breaker_open_s` ONE half-open
  probe per scrape tick decides rejoin vs re-open. A re-register from
  an evicted replica forces the probe immediately, so a warm restart
  is routable within one scrape interval.
- RETRY BUDGET. Failover re-attempts draw from a RetryBudget token
  bucket (resilience/retry.py) deposited by successful traffic: when
  the whole fleet degrades, the bucket drains and requests shed 503
  reason="retry_budget" instead of amplifying the overload into a
  retry storm.
- HEDGED REQUESTS. A request whose first response byte hasn't arrived
  after ~`hedge_ttft_mult` x the scraped TTFT p95 fires ONE hedge to
  the next-ranked replica; first response wins, the loser's connection
  is closed so its engine cancels and its KV blocks free. Hedges spend
  the same retry budget (no hedge storms either).
- FAILOVER WITH STREAM RESUME. The relay is frame-level (SSE), not
  byte-level: when a replica dies mid-stream the router re-sends the
  request to the next candidate and SKIPS the frames the client
  already has — decode is greedy and every replica holds identical
  weights, so the replayed frames are identical and the client sees
  one untruncated stream ending in `[DONE]`.

DISAGGREGATED SERVING (serve/kvxfer.py). With `kv_transfer` on, a
directory hit on a replica OTHER than the routed target no longer
re-routes the request — the router attaches transfer hints
(`x-ptpu-kv-source`: the advertising replica's url, `x-ptpu-kv-len`:
the matched prefix length) and the target PULLS the warm blocks into
its own host tier before admission. Replicas also advertise a serving
PHASE (`prefill` | `decode` | `mixed`, via /register and /kvprefixes):
when the fleet has a ready replica of the wanted phase, requests are
classified by prompt-vs-decode weight (prompt len >=
`phase_prefill_ratio` x max_new_tokens -> prefill-heavy) and sharded
over the matching replicas first — a prefill replica computes and
demotes the prefix, the decode replica pulls it and streams. A failed
pull costs nothing here: the target just re-prefills.

The relay is unbuffered per frame, so the `[DONE]` untruncated-stream
invariant survives the extra hop, and a client disconnect propagates:
the router's write fails, it drops the replica connection, the
replica's write fails, the engine cancels and frees KV blocks.

FLEET OBSERVABILITY (OBSERVABILITY.md §fleet). The router is also the
fleet's one observability front door:

- every proxied request gets a TRACE ID (minted here, or the client's
  own `x-ptpu-trace` passed through) injected on the replica hop; the
  router records its own route/relay spans under the same id, and
  `GET /trace/<id>` fetches each replica's span fragment and stitches
  router + replica rows into ONE Chrome trace with per-process pids —
  TTFT decomposes hop by hop;
- `GET /metrics/fleet` scrapes every replica's exposition and serves
  the federated merge (obs/fleetmetrics.py): counters sum exactly,
  log-bucketed histograms merge bucket-by-bucket (identical layout by
  construction), gauges re-label per replica;
- `GET /debug` is the replica table as the router sees it — ready
  state, breaker state, scraped gauges, prefix-directory size, and
  scrape staleness (also exported as
  `ptpu_router_scrape_age_seconds{replica}`, so routing-on-stale-data
  is visible on the scrape plane too). Scrapes run on per-replica
  threads with their own `scrape_timeout_s`, so one wedged replica
  cannot stall the loop past its interval — its staleness gauge just
  keeps growing while the rest of the fleet stays fresh.
"""

from __future__ import annotations

import itertools
import json
import queue
import re
import signal
import threading
import time
import uuid
import zlib
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from paddle_tpu.obs.fleetmetrics import federate
from paddle_tpu.obs.http import CONTENT_TYPE, json_route, obs_response
from paddle_tpu.obs.metrics import MetricsRegistry
from paddle_tpu.obs.tracing import RequestTracer, stitch_fragments
from paddle_tpu.resilience.errors import PREEMPT_EXIT_CODE
from paddle_tpu.resilience.retry import RetryBudget
from paddle_tpu.serve.sse import (DONE_SENTINEL, iter_sse,
                                  parse_prometheus_values, sse_event)
from paddle_tpu.utils.log import serve_event


def prefix_shard(prompt: Sequence[int], n: int, prefix_len: int = 32) -> int:
    """Stable shard index for a prompt: crc32 over the first
    `prefix_len` token ids (little-endian u32 each) mod n. Identical
    prefixes -> identical replica, across processes and runs."""
    head = list(prompt[:prefix_len])
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little") for t in head)
    return zlib.crc32(raw) % max(n, 1)


def prefix_digest(tokens: Sequence[int]) -> str:
    """8-hex-digit digest of a token prefix: crc32 over the ids as
    little-endian u32. MUST match engine/kvtier.py's prefix_digest
    (the replica side of the /kvprefixes advertisement) — duplicated
    here so a standalone router never imports the engine stack;
    tests/test_kvtier.py pins the two functions equal."""
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little")
                   for t in tokens)
    return format(zlib.crc32(raw), "08x")


# directory tier ranking: a device-resident prefix serves with zero
# copies, a host-tier one needs a DMA revival, anything else re-prefills
_TIER_RANK = {"device": 1, "host": 0}

# breaker state as a gauge level (ptpu_router_breaker_state)
_BREAKER_LEVEL = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

# advertised serving phase as a gauge level (ptpu_router_replica_phase)
_PHASE_LEVEL = {"mixed": 0.0, "prefill": 1.0, "decode": 2.0}

_LE_RE = re.compile(r'le="([^"]+)"')


def _bucket_quantile(vals: dict, family: str, q: float) -> float:
    """histogram_quantile over a flat scrape dict (same walk as
    serve_bench's verdicts): smallest bucket bound covering the q-rank,
    NaN when the family has no samples."""
    per_le: Dict[float, float] = {}
    prefix = family + "_bucket{"
    for key, v in vals.items():
        if not key.startswith(prefix):
            continue
        m = _LE_RE.search(key)
        if not m:
            continue
        le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
        per_le[le] = per_le.get(le, 0.0) + v
    if not per_le:
        return float("nan")
    bounds = sorted(per_le)
    total = per_le[bounds[-1]]
    if total <= 0:
        return float("nan")
    rank = q * total
    for le in bounds:
        if per_le[le] >= rank:
            return le
    return float("inf")


class ReplicaState:
    """What the scrape loop knows about one replica right now."""

    __slots__ = ("url", "host", "port", "ready", "reason", "hit_rate",
                 "queue_depth", "last_scrape", "prefixes", "fails",
                 "breaker", "open_until", "ttft_p95_ms", "registered",
                 "scraping", "phase")

    def __init__(self, url: str):
        parts = urlsplit(url)
        self.url = url.rstrip("/")
        self.host = parts.hostname
        self.port = parts.port or 80
        self.ready = False
        self.reason = "never scraped"
        self.hit_rate = 0.0
        self.queue_depth = 0.0
        self.last_scrape = 0.0
        # fleet prefix directory rows: {(len, digest): tier}
        self.prefixes: Dict[Tuple[int, str], str] = {}
        # circuit breaker: consecutive scrape/connect failures ->
        # closed -> open (evicted) -> half_open (one probe) -> closed
        self.fails = 0
        self.breaker = "closed"
        self.open_until = 0.0
        self.ttft_p95_ms = 0.0
        self.registered = False     # joined via POST /register
        self.scraping = False       # a scrape thread is on it right now
        # disaggregated serving phase (prefill|decode|mixed): from the
        # /register heartbeat or the /kvprefixes advertisement
        self.phase = "mixed"


class _RelayState:
    """Per-request relay progress shared across failover attempts:
    whether the client already has status+headers, and how many data
    frames it has received (replayed frames up to `sent` are skipped
    on a resumed stream)."""

    __slots__ = ("started", "sent")

    def __init__(self):
        self.started = False
        self.sent = 0


class Router:
    """`Router(["http://h:p1", "http://h:p2"]).start()` binds `.port`
    and proxies `/v1/completions`; `/metrics`, `/healthz`, `/readyz`
    describe the router itself (ready iff >=1 replica is ready). The
    url list is the bootstrap seed — replicas may also join live via
    `POST /register`."""

    def __init__(self, replica_urls: Sequence[str],
                 host: str = "127.0.0.1", port: int = 0,
                 prefix_len: int = 32,
                 scrape_interval_s: float = 0.5,
                 drain_deadline_s: float = 30.0,
                 connect_timeout_s: float = 10.0,
                 enable_directory: bool = True,
                 scrape_timeout_s: float = 2.0,
                 breaker_fails: int = 3,
                 breaker_open_s: float = 2.0,
                 retry_budget_ratio: float = 0.2,
                 retry_budget_burst: float = 16.0,
                 enable_hedge: bool = True,
                 hedge_ttft_mult: float = 3.0,
                 hedge_min_s: float = 0.05,
                 hedge_max_s: float = 2.0,
                 kv_transfer: bool = False,
                 phase_prefill_ratio: float = 2.0):
        self.replicas = [ReplicaState(u) for u in replica_urls]
        self.host = host
        self.port = port
        self.prefix_len = prefix_len
        # False reverts routing to pure hash stickiness (A/B baseline)
        self.enable_directory = enable_directory
        self.scrape_interval_s = scrape_interval_s
        self.drain_deadline_s = drain_deadline_s
        self.connect_timeout_s = connect_timeout_s
        self.scrape_timeout_s = scrape_timeout_s
        self.breaker_fails = max(1, int(breaker_fails))
        self.breaker_open_s = breaker_open_s
        self.enable_hedge = enable_hedge
        self.hedge_ttft_mult = hedge_ttft_mult
        self.hedge_min_s = hedge_min_s
        self.hedge_max_s = hedge_max_s
        # kv_transfer flips a directory hit from RE-ROUTING (promote
        # the advertising replica) to TRANSFER HINTS (keep the routed
        # target, tell it where to pull the warm blocks from). Opt-in:
        # re-routing is still the right default for a homogeneous
        # fleet with no transfer plane.
        self.kv_transfer = kv_transfer
        # prompt_len >= ratio * max_new_tokens classifies a request as
        # prefill-heavy when phase-specialized replicas exist
        self.phase_prefill_ratio = phase_prefill_ratio
        self.exit_code: Optional[int] = None

        self.obs = MetricsRegistry()    # the router's OWN process story
        self.retry_budget = RetryBudget(ratio=retry_budget_ratio,
                                        burst=retry_budget_burst,
                                        registry=self.obs)
        self._m_routed = self.obs.counter(
            "ptpu_router_requests_total",
            "Requests proxied, by replica and route kind",
            labelnames=("replica", "kind"))  # kind=primary|directory|fallback
        self._m_sheds = self.obs.counter(
            "ptpu_router_sheds_total",
            "Requests the router itself bounced (503)",
            labelnames=("reason",))  # reason=draining|no_replica|retry_budget
        self._m_replica_ready = self.obs.gauge(
            "ptpu_router_replica_ready", "1 when the replica passes /readyz",
            labelnames=("replica",))
        self._m_replica_hit = self.obs.gauge(
            "ptpu_router_replica_hit_rate",
            "Replica's scraped ptpu_kv_hit_rate", labelnames=("replica",))
        self._m_replica_depth = self.obs.gauge(
            "ptpu_router_replica_queue_depth",
            "Replica's scraped ptpu_sched_queue_depth",
            labelnames=("replica",))
        self._m_inflight = self.obs.gauge(
            "ptpu_router_inflight", "Streams currently being proxied")
        self._m_draining = self.obs.gauge(
            "ptpu_router_draining", "1 while the router drains")
        self._m_dir_hits = self.obs.counter(
            "ptpu_router_directory_hits_total",
            "Requests routed to a replica the prefix directory "
            "identified as holding a warm matching prefix")
        self._m_replica_prefixes = self.obs.gauge(
            "ptpu_router_replica_prefixes",
            "Warm prefixes the replica advertises on /kvprefixes",
            labelnames=("replica",))
        self._m_scrape_age = self.obs.gauge(
            "ptpu_router_scrape_age_seconds",
            "Seconds since the replica's gauges were last scraped "
            "successfully (-1 = never); routing decisions are only as "
            "fresh as this", labelnames=("replica",))
        self._m_retries = self.obs.counter(
            "ptpu_router_retries_total",
            "Failover re-attempts, by what failed on the previous try",
            labelnames=("kind",))       # kind=connect|shed|stream
        self._m_hedges = self.obs.counter(
            "ptpu_router_hedges_total",
            "Hedged requests fired against a slow first replica",
            labelnames=("outcome",))    # outcome=won|lost|denied
        self._m_breaker = self.obs.gauge(
            "ptpu_router_breaker_state",
            "Replica circuit breaker: 0 closed, 1 half-open, 2 open "
            "(evicted from routing)", labelnames=("replica",))
        self._m_membership = self.obs.counter(
            "ptpu_router_membership_events_total",
            "Dynamic-membership transitions",
            labelnames=("event",))      # event=register|evict|rejoin
        self._m_replica_ttft = self.obs.gauge(
            "ptpu_router_replica_ttft_p95_ms",
            "Replica's scraped TTFT p95 (bucket upper bound) — the "
            "base of the hedge delay", labelnames=("replica",))
        self._m_kvx_hints = self.obs.counter(
            "ptpu_router_kvxfer_hints_total",
            "Requests served with a KV transfer hint attached (the "
            "target was told to pull the warm prefix from a peer)")
        self._m_phase_routed = self.obs.counter(
            "ptpu_router_phase_routed_total",
            "Requests sharded over phase-matching replicas",
            labelnames=("phase",))      # phase=prefill|decode
        self._m_replica_phase = self.obs.gauge(
            "ptpu_router_replica_phase",
            "Replica's advertised serving phase: 0 mixed, 1 prefill, "
            "2 decode", labelnames=("replica",))

        # router-side spans under the fleet trace id: one synthetic
        # request id per proxied POST, stitched with the replica's
        # engine spans by /trace/<id>
        self.tracer = RequestTracer(keep_last=512, process_name="router")
        self._trace_seq = itertools.count(1)

        self._server: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._scrape_thread: Optional[threading.Thread] = None
        self._stop_scrape = threading.Event()
        # One lock covers the router's mutable shared state: the in-flight
        # count AND every ReplicaState field the scrape loop and handler
        # threads both touch (including membership appends). Network I/O
        # never happens under it.
        self._lock = threading.Lock()
        self._inflight = 0          # guarded-by: self._lock
        self._draining = False      # guarded-by: self._lock
        self._drained = threading.Event()

    # -- membership / circuit breaker -------------------------------------
    def _note_failure(self, r: ReplicaState, reason: str) -> None:
        """One scrape/connect/stream failure on `r`: demote from
        routing and advance the breaker — `breaker_fails` consecutive
        failures open it (eviction), a failed half-open probe re-opens
        it."""
        evicted = False
        with self._lock:
            r.ready = False
            r.reason = reason
            r.fails += 1
            if r.breaker == "closed" and r.fails >= self.breaker_fails:
                r.breaker = "open"
                r.open_until = time.monotonic() + self.breaker_open_s
                evicted = True
            elif r.breaker == "half_open":
                r.breaker = "open"
                r.open_until = time.monotonic() + self.breaker_open_s
            state, fails = r.breaker, r.fails
        self._m_replica_ready.labels(replica=r.url).set(0.0)
        self._m_breaker.labels(replica=r.url).set(_BREAKER_LEVEL[state])
        if evicted:
            self._m_membership.labels(event="evict").inc()
            serve_event("router_evict", replica=r.url, fails=fails,
                        reason=reason)

    def register_replica(self, url: str,
                         phase: Optional[str] = None) -> ReplicaState:
        """Admit (or re-admit) a replica by base url: the programmatic
        half of POST /register. New url -> appended to the table and
        probed; evicted url -> breaker forced half-open and probed NOW,
        so a restarted replica is routable without waiting out
        `breaker_open_s`. `phase` (when the heartbeat carries one)
        updates the replica's advertised serving phase."""
        url = url.rstrip("/")
        with self._lock:
            r = next((x for x in self.replicas if x.url == url), None)
            is_new = r is None
            if is_new:
                r = ReplicaState(url)
                r.registered = True
                self.replicas.append(r)
            elif r.breaker == "open":
                r.breaker = "half_open"
                r.open_until = 0.0
            if phase in _PHASE_LEVEL:
                r.phase = phase
            ready = r.ready
            phase_pub = r.phase
        self._m_replica_phase.labels(replica=r.url).set(
            _PHASE_LEVEL[phase_pub])
        if is_new:
            self._m_membership.labels(event="register").inc()
            serve_event("router_register", replica=url, phase=phase_pub,
                        replicas=len(self.replicas))
        if not ready:
            # probe on the caller's thread (never under the lock): a
            # passing probe flips it ready/rejoined immediately
            self._scrape_once(r)
        return r

    def _handle_register(self, h: BaseHTTPRequestHandler) -> None:
        try:
            length = int(h.headers.get("Content-Length", "0"))
            body = json.loads(h.rfile.read(length) or b"{}")
            url = str(body.get("url") or "")
            phase = body.get("phase")
        except (ValueError, json.JSONDecodeError):
            url, phase = "", None
        if not url.startswith("http"):
            payload = json.dumps({"ok": False,
                                  "error": "body must be {'url': "
                                           "'http://host:port'}"})
            self._send_json(h, 400, payload)
            return
        r = self.register_replica(url, phase=phase)
        with self._lock:
            known = len(self.replicas)
            ready = r.ready
        self._send_json(h, 200, json.dumps(
            {"ok": True, "ready": ready, "replicas": known}))

    @staticmethod
    def _send_json(h: BaseHTTPRequestHandler, status: int,
                   payload: str) -> None:
        body = payload.encode() + b"\n"
        try:
            h.send_response(status)
            h.send_header("Content-Type", "application/json")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -- scrape loop ------------------------------------------------------
    def _scrape_once(self, r: ReplicaState) -> None:
        # HTTP happens into locals; ReplicaState fields are published in
        # one locked write so handler threads (plan_route, _proxy's
        # connect-failure demotion) never see a half-updated replica.
        ready = False
        reason = ""
        vals = {}
        prefixes: Dict[Tuple[int, str], str] = {}
        phase: Optional[str] = None
        try:
            conn = HTTPConnection(r.host, r.port,
                                  timeout=self.scrape_timeout_s)
            try:
                conn.request("GET", "/readyz")
                resp = conn.getresponse()
                body = resp.read().decode("utf-8", "replace").strip()
                ready = resp.status == 200
                reason = "" if ready else body
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                text = resp.read().decode("utf-8", "replace")
                # fleet prefix directory: tolerate replicas without the
                # endpoint (404 / bad JSON -> empty advertisement, the
                # scrape itself still counts as healthy)
                conn.request("GET", "/kvprefixes")
                presp = conn.getresponse()
                pbody = presp.read()
                if presp.status == 200:
                    try:
                        payload = json.loads(pbody)
                        # phase rides the same advertisement: argv-
                        # seeded replicas never POST /register
                        if payload.get("phase") in _PHASE_LEVEL:
                            phase = payload["phase"]
                        for row in payload.get("prefixes", []):
                            prefixes[(int(row["len"]),
                                      str(row["digest"]))] = \
                                str(row.get("tier", "device"))
                    except (ValueError, KeyError, TypeError):
                        prefixes = {}
            finally:
                conn.close()
            vals = parse_prometheus_values(text)
        except OSError as e:
            self._note_failure(r, f"scrape failed: {e}")
            with self._lock:
                last_scrape = r.last_scrape
            age = (time.monotonic() - last_scrape) if last_scrape else -1.0
            self._m_scrape_age.labels(replica=r.url).set(age)
            return
        ttft = _bucket_quantile(vals, "ptpu_serve_ttft_ms", 0.95)
        with self._lock:
            rejoined = r.breaker != "closed"
            r.breaker = "closed"
            r.fails = 0
            r.open_until = 0.0
            r.ready = ready
            r.reason = reason
            r.prefixes = prefixes
            if phase is not None:
                r.phase = phase
            phase_pub = r.phase
            if vals:
                r.hit_rate = vals.get("ptpu_kv_hit_rate", 0.0)
                r.queue_depth = vals.get("ptpu_sched_queue_depth", 0.0)
                if ttft == ttft and ttft != float("inf"):   # not NaN/Inf
                    r.ttft_p95_ms = ttft
                r.last_scrape = time.monotonic()
            hit_rate, queue_depth = r.hit_rate, r.queue_depth
            last_scrape, ttft_pub = r.last_scrape, r.ttft_p95_ms
        if rejoined:
            self._m_membership.labels(event="rejoin").inc()
            serve_event("router_rejoin", replica=r.url, ready=ready)
        self._m_replica_ready.labels(replica=r.url).set(1.0 if ready else 0.0)
        self._m_breaker.labels(replica=r.url).set(0.0)
        self._m_replica_hit.labels(replica=r.url).set(hit_rate)
        self._m_replica_depth.labels(replica=r.url).set(queue_depth)
        self._m_replica_prefixes.labels(replica=r.url).set(
            float(len(prefixes)))
        self._m_replica_ttft.labels(replica=r.url).set(ttft_pub)
        self._m_replica_phase.labels(replica=r.url).set(
            _PHASE_LEVEL[phase_pub])
        # staleness: keeps GROWING while scrapes fail, so alerting can
        # tell "replica down" from "replica briefly slow"
        age = (time.monotonic() - last_scrape) if last_scrape else -1.0
        self._m_scrape_age.labels(replica=r.url).set(age)

    def _scrape_guard(self, r: ReplicaState) -> None:
        try:
            self._scrape_once(r)
        finally:
            with self._lock:
                r.scraping = False

    def scrape_now(self, wait_s: Optional[float] = None) -> None:
        """One pass over every replica, each on its own thread with its
        own `scrape_timeout_s` — a wedged /metrics handler delays ONLY
        its replica (whose in-flight flag also stops pileup across
        ticks); the rest of the fleet stays fresh. Joins up to `wait_s`
        (default: one scrape timeout + slack) so startup and tests see
        a synchronous pass."""
        with self._lock:
            reps = list(self.replicas)
        now = time.monotonic()
        threads: List[threading.Thread] = []
        for r in reps:
            with self._lock:
                if r.scraping:          # previous scrape still stuck on it
                    skip, half_open = True, False
                elif r.breaker == "open" and now < r.open_until:
                    skip, half_open = True, False   # evicted: wait out open_s
                else:
                    skip = False
                    half_open = r.breaker == "open"
                    if half_open:
                        r.breaker = "half_open"     # one probe
                    r.scraping = True
                last_scrape = r.last_scrape
            if skip:
                age = (now - last_scrape) if last_scrape else -1.0
                self._m_scrape_age.labels(replica=r.url).set(age)
                continue
            if half_open:
                self._m_breaker.labels(replica=r.url).set(
                    _BREAKER_LEVEL["half_open"])
            t = threading.Thread(target=self._scrape_guard, args=(r,),
                                 daemon=True, name="ptpu-router-scrape-one")
            t.start()
            threads.append(t)
        deadline = time.monotonic() + (
            wait_s if wait_s is not None else self.scrape_timeout_s + 0.5)
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def _scrape_loop(self) -> None:
        # wait_s=0: the periodic tick never waits on the scrape
        # threads, so the cadence stays `scrape_interval_s` even while
        # one replica's scrape is timing out — a black-holed member
        # must not slow down how fast a DEAD member is detected. The
        # per-replica `scraping` flag stops pileup on the slow one.
        while not self._stop_scrape.wait(self.scrape_interval_s):
            self.scrape_now(wait_s=0.0)

    # -- routing policy ---------------------------------------------------
    def _directory_best(self, prompt: Sequence[int], snapshot: dict
                        ) -> Tuple[Optional[ReplicaState], int]:
        """The ready replica advertising the LONGEST prefix of `prompt`
        at the HOTTEST tier plus that matched length, or (None, 0) when
        the fleet directory has no match. Digests are memoized per
        length: one crc32 per distinct advertised prefix length, not
        per (replica, row)."""
        best: Optional[ReplicaState] = None
        best_score = (-1, -1)
        memo: Dict[int, str] = {}
        for r, (ready, _, _, prefixes, _, _) in snapshot.items():
            if not ready:
                continue
            for (ln, dg), tier in prefixes.items():
                score = (ln, _TIER_RANK.get(tier, -1))
                if ln > len(prompt) or score <= best_score:
                    continue
                if ln not in memo:
                    memo[ln] = prefix_digest(prompt[:ln])
                if memo[ln] == dg:
                    best, best_score = r, score
        return best, max(0, best_score[0])

    def _classify_phase(self, prompt: Sequence[int],
                        max_new_tokens: Optional[int]) -> str:
        """Which phase specialization serves this request best:
        "prefill" when the prompt dominates the work (prompt len >=
        phase_prefill_ratio x expected decode tokens), else "decode"."""
        max_new = max(1, int(max_new_tokens)
                      if max_new_tokens is not None else 64)
        if len(prompt) >= self.phase_prefill_ratio * max_new:
            return "prefill"
        return "decode"

    def _plan(self, prompt: Sequence[int],
              max_new_tokens: Optional[int] = None
              ) -> Tuple[List[ReplicaState], Optional[ReplicaState],
                         Optional[ReplicaState], int, Optional[str]]:
        """(candidates in try-order, directory pick or None, sticky,
        matched directory prefix length, phase specialization applied
        or None). The hash primary maps over the READY set (in table
        order), so a dead replica's shard re-maps over survivors;
        `sticky` is the hash over the FULL member table — the label
        reference point, so stickiness verdicts don't shift when
        readiness flaps. Ready fallbacks rank best-first (highest
        scraped hit rate, shortest queue); routable-but-not-ready
        replicas trail as a last ditch (the scrape may be stale);
        breaker-open replicas are not tried at all.

        PHASE. When the fleet has a ready replica whose advertised
        phase exactly matches the request's classification, the hash
        shards over the MATCHING set first and the rest of the ready
        fleet trails — a mixed fleet (no specialists) routes exactly as
        before.

        DIRECTORY. When the fleet prefix directory knows a ready
        replica holding a warm prefix of this prompt: without
        kv_transfer that replica is promoted to the front (warm KV
        beats where the hash says the prefix should live); with
        kv_transfer the ORDER STANDS and the caller attaches transfer
        hints instead — the routed target pulls the blocks from
        dir_pick (serve/kvxfer.py)."""
        with self._lock:    # one consistent snapshot to rank against
            stats = {r: (r.ready, r.hit_rate, r.queue_depth,
                         dict(r.prefixes), r.breaker, r.phase)
                     for r in self.replicas}
        members = list(stats.keys())
        if not members:
            return [], None, None, 0, None
        sticky = members[prefix_shard(prompt, len(members),
                                      self.prefix_len)]
        routable = [r for r in members if stats[r][4] != "open"]
        ready = [r for r in routable if stats[r][0]]
        want: Optional[str] = None
        if ready:
            pool = ready
            wanted = self._classify_phase(prompt, max_new_tokens)
            matching = [r for r in ready if stats[r][5] == wanted]
            if matching and len(matching) < len(ready):
                # phase specialists exist: shard over them first
                pool = matching
                want = wanted
            primary = pool[prefix_shard(prompt, len(pool),
                                        self.prefix_len)]
            fallbacks = sorted(
                (r for r in pool if r is not primary),
                key=lambda r: (-stats[r][1], stats[r][2]))
            order = [primary] + fallbacks
            order += sorted(
                (r for r in ready if r not in pool),
                key=lambda r: (-stats[r][1], stats[r][2]))
            in_order = set(map(id, order))
            order += [r for r in routable if id(r) not in in_order]
        else:
            # none ready: try the routable set anyway (scrapes may be
            # stale) — but NEVER a breaker-open replica; a fully open
            # fleet sheds until a half-open probe rejoins someone
            order = routable
        dir_pick, dir_len = ((self._directory_best(prompt, stats))
                             if self.enable_directory else (None, 0))
        if (dir_pick is not None and not self.kv_transfer
                and dir_pick is not order[0]):
            if dir_pick in order:
                order.remove(dir_pick)
            order.insert(0, dir_pick)
        return order, dir_pick, sticky, dir_len, want

    def plan_route(self, prompt: Sequence[int]) -> List[ReplicaState]:
        """Candidate replicas in try-order (see _plan)."""
        return self._plan(prompt)[0]

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Router":
        if self._server is not None:
            return self
        self.scrape_now()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, daemon=True, name="ptpu-router-scrape")
        self._scrape_thread.start()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):                       # noqa: N802
                outer._handle_get(self)

            def do_POST(self):                      # noqa: N802
                outer._handle_post(self)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ptpu-router-http")
        self._serve_thread.start()
        serve_event("router_listening", host=self.host, port=self.port,
                    replicas=[r.url for r in self.replicas])
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def install_signals(self) -> "Router":
        def _on_signal(signum, frame):
            serve_event("router_sigterm", signal=int(signum))
            threading.Thread(target=self.begin_drain, daemon=True).start()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
        return self

    def begin_drain(self) -> None:
        """Stop admitting; wait for in-flight proxied streams to finish
        (bounded by drain_deadline_s); record exit code 75."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._m_draining.set(1.0)
        deadline = time.monotonic() + self.drain_deadline_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.05)
        self.exit_code = PREEMPT_EXIT_CODE
        serve_event("router_drained", exit_code=self.exit_code,
                    inflight_at_exit=self._inflight)
        self._drained.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        self._drained.wait(timeout)
        return self.exit_code

    def stop(self) -> None:
        self._stop_scrape.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
            self._serve_thread = None
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None

    # -- HTTP -------------------------------------------------------------
    def readiness(self) -> Tuple[bool, str]:
        with self._lock:
            if self._draining:
                return False, "draining"
            if any(r.ready for r in self.replicas):
                return True, ""
        return False, "no ready replicas"

    def _fetch(self, r: ReplicaState, path: str,
               timeout: Optional[float] = None) -> Optional[str]:
        """GET `path` from a replica, body text on 200 else None. Runs
        on handler threads with NO router lock held (network under the
        lock is forbidden — see self._lock's comment). `timeout`
        defaults to the proxy connect timeout; aggregation routes pass
        `scrape_timeout_s` so one hung replica delays, not stalls,
        the merge."""
        try:
            conn = HTTPConnection(
                r.host, r.port,
                timeout=self.connect_timeout_s if timeout is None
                else timeout)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return None
                return body.decode("utf-8", "replace")
            finally:
                conn.close()
        except OSError:
            return None

    def _fleet_route(self):
        """/metrics/fleet: scrape every replica NOW and serve the
        federated exposition. Unreachable replicas are simply absent
        from the merge (their staleness still shows on the router's
        own ptpu_router_scrape_age_seconds)."""
        expositions: Dict[str, str] = {}
        for r in self.replicas:
            text = self._fetch(r, "/metrics", timeout=self.scrape_timeout_s)
            if text is not None:
                expositions[r.url] = text
        return 200, CONTENT_TYPE, federate(expositions).encode()

    def _trace_route(self, path: str):
        """/trace/<id>: merge the router's own span fragment for the
        trace id with every replica's into one Chrome trace — each
        process gets its own pid row, timestamps are epoch-anchored
        (now_us) so no shifting is needed."""
        tid = path[len("/trace/"):].strip("/")
        fragments: List[Tuple[str, dict]] = []
        own = self.tracer.trace_fragment(tid) if tid else None
        if own is not None:
            fragments.append(("router", own))
        for r in self.replicas:
            text = (self._fetch(r, "/trace/" + tid,
                                timeout=self.scrape_timeout_s)
                    if tid else None)
            if text is None:
                continue
            try:
                frag = json.loads(text)
            except ValueError:
                continue
            fragments.append((f"replica {r.url}", frag))
        if not fragments:
            return (404, "application/json",
                    json.dumps({"error": "unknown trace",
                                "trace_id": tid}).encode() + b"\n")
        merged = stitch_fragments(fragments, trace_id=tid)
        return (200, "application/json",
                json.dumps(merged).encode() + b"\n")

    def _debug_payload(self) -> dict:
        """/debug: the replica table as routing sees it right now."""
        now = time.monotonic()
        with self._lock:
            replicas = [{
                "url": r.url,
                "ready": r.ready,
                "reason": r.reason,
                "hit_rate": r.hit_rate,
                "queue_depth": r.queue_depth,
                "scrape_age_s": (round(now - r.last_scrape, 3)
                                 if r.last_scrape else None),
                "prefixes": len(r.prefixes),
                "breaker": r.breaker,
                "fails": r.fails,
                "registered": r.registered,
                "ttft_p95_ms": r.ttft_p95_ms,
                "phase": r.phase,
            } for r in self.replicas]
            inflight = self._inflight
            draining = self._draining
        return {"replicas": replicas, "inflight": inflight,
                "draining": draining,
                "scrape_interval_s": self.scrape_interval_s,
                "directory_enabled": self.enable_directory,
                "retry_budget_tokens": self.retry_budget.tokens(),
                "hedge_enabled": self.enable_hedge,
                "kv_transfer": self.kv_transfer}

    def _handle_get(self, h: BaseHTTPRequestHandler) -> None:
        resp = obs_response(
            h.path, self.obs, readiness=self.readiness,
            routes={"/metrics/fleet": self._fleet_route,
                    "/debug": json_route(self._debug_payload)},
            prefix_routes={"/trace/": self._trace_route})
        if resp is None:
            resp = (404, "text/plain", b"not found\n")
        status, ctype, body = resp
        try:
            h.send_response(status)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _shed(self, h: BaseHTTPRequestHandler, reason: str) -> None:
        self._m_sheds.labels(reason=reason).inc()
        body = json.dumps({"error": "overloaded", "reason": reason,
                           "retry_after_s": 1.0}).encode() + b"\n"
        try:
            h.send_response(503)
            h.send_header("Content-Type", "application/json")
            h.send_header("Content-Length", str(len(body)))
            h.send_header("Retry-After", "1")
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _handle_post(self, h: BaseHTTPRequestHandler) -> None:
        path = h.path.split("?")[0]
        if path == "/register":
            self._handle_register(h)
            return
        if path != "/v1/completions":
            self._handle_get(h)         # reuse the 404 path
            return
        if self._draining:
            self._shed(h, "draining")
            return
        max_new: Optional[int] = None
        try:
            length = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(length)
            body = json.loads(raw or b"{}")
            prompt = body.get("prompt") or []
            if isinstance(prompt, str):
                # string prompts tokenize REPLICA-side; route on the
                # utf-8 bytes — stable across processes, and identical
                # strings still shard sticky (the directory simply
                # won't match until token-level requests warmed it)
                prompt = list(prompt.encode("utf-8"))
            mn = body.get("max_new_tokens")
            if mn is not None:
                max_new = int(mn)
        except (ValueError, TypeError, json.JSONDecodeError):
            raw, prompt = b"{}", []
        # fleet trace id: honor the client's, else mint one; the same
        # id tags the router's route/relay spans AND rides the replica
        # hop as x-ptpu-trace, so /trace/<id> can stitch both processes
        tid = h.headers.get("x-ptpu-trace") or uuid.uuid4().hex[:16]
        rid = next(self._trace_seq)
        self.tracer.set_trace_id(rid, tid)
        self.tracer.span_begin(rid, "route")
        candidates, dir_pick, sticky, dir_len, want = self._plan(
            prompt, max_new)
        if not candidates:
            self.tracer.on_finish(rid, "shed")
            self._shed(h, "no_replica")
            return
        if want is not None:
            self._m_phase_routed.labels(phase=want).inc()
        self._track_inflight(+1)
        try:
            self._proxy(h, raw, prompt, candidates, dir_pick, sticky,
                        dir_len=dir_len, tid=tid, rid=rid)
        finally:
            self._track_inflight(-1)

    def _track_inflight(self, delta: int) -> None:
        """Count and gauge move together under the lock: the old code
        re-read `self._inflight` outside it, so two crossing requests
        could publish stale values out of order and leave the gauge
        permanently off. The gauge's own child lock is leaf-level (it
        never takes router locks), so nesting it here cannot deadlock."""
        with self._lock:
            self._inflight += delta
            self._m_inflight.set(float(self._inflight))

    # -- proxy data path --------------------------------------------------
    def _connect_stream(self, r: ReplicaState, raw: bytes,
                        headers: dict):
        """POST the completion to one replica.
        ("ok", conn, resp) | ("shed", body) | ("error",)."""
        try:
            conn = HTTPConnection(r.host, r.port,
                                  timeout=self.connect_timeout_s)
            conn.request(
                "POST", "/v1/completions", body=raw, headers=headers)
            resp = conn.getresponse()
        except OSError as e:
            self._note_failure(r, f"connect failed: {e}")
            return ("error",)
        if resp.status == 503:      # replica shed: caller tries the next
            body = resp.read()
            conn.close()
            return ("shed", body)
        return ("ok", conn, resp)

    def _hedge_delay_s(self, r: ReplicaState) -> float:
        """How long to give `r`'s first response byte before hedging:
        hedge_ttft_mult x its scraped TTFT p95 (fleet max when `r` has
        no samples yet), clamped to [hedge_min_s, hedge_max_s]. An
        unmeasured fleet waits the full hedge_max_s — no speculative
        traffic before there is evidence of what slow means."""
        with self._lock:
            p95 = r.ttft_p95_ms or max(
                (x.ttft_p95_ms for x in self.replicas if x.ready),
                default=0.0)
        if p95 <= 0:
            return self.hedge_max_s
        return min(max(self.hedge_ttft_mult * p95 / 1000.0,
                       self.hedge_min_s), self.hedge_max_s)

    def _open_stream(self, r: ReplicaState, raw: bytes, headers: dict,
                     hedge_pool: Optional[List[ReplicaState]],
                     rid: Optional[int]):
        """Open the stream on `r`; with a non-empty `hedge_pool`, race
        ONE hedge to its head after the TTFT-derived delay — first
        response wins, the loser's connection is closed (the engine
        behind it cancels and frees KV). The hedge spends a retry-
        budget token when it fires; an empty bucket silently skips it.
        Returns ("ok", replica, conn, resp) | ("shed", body) |
        ("error",)."""
        if not hedge_pool:
            res = self._connect_stream(r, raw, headers)
            return res if res[0] != "ok" else ("ok", r, res[1], res[2])
        delay = self._hedge_delay_s(r)
        results: "queue.Queue" = queue.Queue()
        decided = threading.Event()
        fired = threading.Event()
        hedge_target = hedge_pool[0]

        def attempt(rep: ReplicaState, tag: str, wait_s: float) -> None:
            if wait_s > 0.0 and decided.wait(wait_s):
                return                  # first answered before the delay
            if tag == "hedge":
                if not self.retry_budget.try_spend("router_hedge"):
                    self._m_hedges.labels(outcome="denied").inc()
                    results.put((tag, rep, ("error",)))
                    return
                fired.set()
                if rid is not None:
                    self.tracer.mark(rid, "hedge_fired", replica=rep.url)
            results.put((tag, rep, self._connect_stream(rep, raw, headers)))

        threads = [
            threading.Thread(target=attempt, args=(r, "first", 0.0),
                             daemon=True),
            threading.Thread(target=attempt,
                             args=(hedge_target, "hedge", delay),
                             daemon=True)]
        for t in threads:
            t.start()
        chosen = None
        first_failure = None
        outstanding = 2
        overall = self.connect_timeout_s + delay + 1.0
        endline = time.monotonic() + overall
        while outstanding > 0 and chosen is None:
            try:
                tag, rep, res = results.get(
                    timeout=max(0.1, endline - time.monotonic()))
            except queue.Empty:
                break
            outstanding -= 1
            if res[0] == "ok":
                chosen = (tag, rep, res)
            elif tag == "first":
                first_failure = res
                if not fired.is_set():
                    # the primary failed before any hedge went out:
                    # cancel the sleeping hedge and fail over normally
                    decided.set()
                    return first_failure
            # a failed hedge: keep waiting for the primary
        decided.set()
        if chosen is None:
            return first_failure if first_failure is not None else ("error",)
        tag, rep, res = chosen
        if tag == "hedge":
            self._m_hedges.labels(outcome="won").inc()
        elif fired.is_set():
            self._m_hedges.labels(outcome="lost").inc()
        if outstanding > 0:
            # the loser is still connecting/streaming: reap its socket
            # when it resolves so the engine behind it cancels
            def reap(n: int) -> None:
                for _ in range(n):
                    try:
                        _, _, late = results.get(
                            timeout=self.connect_timeout_s + 5.0)
                    except queue.Empty:
                        return
                    if late[0] == "ok":
                        for obj in (late[2], late[1]):
                            try:
                                obj.close()
                            except OSError:
                                pass
            threading.Thread(target=reap, args=(outstanding,),
                             daemon=True).start()
        return ("ok", rep, res[1], res[2])

    def _client_write(self, h: BaseHTTPRequestHandler,
                      data: bytes) -> bool:
        try:
            h.wfile.write(data)
            h.wfile.flush()
            return True
        except OSError:
            return False

    def _relay_sse(self, h: BaseHTTPRequestHandler, resp,
                   state: _RelayState) -> str:
        """Frame-level relay: forward SSE frames as they arrive,
        skipping the first `state.sent` data frames (a resumed stream
        replays from the start — greedy decode on identical weights
        makes the replay identical). Returns "done" ([DONE] relayed /
        non-stream response fully copied), "client_gone" (our write
        failed), or "truncated" (upstream died first — the caller
        fails over)."""
        ctype = resp.getheader("Content-Type", "") or ""
        if resp.status != 200 or "text/event-stream" not in ctype:
            if state.started:
                return "truncated"  # can't splice a non-stream mid-stream
            self._relay(h, resp)
            return "done"
        if not state.started:
            try:
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.end_headers()
            except OSError:
                return "client_gone"
            state.started = True
        n = 0
        try:
            for payload in iter_sse(resp):
                if payload == DONE_SENTINEL:
                    if not self._client_write(h, sse_event(payload)):
                        return "client_gone"
                    return "done"
                n += 1
                if n <= state.sent:
                    continue        # the client already has this frame
                if not self._client_write(h, sse_event(payload)):
                    return "client_gone"
                state.sent = n
        except OSError:             # read timeout / reset from upstream
            pass
        return "truncated"          # EOF without [DONE]

    def _proxy(self, h: BaseHTTPRequestHandler, raw: bytes,
               prompt: Sequence[int],
               candidates: List[ReplicaState],
               dir_pick: Optional[ReplicaState] = None,
               sticky: Optional[ReplicaState] = None, *,
               dir_len: int = 0,
               tid: Optional[str] = None,
               rid: Optional[int] = None) -> None:
        """Drive one request to a `[DONE]`-terminated stream across as
        many replicas as the retry budget allows: connect failures and
        replica 503s fail over BEFORE the first byte; a mid-stream
        death fails over WITH RESUME (state.sent frames are skipped on
        the replay); the first attempt may hedge. Every re-attempt
        after the first costs a budget token — an empty bucket sheds
        503 reason="retry_budget" rather than storming a degraded
        fleet. The served replica's route kind: "primary" when it is
        the full-table hash pick (the directory agreeing with the hash
        stays "primary" so stickiness verdicts survive), "directory"
        when the fleet prefix directory OVERRODE the hash, "fallback"
        otherwise."""
        headers = {"Content-Type": "application/json"}
        if tid:
            headers["x-ptpu-trace"] = tid
        state = _RelayState()
        pending = list(candidates)
        last_shed: Optional[bytes] = None
        attempt = 0
        retry_kind = "connect"
        while pending:
            r = pending.pop(0)
            attempt += 1
            if attempt > 1:
                if not self.retry_budget.try_spend("router"):
                    if rid is not None:
                        self.tracer.on_finish(rid, "budget_exhausted")
                    if not state.started:
                        self._shed(h, "retry_budget")
                    return
                self._m_retries.labels(kind=retry_kind).inc()
                if rid is not None:
                    self.tracer.mark(rid, "failover", replica=r.url,
                                     kind=retry_kind)
            hedge_pool = (pending if attempt == 1 and self.enable_hedge
                          and pending and not state.started else None)
            # kv_transfer: when the warm prefix lives on a replica we
            # are NOT about to try, tell this attempt's target where to
            # pull it from (per-attempt copy: a later attempt may BE
            # dir_pick and must not be told to pull from itself)
            hinted = (self.kv_transfer and dir_pick is not None
                      and dir_len > 0 and r is not dir_pick)
            attempt_headers = headers
            if hinted:
                attempt_headers = dict(headers)
                attempt_headers["x-ptpu-kv-source"] = dir_pick.url
                attempt_headers["x-ptpu-kv-len"] = str(dir_len)
            res = self._open_stream(r, raw, attempt_headers,
                                    hedge_pool, rid)
            if res[0] == "shed":
                last_shed = res[1]
                retry_kind = "shed"
                if rid is not None:
                    self.tracer.mark(rid, "replica_shed", replica=r.url)
                continue
            if res[0] == "error":
                retry_kind = "connect"
                if rid is not None:
                    self.tracer.mark(rid, "connect_failed", replica=r.url)
                continue
            _, r_used, conn, resp = res
            if r_used is not r:
                # the hedge won: it came out of pending; the slow
                # primary goes to the back as a last-resort retry
                if r_used in pending:
                    pending.remove(r_used)
                pending.append(r)
            if r_used is sticky:
                kind = "primary"
            elif dir_pick is not None and r_used is dir_pick:
                kind = "directory"
            else:
                kind = "fallback"
            if dir_pick is not None and r_used is dir_pick:
                self._m_dir_hits.inc()
            if hinted and r_used is not dir_pick:
                # the served replica was told where to pull warm KV —
                # the directory paid off WITHOUT re-routing
                self._m_dir_hits.inc()
                self._m_kvx_hints.inc()
            self._m_routed.labels(replica=r_used.url, kind=kind).inc()
            if rid is not None:
                self.tracer.mark(rid, "routed", replica=r_used.url,
                                 kind=kind)
                self.tracer.span_begin(rid, "relay")
            outcome = self._relay_sse(h, resp, state)
            conn.close()
            if outcome == "done":
                if rid is not None:
                    self.tracer.on_finish(rid, "relayed")
                return
            if outcome == "client_gone":
                if rid is not None:
                    self.tracer.on_finish(rid, "client_gone")
                return
            # upstream died mid-stream: breaker takes note, the next
            # candidate resumes past the frames the client already has
            self._note_failure(r_used, "stream truncated")
            retry_kind = "stream"
            if rid is not None:
                self.tracer.mark(rid, "stream_truncated",
                                 replica=r_used.url, frames=state.sent)
        if rid is not None:
            self.tracer.on_finish(rid, "shed")
        if state.started:
            return      # partial stream, nothing left to resume from
        if last_shed is not None:       # every replica shed: relay it
            try:
                h.send_response(503)
                h.send_header("Content-Type", "application/json")
                h.send_header("Content-Length", str(len(last_shed)))
                h.end_headers()
                h.wfile.write(last_shed)
            except (BrokenPipeError, ConnectionResetError):
                pass
            return
        self._shed(h, "no_replica")

    @staticmethod
    def _relay(h: BaseHTTPRequestHandler, resp) -> None:
        """Copy status + content-type + body bytes to the client,
        unbuffered per read so tokens stream as they arrive. A client
        write failure closes the replica socket (via the caller's
        conn.close()), which cancels the request engine-side. The
        non-SSE path (errors, future non-stream responses); SSE goes
        through _relay_sse for failover-with-resume."""
        try:
            h.send_response(resp.status)
            ctype = resp.getheader("Content-Type", "application/octet-stream")
            h.send_header("Content-Type", ctype)
            h.end_headers()
            while True:
                chunk = resp.read1(8192) if hasattr(resp, "read1") \
                    else resp.read(8192)
                if not chunk:
                    break
                h.wfile.write(chunk)
                h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


def main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.serve.router --replica URL --replica URL`
    (or no --replica at all: replicas join via POST /register)"""
    import argparse

    p = argparse.ArgumentParser(description="ptpu serve router")
    p.add_argument("--replica", action="append", default=[],
                   help="replica base url (repeatable; optional — "
                        "replicas can also POST /register themselves)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--prefix-len", type=int, default=32)
    p.add_argument("--scrape-interval-s", type=float, default=0.5)
    p.add_argument("--scrape-timeout-s", type=float, default=2.0,
                   help="per-replica scrape socket timeout: a wedged "
                        "replica delays only itself, never the loop")
    p.add_argument("--drain-deadline-s", type=float, default=30.0)
    p.add_argument("--no-prefix-directory", action="store_true",
                   help="route on hash stickiness only; ignore the "
                        "scraped /kvprefixes fleet directory")
    p.add_argument("--breaker-fails", type=int, default=3,
                   help="consecutive scrape/connect failures that open "
                        "a replica's circuit breaker (evict)")
    p.add_argument("--breaker-open-s", type=float, default=2.0,
                   help="how long an open breaker waits before its "
                        "half-open probe")
    p.add_argument("--retry-budget-ratio", type=float, default=0.2,
                   help="retry tokens deposited per successful request")
    p.add_argument("--retry-budget-burst", type=float, default=16.0,
                   help="retry-budget bucket size (cold-start allowance)")
    p.add_argument("--no-hedge", action="store_true",
                   help="disable hedged requests")
    p.add_argument("--hedge-ttft-mult", type=float, default=3.0,
                   help="hedge after this multiple of the scraped "
                        "TTFT p95")
    p.add_argument("--hedge-min-s", type=float, default=0.05)
    p.add_argument("--hedge-max-s", type=float, default=2.0)
    p.add_argument("--kv-transfer", action="store_true",
                   help="attach KV transfer hints on directory hits "
                        "instead of re-routing (disaggregated serving)")
    p.add_argument("--phase-prefill-ratio", type=float, default=2.0,
                   help="prompt len >= ratio * max_new_tokens routes "
                        "to prefill-phase replicas when any exist")
    a = p.parse_args(argv)
    router = Router(a.replica, host=a.host, port=a.port,
                    prefix_len=a.prefix_len,
                    scrape_interval_s=a.scrape_interval_s,
                    scrape_timeout_s=a.scrape_timeout_s,
                    drain_deadline_s=a.drain_deadline_s,
                    enable_directory=not a.no_prefix_directory,
                    breaker_fails=a.breaker_fails,
                    breaker_open_s=a.breaker_open_s,
                    retry_budget_ratio=a.retry_budget_ratio,
                    retry_budget_burst=a.retry_budget_burst,
                    enable_hedge=not a.no_hedge,
                    hedge_ttft_mult=a.hedge_ttft_mult,
                    hedge_min_s=a.hedge_min_s,
                    hedge_max_s=a.hedge_max_s,
                    kv_transfer=a.kv_transfer,
                    phase_prefill_ratio=a.phase_prefill_ratio)
    router.start().install_signals()
    code = router.wait()
    router.stop()
    return code if code is not None else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())

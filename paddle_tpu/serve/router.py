"""Router: one front door over N serve replicas.

Scale-out story (ROADMAP "serve millions"): each replica is a
ServeFrontend process with its own engine, KV pool and telemetry; the
router is a thin streaming proxy that decides WHICH replica sees a
request and otherwise copies bytes. Three decisions, all driven by the
replicas' own scraped telemetry — the router holds no model state:

- STICKY PREFIX ROUTING. Prefix caching only pays when requests that
  share a prompt prefix land on the SAME replica (each engine's block
  pool is private). The primary replica is a stable hash — crc32, not
  Python's per-process-salted `hash()` — of the first `prefix_len`
  prompt tokens, modulo N: every request with the same system prompt
  hashes to the same replica, so the fleet-wide hit rate tracks the
  single-replica hit rate instead of decaying ~1/N (serve_bench's
  router scenario measures exactly this).
- FLEET PREFIX DIRECTORY. The hash is a degenerate directory (it
  predicts where a prefix SHOULD be warm); the real one is scraped:
  each replica advertises its warm prefixes on /kvprefixes as
  (length, crc32 digest, tier) rows — "device" for prefix-index
  blocks still in the pool, "host" for blocks demoted to the RAM tier
  (engine/kvtier.py). plan_route checks the incoming prompt against
  the directory and prefers the READY replica holding the LONGEST
  matching prefix at the HOTTEST tier (device beats host beats
  nothing), falling back to the hash primary when no replica has it.
  After a restart, rebalance, or failover the directory finds warm KV
  wherever it actually lives instead of where the hash says it should.
  A digest collision can only misroute (the receiving replica
  re-matches on exact tokens before reusing anything) — a perf risk,
  never a correctness one.
- TELEMETRY-RANKED FALLBACK. When the primary is not routable (failed
  /readyz: cold or draining; scrape failure; or it sheds 503), the
  request falls back to the remaining ready replicas ranked by their
  scraped `ptpu_kv_hit_rate` (desc — a warm cache serves a prefix
  cheapest) then `ptpu_sched_queue_depth` (asc — shortest line). The
  scrape loop refreshes each replica's gauges every
  `scrape_interval_s` on a daemon thread.
- DRAIN, SAME CONTRACT AS REPLICAS. SIGTERM stops admission (503
  reason="draining"), lets in-flight proxied streams finish to a
  bounded deadline, and exits PREEMPT_EXIT_CODE (75) — a router is as
  preemptible as the replicas behind it.

The proxy relays the replica's SSE byte stream unbuffered, so the
`[DONE]` untruncated-stream invariant survives the extra hop, and a
client disconnect propagates: the router's write fails, it drops the
replica connection, the replica's write fails, the engine cancels and
frees KV blocks.

FLEET OBSERVABILITY (OBSERVABILITY.md §fleet). The router is also the
fleet's one observability front door:

- every proxied request gets a TRACE ID (minted here, or the client's
  own `x-ptpu-trace` passed through) injected on the replica hop; the
  router records its own route/relay spans under the same id, and
  `GET /trace/<id>` fetches each replica's span fragment and stitches
  router + replica rows into ONE Chrome trace with per-process pids —
  TTFT decomposes hop by hop;
- `GET /metrics/fleet` scrapes every replica's exposition and serves
  the federated merge (obs/fleetmetrics.py): counters sum exactly,
  log-bucketed histograms merge bucket-by-bucket (identical layout by
  construction), gauges re-label per replica;
- `GET /debug` is the replica table as the router sees it — ready
  state, scraped gauges, prefix-directory size, and scrape staleness
  (also exported as `ptpu_router_scrape_age_seconds{replica}`, so
  routing-on-stale-data is visible on the scrape plane too).
"""

from __future__ import annotations

import itertools
import json
import signal
import threading
import time
import uuid
import zlib
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from paddle_tpu.obs.fleetmetrics import federate
from paddle_tpu.obs.http import CONTENT_TYPE, json_route, obs_response
from paddle_tpu.obs.metrics import MetricsRegistry
from paddle_tpu.obs.tracing import RequestTracer, stitch_fragments
from paddle_tpu.resilience.errors import PREEMPT_EXIT_CODE
from paddle_tpu.serve.sse import parse_prometheus_values
from paddle_tpu.utils.log import serve_event


def prefix_shard(prompt: Sequence[int], n: int, prefix_len: int = 32) -> int:
    """Stable shard index for a prompt: crc32 over the first
    `prefix_len` token ids (little-endian u32 each) mod n. Identical
    prefixes -> identical replica, across processes and runs."""
    head = list(prompt[:prefix_len])
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little") for t in head)
    return zlib.crc32(raw) % max(n, 1)


def prefix_digest(tokens: Sequence[int]) -> str:
    """8-hex-digit digest of a token prefix: crc32 over the ids as
    little-endian u32. MUST match engine/kvtier.py's prefix_digest
    (the replica side of the /kvprefixes advertisement) — duplicated
    here so a standalone router never imports the engine stack;
    tests/test_kvtier.py pins the two functions equal."""
    raw = b"".join(int(t & 0xFFFFFFFF).to_bytes(4, "little")
                   for t in tokens)
    return format(zlib.crc32(raw), "08x")


# directory tier ranking: a device-resident prefix serves with zero
# copies, a host-tier one needs a DMA revival, anything else re-prefills
_TIER_RANK = {"device": 1, "host": 0}


class ReplicaState:
    """What the scrape loop knows about one replica right now."""

    __slots__ = ("url", "host", "port", "ready", "reason", "hit_rate",
                 "queue_depth", "last_scrape", "prefixes")

    def __init__(self, url: str):
        parts = urlsplit(url)
        self.url = url.rstrip("/")
        self.host = parts.hostname
        self.port = parts.port or 80
        self.ready = False
        self.reason = "never scraped"
        self.hit_rate = 0.0
        self.queue_depth = 0.0
        self.last_scrape = 0.0
        # fleet prefix directory rows: {(len, digest): tier}
        self.prefixes: Dict[Tuple[int, str], str] = {}


class Router:
    """`Router(["http://h:p1", "http://h:p2"]).start()` binds `.port`
    and proxies `/v1/completions`; `/metrics`, `/healthz`, `/readyz`
    describe the router itself (ready iff >=1 replica is ready)."""

    def __init__(self, replica_urls: Sequence[str],
                 host: str = "127.0.0.1", port: int = 0,
                 prefix_len: int = 32,
                 scrape_interval_s: float = 0.5,
                 drain_deadline_s: float = 30.0,
                 connect_timeout_s: float = 10.0,
                 enable_directory: bool = True):
        if not replica_urls:
            raise ValueError("router needs at least one replica url")
        self.replicas = [ReplicaState(u) for u in replica_urls]
        self.host = host
        self.port = port
        self.prefix_len = prefix_len
        # False reverts routing to pure hash stickiness (A/B baseline)
        self.enable_directory = enable_directory
        self.scrape_interval_s = scrape_interval_s
        self.drain_deadline_s = drain_deadline_s
        self.connect_timeout_s = connect_timeout_s
        self.exit_code: Optional[int] = None

        self.obs = MetricsRegistry()    # the router's OWN process story
        self._m_routed = self.obs.counter(
            "ptpu_router_requests_total",
            "Requests proxied, by replica and route kind",
            labelnames=("replica", "kind"))  # kind=primary|directory|fallback
        self._m_sheds = self.obs.counter(
            "ptpu_router_sheds_total",
            "Requests the router itself bounced (503)",
            labelnames=("reason",))     # reason=draining|no_replica
        self._m_replica_ready = self.obs.gauge(
            "ptpu_router_replica_ready", "1 when the replica passes /readyz",
            labelnames=("replica",))
        self._m_replica_hit = self.obs.gauge(
            "ptpu_router_replica_hit_rate",
            "Replica's scraped ptpu_kv_hit_rate", labelnames=("replica",))
        self._m_replica_depth = self.obs.gauge(
            "ptpu_router_replica_queue_depth",
            "Replica's scraped ptpu_sched_queue_depth",
            labelnames=("replica",))
        self._m_inflight = self.obs.gauge(
            "ptpu_router_inflight", "Streams currently being proxied")
        self._m_draining = self.obs.gauge(
            "ptpu_router_draining", "1 while the router drains")
        self._m_dir_hits = self.obs.counter(
            "ptpu_router_directory_hits_total",
            "Requests routed to a replica the prefix directory "
            "identified as holding a warm matching prefix")
        self._m_replica_prefixes = self.obs.gauge(
            "ptpu_router_replica_prefixes",
            "Warm prefixes the replica advertises on /kvprefixes",
            labelnames=("replica",))
        self._m_scrape_age = self.obs.gauge(
            "ptpu_router_scrape_age_seconds",
            "Seconds since the replica's gauges were last scraped "
            "successfully (-1 = never); routing decisions are only as "
            "fresh as this", labelnames=("replica",))

        # router-side spans under the fleet trace id: one synthetic
        # request id per proxied POST, stitched with the replica's
        # engine spans by /trace/<id>
        self.tracer = RequestTracer(keep_last=512, process_name="router")
        self._trace_seq = itertools.count(1)

        self._server: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._scrape_thread: Optional[threading.Thread] = None
        self._stop_scrape = threading.Event()
        # One lock covers the router's mutable shared state: the in-flight
        # count AND every ReplicaState field the scrape loop and handler
        # threads both touch. Network I/O never happens under it.
        self._lock = threading.Lock()
        self._inflight = 0          # guarded-by: self._lock
        self._draining = False      # guarded-by: self._lock
        self._drained = threading.Event()

    # -- scrape loop ------------------------------------------------------
    def _scrape_once(self, r: ReplicaState) -> None:
        # HTTP happens into locals; ReplicaState fields are published in
        # one locked write so handler threads (plan_route, _proxy's
        # connect-failure demotion) never see a half-updated replica.
        ready = False
        reason = ""
        vals = {}
        prefixes: Dict[Tuple[int, str], str] = {}
        try:
            conn = HTTPConnection(r.host, r.port,
                                  timeout=self.connect_timeout_s)
            try:
                conn.request("GET", "/readyz")
                resp = conn.getresponse()
                body = resp.read().decode("utf-8", "replace").strip()
                ready = resp.status == 200
                reason = "" if ready else body
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                text = resp.read().decode("utf-8", "replace")
                # fleet prefix directory: tolerate replicas without the
                # endpoint (404 / bad JSON -> empty advertisement, the
                # scrape itself still counts as healthy)
                conn.request("GET", "/kvprefixes")
                presp = conn.getresponse()
                pbody = presp.read()
                if presp.status == 200:
                    try:
                        for row in json.loads(pbody).get("prefixes", []):
                            prefixes[(int(row["len"]),
                                      str(row["digest"]))] = \
                                str(row.get("tier", "device"))
                    except (ValueError, KeyError, TypeError):
                        prefixes = {}
            finally:
                conn.close()
            vals = parse_prometheus_values(text)
        except OSError as e:
            ready = False
            reason = f"scrape failed: {e}"
        with self._lock:
            r.ready = ready
            r.reason = reason
            r.prefixes = prefixes
            if vals:
                r.hit_rate = vals.get("ptpu_kv_hit_rate", 0.0)
                r.queue_depth = vals.get("ptpu_sched_queue_depth", 0.0)
                r.last_scrape = time.monotonic()
            hit_rate, queue_depth = r.hit_rate, r.queue_depth
            last_scrape = r.last_scrape
        self._m_replica_ready.labels(replica=r.url).set(1.0 if ready else 0.0)
        self._m_replica_hit.labels(replica=r.url).set(hit_rate)
        self._m_replica_depth.labels(replica=r.url).set(queue_depth)
        self._m_replica_prefixes.labels(replica=r.url).set(
            float(len(prefixes)))
        # staleness: keeps GROWING while scrapes fail, so alerting can
        # tell "replica down" from "replica briefly slow"
        age = (time.monotonic() - last_scrape) if last_scrape else -1.0
        self._m_scrape_age.labels(replica=r.url).set(age)

    def scrape_now(self) -> None:
        """One synchronous pass over every replica (startup, tests)."""
        for r in self.replicas:
            self._scrape_once(r)

    def _scrape_loop(self) -> None:
        while not self._stop_scrape.wait(self.scrape_interval_s):
            self.scrape_now()

    # -- routing policy ---------------------------------------------------
    def _directory_best(self, prompt: Sequence[int],
                        snapshot: dict) -> Optional[ReplicaState]:
        """The ready replica advertising the LONGEST prefix of `prompt`
        at the HOTTEST tier, or None when the fleet directory has no
        match. Digests are memoized per length: one crc32 per distinct
        advertised prefix length, not per (replica, row)."""
        best: Optional[ReplicaState] = None
        best_score = (-1, -1)
        memo: Dict[int, str] = {}
        for r in self.replicas:
            ready, _, _, prefixes = snapshot[r]
            if not ready:
                continue
            for (ln, dg), tier in prefixes.items():
                score = (ln, _TIER_RANK.get(tier, -1))
                if ln > len(prompt) or score <= best_score:
                    continue
                if ln not in memo:
                    memo[ln] = prefix_digest(prompt[:ln])
                if memo[ln] == dg:
                    best, best_score = r, score
        return best

    def _plan(self, prompt: Sequence[int]
              ) -> Tuple[List[ReplicaState], Optional[ReplicaState]]:
        """(candidates in try-order, directory pick or None). Base
        order: the sticky prefix-hash primary first (even when it looks
        not-ready the scrape may be stale — a 503 there falls through),
        then every OTHER ready replica ranked best-fallback-first:
        highest scraped hit rate, then shortest queue. When the fleet
        prefix directory knows a ready replica holding a warm prefix of
        this prompt, that replica is promoted to the front — warm KV
        beats where the hash says the prefix should live."""
        primary = self.replicas[prefix_shard(prompt, len(self.replicas),
                                             self.prefix_len)]
        with self._lock:    # one consistent snapshot to rank against
            stats = {r: (r.ready, r.hit_rate, r.queue_depth,
                         dict(r.prefixes))
                     for r in self.replicas}
        dir_pick = (self._directory_best(prompt, stats)
                    if self.enable_directory else None)
        fallbacks = sorted(
            (r for r in self.replicas if r is not primary and stats[r][0]),
            key=lambda r: (-stats[r][1], stats[r][2]))
        if stats[primary][0]:
            order = [primary] + fallbacks
        else:
            order = fallbacks + [primary]   # last-ditch: maybe stale scrape
        if dir_pick is not None and dir_pick is not order[0]:
            order.remove(dir_pick)
            order.insert(0, dir_pick)
        return order, dir_pick

    def plan_route(self, prompt: Sequence[int]) -> List[ReplicaState]:
        """Candidate replicas in try-order (see _plan)."""
        return self._plan(prompt)[0]

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Router":
        if self._server is not None:
            return self
        self.scrape_now()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, daemon=True, name="ptpu-router-scrape")
        self._scrape_thread.start()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):                       # noqa: N802
                outer._handle_get(self)

            def do_POST(self):                      # noqa: N802
                outer._handle_post(self)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ptpu-router-http")
        self._serve_thread.start()
        serve_event("router_listening", host=self.host, port=self.port,
                    replicas=[r.url for r in self.replicas])
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def install_signals(self) -> "Router":
        def _on_signal(signum, frame):
            serve_event("router_sigterm", signal=int(signum))
            threading.Thread(target=self.begin_drain, daemon=True).start()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
        return self

    def begin_drain(self) -> None:
        """Stop admitting; wait for in-flight proxied streams to finish
        (bounded by drain_deadline_s); record exit code 75."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._m_draining.set(1.0)
        deadline = time.monotonic() + self.drain_deadline_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.05)
        self.exit_code = PREEMPT_EXIT_CODE
        serve_event("router_drained", exit_code=self.exit_code,
                    inflight_at_exit=self._inflight)
        self._drained.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        self._drained.wait(timeout)
        return self.exit_code

    def stop(self) -> None:
        self._stop_scrape.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
            self._serve_thread = None
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None

    # -- HTTP -------------------------------------------------------------
    def readiness(self) -> Tuple[bool, str]:
        with self._lock:
            if self._draining:
                return False, "draining"
            if any(r.ready for r in self.replicas):
                return True, ""
        return False, "no ready replicas"

    def _fetch(self, r: ReplicaState, path: str) -> Optional[str]:
        """GET `path` from a replica, body text on 200 else None. Runs
        on handler threads with NO router lock held (network under the
        lock is forbidden — see self._lock's comment)."""
        try:
            conn = HTTPConnection(r.host, r.port,
                                  timeout=self.connect_timeout_s)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    return None
                return body.decode("utf-8", "replace")
            finally:
                conn.close()
        except OSError:
            return None

    def _fleet_route(self):
        """/metrics/fleet: scrape every replica NOW and serve the
        federated exposition. Unreachable replicas are simply absent
        from the merge (their staleness still shows on the router's
        own ptpu_router_scrape_age_seconds)."""
        expositions: Dict[str, str] = {}
        for r in self.replicas:
            text = self._fetch(r, "/metrics")
            if text is not None:
                expositions[r.url] = text
        return 200, CONTENT_TYPE, federate(expositions).encode()

    def _trace_route(self, path: str):
        """/trace/<id>: merge the router's own span fragment for the
        trace id with every replica's into one Chrome trace — each
        process gets its own pid row, timestamps are epoch-anchored
        (now_us) so no shifting is needed."""
        tid = path[len("/trace/"):].strip("/")
        fragments: List[Tuple[str, dict]] = []
        own = self.tracer.trace_fragment(tid) if tid else None
        if own is not None:
            fragments.append(("router", own))
        for r in self.replicas:
            text = self._fetch(r, "/trace/" + tid) if tid else None
            if text is None:
                continue
            try:
                frag = json.loads(text)
            except ValueError:
                continue
            fragments.append((f"replica {r.url}", frag))
        if not fragments:
            return (404, "application/json",
                    json.dumps({"error": "unknown trace",
                                "trace_id": tid}).encode() + b"\n")
        merged = stitch_fragments(fragments, trace_id=tid)
        return (200, "application/json",
                json.dumps(merged).encode() + b"\n")

    def _debug_payload(self) -> dict:
        """/debug: the replica table as routing sees it right now."""
        now = time.monotonic()
        with self._lock:
            replicas = [{
                "url": r.url,
                "ready": r.ready,
                "reason": r.reason,
                "hit_rate": r.hit_rate,
                "queue_depth": r.queue_depth,
                "scrape_age_s": (round(now - r.last_scrape, 3)
                                 if r.last_scrape else None),
                "prefixes": len(r.prefixes),
            } for r in self.replicas]
            inflight = self._inflight
            draining = self._draining
        return {"replicas": replicas, "inflight": inflight,
                "draining": draining,
                "scrape_interval_s": self.scrape_interval_s,
                "directory_enabled": self.enable_directory}

    def _handle_get(self, h: BaseHTTPRequestHandler) -> None:
        resp = obs_response(
            h.path, self.obs, readiness=self.readiness,
            routes={"/metrics/fleet": self._fleet_route,
                    "/debug": json_route(self._debug_payload)},
            prefix_routes={"/trace/": self._trace_route})
        if resp is None:
            resp = (404, "text/plain", b"not found\n")
        status, ctype, body = resp
        try:
            h.send_response(status)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _shed(self, h: BaseHTTPRequestHandler, reason: str) -> None:
        self._m_sheds.labels(reason=reason).inc()
        body = json.dumps({"error": "overloaded", "reason": reason,
                           "retry_after_s": 1.0}).encode() + b"\n"
        try:
            h.send_response(503)
            h.send_header("Content-Type", "application/json")
            h.send_header("Content-Length", str(len(body)))
            h.send_header("Retry-After", "1")
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _handle_post(self, h: BaseHTTPRequestHandler) -> None:
        if h.path.split("?")[0] != "/v1/completions":
            self._handle_get(h)         # reuse the 404 path
            return
        if self._draining:
            self._shed(h, "draining")
            return
        try:
            length = int(h.headers.get("Content-Length", "0"))
            raw = h.rfile.read(length)
            prompt = json.loads(raw or b"{}").get("prompt") or []
        except (ValueError, json.JSONDecodeError):
            raw, prompt = b"{}", []
        # fleet trace id: honor the client's, else mint one; the same
        # id tags the router's route/relay spans AND rides the replica
        # hop as x-ptpu-trace, so /trace/<id> can stitch both processes
        tid = h.headers.get("x-ptpu-trace") or uuid.uuid4().hex[:16]
        rid = next(self._trace_seq)
        self.tracer.set_trace_id(rid, tid)
        self.tracer.span_begin(rid, "route")
        candidates, dir_pick = self._plan(prompt)
        if not candidates:
            self.tracer.on_finish(rid, "shed")
            self._shed(h, "no_replica")
            return
        self._track_inflight(+1)
        try:
            self._proxy(h, raw, prompt, candidates, dir_pick,
                        tid=tid, rid=rid)
        finally:
            self._track_inflight(-1)

    def _track_inflight(self, delta: int) -> None:
        """Count and gauge move together under the lock: the old code
        re-read `self._inflight` outside it, so two crossing requests
        could publish stale values out of order and leave the gauge
        permanently off. The gauge's own child lock is leaf-level (it
        never takes router locks), so nesting it here cannot deadlock."""
        with self._lock:
            self._inflight += delta
            self._m_inflight.set(float(self._inflight))

    def _proxy(self, h: BaseHTTPRequestHandler, raw: bytes,
               prompt: Sequence[int],
               candidates: List[ReplicaState],
               dir_pick: Optional[ReplicaState] = None, *,
               tid: Optional[str] = None,
               rid: Optional[int] = None) -> None:
        """Try candidates in order; a refused connection or a 503 shed
        moves to the next. The first streamable response is relayed
        byte-for-byte (SSE frames pass through untouched). The served
        replica's route kind: "primary" when it is the hash-sticky
        pick (the directory agreeing with the hash stays "primary" so
        stickiness verdicts survive), "directory" when the fleet
        prefix directory OVERRODE the hash, "fallback" otherwise."""
        sticky = self.replicas[prefix_shard(prompt, len(self.replicas),
                                            self.prefix_len)]
        headers = {"Content-Type": "application/json"}
        if tid:
            headers["x-ptpu-trace"] = tid
        last_resp: Optional[Tuple[int, bytes]] = None
        for r in candidates:
            try:
                conn = HTTPConnection(r.host, r.port,
                                      timeout=self.connect_timeout_s)
                conn.request(
                    "POST", "/v1/completions", body=raw, headers=headers)
                resp = conn.getresponse()
            except OSError:
                with self._lock:
                    r.ready = False
                    r.reason = "connect failed"
                if rid is not None:
                    self.tracer.mark(rid, "connect_failed", replica=r.url)
                continue
            if resp.status == 503:      # replica shed: try the next
                last_resp = (503, resp.read())
                conn.close()
                if rid is not None:
                    self.tracer.mark(rid, "replica_shed", replica=r.url)
                continue
            if r is sticky:
                kind = "primary"
            elif dir_pick is not None and r is dir_pick:
                kind = "directory"
            else:
                kind = "fallback"
            if dir_pick is not None and r is dir_pick:
                self._m_dir_hits.inc()
            self._m_routed.labels(replica=r.url, kind=kind).inc()
            if rid is not None:
                self.tracer.mark(rid, "routed", replica=r.url, kind=kind)
                self.tracer.span_begin(rid, "relay")
            self._relay(h, resp)
            conn.close()
            if rid is not None:
                self.tracer.on_finish(rid, "relayed")
            return
        if rid is not None:
            self.tracer.on_finish(rid, "shed")
        if last_resp is not None:       # every replica shed: relay it
            status, body = last_resp
            try:
                h.send_response(status)
                h.send_header("Content-Type", "application/json")
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass
            return
        self._shed(h, "no_replica")

    @staticmethod
    def _relay(h: BaseHTTPRequestHandler, resp) -> None:
        """Copy status + content-type + body bytes to the client,
        unbuffered per read so tokens stream as they arrive. A client
        write failure closes the replica socket (via the caller's
        conn.close()), which cancels the request engine-side."""
        try:
            h.send_response(resp.status)
            ctype = resp.getheader("Content-Type", "application/octet-stream")
            h.send_header("Content-Type", ctype)
            h.end_headers()
            while True:
                chunk = resp.read1(8192) if hasattr(resp, "read1") \
                    else resp.read(8192)
                if not chunk:
                    break
                h.wfile.write(chunk)
                h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


def main(argv: Optional[List[str]] = None) -> int:
    """`python -m paddle_tpu.serve.router --replica URL --replica URL`"""
    import argparse

    p = argparse.ArgumentParser(description="ptpu serve router")
    p.add_argument("--replica", action="append", required=True,
                   help="replica base url (repeatable)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--prefix-len", type=int, default=32)
    p.add_argument("--scrape-interval-s", type=float, default=0.5)
    p.add_argument("--drain-deadline-s", type=float, default=30.0)
    p.add_argument("--no-prefix-directory", action="store_true",
                   help="route on hash stickiness only; ignore the "
                        "scraped /kvprefixes fleet directory")
    a = p.parse_args(argv)
    router = Router(a.replica, host=a.host, port=a.port,
                    prefix_len=a.prefix_len,
                    scrape_interval_s=a.scrape_interval_s,
                    drain_deadline_s=a.drain_deadline_s,
                    enable_directory=not a.no_prefix_directory)
    router.start().install_signals()
    code = router.wait()
    router.stop()
    return code if code is not None else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
